"""Thread-based dynamic batcher: the serving front door.

Requests enqueue from any thread; one worker drains them into per-bucket
batches under a `max_batch_size` / `max_wait_ms` policy (ParaFold-style:
throughput comes from scheduling, not the model). Three QoS behaviors:

- deadline shedding: a request whose deadline expires while queued is
  resolved `status="shed"` without touching the accelerator — folding
  dead work is the most expensive way to miss a deadline;
- bounded-queue backpressure: `queue_limit` caps in-flight requests;
  `full_policy="reject"` raises QueueFullError at submit (shed at the
  door), `"block"` makes submit wait for capacity;
- priority: when a backlog exceeds one batch, higher-priority requests
  fold first (FIFO within a priority level).

Batches are always padded to `max_batch_size` (bucketing.assemble), so
the compiled-shape set is closed: one executable per (bucket,
num_recycles), never one per observed batch size. The scheduler/executor
seam is deliberate — a later multi-chip server replaces FoldExecutor
with a `parallel.mesh`-sharded one and this file does not change.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from alphafold2_tpu.serve.bucketing import BucketPolicy
from alphafold2_tpu.serve.executor import FoldExecutor
from alphafold2_tpu.serve.metrics import ServeMetrics
from alphafold2_tpu.serve.request import (FoldRequest, FoldResponse,
                                          FoldTicket)


class QueueFullError(RuntimeError):
    """submit() refused: queue at queue_limit and full_policy='reject'."""


@dataclass
class SchedulerConfig:
    max_batch_size: int = 4
    max_wait_ms: float = 50.0      # oldest request age that forces a batch
    queue_limit: int = 256         # in-flight cap (queued, not yet folded)
    num_recycles: int = 1
    full_policy: str = "reject"    # "reject" | "block"
    poll_ms: float = 5.0           # worker wakeup granularity
    # Serving MSA depth. None = per-batch max over members — ONLY safe
    # when every request carries the same depth; ragged-depth traffic
    # then mints one compiled shape per observed depth and defeats the
    # closed-shape guarantee. Pin it (bucketing.assemble semantics:
    # pad shallow, keep the first msa_depth rows of deeper MSAs) for
    # production traffic; 0 serves MSA-free.
    msa_depth: Optional[int] = None

    def __post_init__(self):
        if self.full_policy not in ("reject", "block"):
            raise ValueError(f"full_policy must be 'reject' or 'block', "
                             f"got {self.full_policy!r}")
        if self.max_batch_size < 1 or self.queue_limit < 1:
            raise ValueError("max_batch_size and queue_limit must be >= 1")


class _Entry:
    __slots__ = ("request", "ticket", "bucket_len", "enqueued_at",
                 "deadline")

    def __init__(self, request: FoldRequest, bucket_len: int):
        self.request = request
        self.ticket = FoldTicket(request.request_id)
        self.bucket_len = bucket_len
        self.enqueued_at = time.monotonic()
        self.deadline = (None if request.deadline_s is None
                         else self.enqueued_at + request.deadline_s)


class Scheduler:
    """Dynamic batching fold server over one FoldExecutor."""

    def __init__(self, executor: FoldExecutor, buckets: BucketPolicy,
                 config: Optional[SchedulerConfig] = None,
                 metrics: Optional[ServeMetrics] = None):
        self.executor = executor
        self.buckets = buckets
        self.config = config or SchedulerConfig()
        self.metrics = metrics or ServeMetrics()
        self._cond = threading.Condition()
        self._incoming: deque = deque()
        self._pending: Dict[int, List[_Entry]] = {}
        self._depth = 0            # incoming + pending, guarded by _cond
        self._running = False
        self._drain = True
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Scheduler":
        with self._cond:
            if self._running:
                return self
            self._running = True
            self._drain = True
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="serve-scheduler")
        self._worker.start()
        return self

    def stop(self, drain: bool = True):
        """Stop the worker. drain=True folds everything already queued
        (expired deadlines still shed); drain=False resolves queued
        requests as status='cancelled'."""
        with self._cond:
            self._running = False
            self._drain = drain
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join()
            self._worker = None

    def __enter__(self) -> "Scheduler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def warmup(self, msa_depth: Optional[int] = None) -> int:
        """Precompile every bucket at the serving signature so the first
        real request pays queueing, not XLA. Returns fresh compiles.
        Defaults to the config's pinned msa_depth; the guarantee only
        holds when serving shapes are pinned to match (config.msa_depth,
        or uniform-depth traffic equal to this depth)."""
        if msa_depth is None:
            msa_depth = self.config.msa_depth or 0
        keys = [(edge, self.config.max_batch_size, msa_depth,
                 self.config.num_recycles) for edge in self.buckets.edges]
        return self.executor.warmup(keys)

    # -- submission ------------------------------------------------------

    def submit(self, request: FoldRequest) -> FoldTicket:
        bucket_len = self.buckets.bucket_for(request.length)  # fail fast
        with self._cond:
            if not self._running:
                raise RuntimeError("Scheduler.submit() before start()")
            while self._depth >= self.config.queue_limit:
                if self.config.full_policy == "reject":
                    self.metrics.record_rejected()
                    raise QueueFullError(
                        f"queue at limit {self.config.queue_limit}")
                self._cond.wait()
                if not self._running:
                    raise RuntimeError("Scheduler stopped while blocked "
                                       "on a full queue")
            entry = _Entry(request, bucket_len)
            self._incoming.append(entry)
            self._depth += 1
            depth = self._depth
            self._cond.notify_all()
        self.metrics.record_enqueued(depth)
        return entry.ticket

    def serve_stats(self) -> dict:
        """Health-check snapshot: serving counters + executor cache."""
        stats = self.metrics.snapshot()
        stats["executor"] = self.executor.stats()
        stats["bucket_edges"] = list(self.buckets.edges)
        with self._cond:
            stats["running"] = self._running
        return stats

    # -- worker ----------------------------------------------------------

    def _run(self):
        try:
            self._run_inner()
        except Exception as exc:   # worker must never die silently:
            self._fail_outstanding(repr(exc))
            return
        if not self._drain:
            self._cancel_remaining()

    def _run_inner(self):
        poll_s = self.config.poll_ms / 1000.0
        just_executed = False   # a ready batch may already be waiting
        while True:
            with self._cond:
                if not just_executed and not self._incoming \
                        and self._running:
                    # timed wait only while entries pend (max_wait_ms /
                    # deadline bookkeeping needs the clock); a fully
                    # idle scheduler parks until submit()/stop() notify
                    if any(self._pending.values()):
                        self._cond.wait(timeout=poll_s)
                    else:
                        self._cond.wait()
                while self._incoming:
                    entry = self._incoming.popleft()
                    self._pending.setdefault(entry.bucket_len,
                                             []).append(entry)
                stopping = not self._running
                drain = self._drain
            if stopping and not drain:
                break
            self._shed_expired()
            batch = self._form_batch(stopping)
            just_executed = batch is not None
            if batch is not None:
                self._execute(*batch)
                continue
            if stopping:
                with self._cond:
                    if self._incoming or any(self._pending.values()):
                        continue
                break

    def _resolve_removed(self, entries: List[_Entry]):
        """Entries left the queue: update depth, wake blocked submitters."""
        if not entries:
            return
        with self._cond:
            self._depth -= len(entries)
            self._cond.notify_all()

    def _shed_expired(self):
        now = time.monotonic()
        shed: List[_Entry] = []
        for bucket_len, entries in self._pending.items():
            keep = []
            for e in entries:
                if e.deadline is not None and now > e.deadline:
                    shed.append(e)
                else:
                    keep.append(e)
            self._pending[bucket_len] = keep
        self._resolve_removed(shed)
        for e in shed:
            self.metrics.record_shed()
            e.ticket._resolve(FoldResponse(
                request_id=e.request.request_id, status="shed",
                bucket_len=e.bucket_len,
                latency_s=now - e.enqueued_at,
                error="deadline expired before folding"))

    def _form_batch(self, stopping: bool):
        """Pick the bucket whose oldest entry has waited longest, if any
        bucket is ready (full batch, max_wait exceeded, or draining)."""
        cfg = self.config
        now = time.monotonic()
        best = None
        for bucket_len, entries in self._pending.items():
            if not entries:
                continue
            oldest = min(e.enqueued_at for e in entries)
            ready = (len(entries) >= cfg.max_batch_size
                     or (now - oldest) * 1000.0 >= cfg.max_wait_ms
                     or stopping)
            if ready and (best is None or oldest < best[1]):
                best = (bucket_len, oldest)
        if best is None:
            return None
        bucket_len = best[0]
        entries = self._pending[bucket_len]
        # higher priority folds first; FIFO within a priority level
        entries.sort(key=lambda e: (-e.request.priority, e.enqueued_at))
        take = entries[:cfg.max_batch_size]
        self._pending[bucket_len] = entries[cfg.max_batch_size:]
        self._resolve_removed(take)
        return bucket_len, take

    def _execute(self, bucket_len: int, entries: List[_Entry]):
        cfg = self.config
        t0 = time.monotonic()
        # the whole assemble -> run -> device-fetch window is guarded:
        # entries already left the queue, so an unresolved exception here
        # would orphan their tickets forever (resolve as error instead)
        try:
            batch, waste = self.buckets.assemble(
                [e.request for e in entries], bucket_len,
                cfg.max_batch_size, msa_depth=cfg.msa_depth)
            result = self.executor.run(batch, cfg.num_recycles)
            coords = np.asarray(result.coords)
            confidence = np.asarray(result.confidence)
        except Exception as exc:  # resolve, never kill the worker
            self.metrics.record_error(len(entries))
            for e in entries:
                e.ticket._resolve(FoldResponse(
                    request_id=e.request.request_id, status="error",
                    bucket_len=bucket_len, error=repr(exc)))
            return
        now = time.monotonic()
        real_tokens = 0
        for i, e in enumerate(entries):
            n = e.request.length
            real_tokens += n
            latency = now - e.enqueued_at
            self.metrics.record_served(bucket_len, latency)
            e.ticket._resolve(FoldResponse(
                request_id=e.request.request_id, status="ok",
                # copy: a view would pin the whole padded batch in the
                # caller's hands for the lifetime of the response
                coords=coords[i, :n].copy(),
                confidence=confidence[i, :n].copy(),
                bucket_len=bucket_len, latency_s=latency))
        with self._cond:
            depth = self._depth
        self.metrics.record_batch(
            bucket_len, cfg.max_batch_size, len(entries), real_tokens,
            waste, now - t0, depth)

    def _drain_all_entries(self) -> List[_Entry]:
        with self._cond:
            leftovers = list(self._incoming)
            self._incoming.clear()
            for entries in self._pending.values():
                leftovers.extend(entries)
            self._pending.clear()
            self._depth -= len(leftovers)
            self._cond.notify_all()
        return leftovers

    def _cancel_remaining(self):
        leftovers = self._drain_all_entries()
        self.metrics.record_cancelled(len(leftovers))
        for e in leftovers:
            e.ticket._resolve(FoldResponse(
                request_id=e.request.request_id, status="cancelled",
                bucket_len=e.bucket_len))

    def _fail_outstanding(self, error: str):
        """Worker crashed outside executor.run (e.g. the metrics sink):
        stop accepting work and resolve every outstanding ticket as an
        error instead of leaving callers blocked forever."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        leftovers = self._drain_all_entries()
        self.metrics.record_error(len(leftovers))
        for e in leftovers:
            e.ticket._resolve(FoldResponse(
                request_id=e.request.request_id, status="error",
                bucket_len=e.bucket_len,
                error=f"scheduler worker crashed: {error}"))
