"""Serving metrics: counters, queue depth, padding waste, latency tails.

Latency reservoirs are `obs.registry.Histogram` objects (per bucket),
so p50/p90/p99 here come from the same histogram + single
`utils.profiling.percentile` quantile path as every other stat in the
repo — and every recording is mirrored into the process-wide
`MetricsRegistry` (serve_* counters, gauges, and a bucket-labeled
latency histogram) so a Prometheus scrape (obs/export.py) sees this
server next to the cache and the train loop. Reuses
`utils.logging.MetricsLogger` for the JSONL sink (one record per
executed batch — queue depth, padding waste, and the current per-bucket
p50/p90/p99 latency). `snapshot()` is the health-check view: O(1)-ish,
lock-consistent, JSON-serializable.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from alphafold2_tpu.obs.registry import (DEFAULT_LATENCY_BUCKETS, Histogram,
                                         MetricsRegistry, get_registry)
from alphafold2_tpu.utils.logging import MetricsLogger


class ServeMetrics:
    """Thread-safe serving counters + JSONL emission + registry mirror.

    registry: obs.MetricsRegistry to report into (None = the process
        default). Instance counters/latencies answer `snapshot()` for
        THIS server; the registry carries the process-wide cumulative
        view across all servers for exporters.
    """

    def __init__(self, jsonl_path: Optional[str] = None,
                 stdout: bool = False, max_latencies_per_bucket: int = 4096,
                 registry: Optional[MetricsRegistry] = None):
        self._logger = MetricsLogger(jsonl_path, stdout=stdout) \
            if (jsonl_path or stdout) else None
        self._lock = threading.Lock()
        self._max_lat = max_latencies_per_bucket
        self.enqueued = 0
        self.served = 0
        self.shed = 0
        self.errors = 0
        self.cancelled = 0
        self.rejected = 0           # backpressure: submit refused
        # preemption reclaim terminals (ISSUE 20; zero — and absent
        # from snapshot() — unless a reclaim actually happened)
        self.preempted = 0
        # resilience outcomes (all zero without a RetryPolicy)
        self.degraded = 0           # fast-shed while the breaker is open
        self.poisoned = 0           # quarantined poison terminal states
        self.retried = 0            # re-enqueues after transient failures
        # HBM admission guard (zero without a mesh policy)
        self.too_large = 0          # rejected: exceeds largest mesh slice
        self.batches = 0
        self.queue_depth = 0
        # cumulative wall seconds the executor spent inside batch
        # executions (sum of batch latencies). 1 - busy/wall is the
        # executor idle fraction — the number the feature pipeline
        # exists to drive down (ISSUE 10: the accelerator must never
        # idle waiting on features); serve_loadtest reports it
        self.exec_busy_s = 0.0
        # result-cache outcomes at submit (all zero when caching is off)
        self.cache_hits = 0         # served straight from the store
        self.cache_misses = 0       # key looked up, not found
        self.coalesced = 0          # parked behind an in-flight leader
        self._real_tokens = 0
        self._padded_tokens = 0
        # admission-aware occupancy-weighted padding (ISSUE 13): the
        # formation-time accounting above prices the grid ONCE at
        # assemble ("founders only" — PR 11's known gap); these price
        # what each executed recycle step actually carried, so row
        # admissions (and the padding a cross-bucket admit accepts)
        # move the number instead of being invisible
        self._step_real_tokens = 0
        self._step_grid_tokens = 0
        self.row_admits = 0          # rows admitted mid-loop (all kinds)
        # per-bucket latency reservoirs (seconds, request-level) —
        # instance-scoped Histograms answering this server's snapshot()
        self._latencies: Dict[int, Histogram] = {}
        # process-wide mirror every recording also lands in
        reg = registry or get_registry()
        self._m_enqueued = reg.counter(
            "serve_enqueued_total", "requests accepted into the queue")
        self._m_outcomes = reg.counter(
            "serve_requests_total",
            "terminal request outcomes by state", ("outcome",))
        self._m_cache = reg.counter(
            "serve_cache_events_total",
            "submit-side result-cache outcomes", ("event",))
        self._m_batches = reg.counter(
            "serve_batches_total", "executed batches")
        self._m_tokens = reg.counter(
            "serve_tokens_total",
            "token grid accounting per executed batch", ("kind",))
        self._m_queue_depth = reg.gauge(
            "serve_queue_depth", "queued + pending requests")
        self._m_latency = reg.histogram(
            "serve_request_latency_seconds",
            "submit-to-resolve latency of served requests",
            ("bucket_len",), reservoir=max_latencies_per_bucket)
        self._m_admit_pad = reg.histogram(
            "serve_admit_pad_fraction",
            "per-admission pad fraction at the host bucket edge "
            "(1 - length/host_edge) of rows admitted mid-loop",
            buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0))
        # instance-scoped mirror answering this server's snapshot()
        self._admit_pad_hist = Histogram(
            "serve_admit_pad_fraction", "per-admit pad fraction",
            buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0),
            reservoir=max_latencies_per_bucket)

    def _bucket_hist(self, bucket_len: int) -> Histogram:
        """Caller holds self._lock."""
        h = self._latencies.get(bucket_len)
        if h is None:
            h = self._latencies[bucket_len] = Histogram(
                "serve_request_latency_seconds", "per-bucket latency",
                buckets=DEFAULT_LATENCY_BUCKETS, reservoir=self._max_lat)
        return h

    # -- recording -------------------------------------------------------

    def record_enqueued(self, queue_depth: int):
        with self._lock:
            self.enqueued += 1
            self.queue_depth = queue_depth
        self._m_enqueued.inc()
        self._m_queue_depth.set(queue_depth)

    def record_rejected(self):
        with self._lock:
            self.rejected += 1
        self._m_outcomes.inc(outcome="rejected")

    def record_shed(self, n: int = 1):
        with self._lock:
            self.shed += n
        self._m_outcomes.inc(n, outcome="shed")

    def record_error(self, n: int = 1):
        with self._lock:
            self.errors += n
        self._m_outcomes.inc(n, outcome="error")

    def record_cancelled(self, n: int = 1):
        with self._lock:
            self.cancelled += n
        self._m_outcomes.inc(n, outcome="cancelled")

    def record_preempted(self, n: int = 1):
        """Requests resolved "preempted": the replica was reclaimed
        mid-work; checkpoints (where spillable) were handed off for
        adoption and the caller retries elsewhere. The outcome label
        is minted on first use, so a never-preempted server's registry
        stays byte-identical (ISSUE 20)."""
        with self._lock:
            self.preempted += n
        self._m_outcomes.inc(n, outcome="preempted")

    def record_degraded(self, n: int = 1):
        with self._lock:
            self.degraded += n
        self._m_outcomes.inc(n, outcome="degraded")

    def record_poisoned(self, n: int = 1):
        with self._lock:
            self.poisoned += n
        self._m_outcomes.inc(n, outcome="poisoned")

    def record_too_large(self, n: int = 1):
        with self._lock:
            self.too_large += n
        self._m_outcomes.inc(n, outcome="too_large")

    def record_retried(self, n: int = 1):
        """Requests re-enqueued after a transient batch failure (NOT a
        terminal outcome — the same request later lands in served/
        errors/shed as usual)."""
        with self._lock:
            self.retried += n

    def record_admit(self, pad_fraction: float):
        """One row admitted mid-loop (continuous batching): observe
        its pad fraction at the host bucket edge. Cross-bucket admits
        (ISSUE 13) populate the high bins — the distribution IS the
        padding-vs-dead-row trade being taken."""
        pad_fraction = min(max(float(pad_fraction), 0.0), 1.0)
        with self._lock:
            self.row_admits += 1
            self._admit_pad_hist.observe(pad_fraction)
        self._m_admit_pad.observe(pad_fraction)

    def record_step_occupancy(self, real_tokens: int, grid_tokens: int):
        """One executed recycle step's token accounting: live rows'
        real residues vs the full (B, L) grid the step paid for.
        `padding_waste_admitted` in snapshot() is 1 - sum/sum over
        every recorded step — the occupancy-weighted padding fraction
        the continuous/cross-bucket batcher actually served at (the
        formation-time `padding_waste` cannot see admissions)."""
        with self._lock:
            self._step_real_tokens += int(real_tokens)
            self._step_grid_tokens += int(grid_tokens)

    def record_cache_hit(self):
        with self._lock:
            self.cache_hits += 1
        self._m_cache.inc(event="hit")

    def record_cache_miss(self):
        with self._lock:
            self.cache_misses += 1
        self._m_cache.inc(event="miss")

    def record_coalesced(self):
        with self._lock:
            self.coalesced += 1
        self._m_cache.inc(event="coalesced")

    def _cache_view(self) -> dict:
        """Caller holds self._lock."""
        total = self.cache_hits + self.cache_misses
        return {"hits": self.cache_hits, "misses": self.cache_misses,
                "coalesced": self.coalesced,
                "hit_ratio": self.cache_hits / total if total else 0.0}

    def record_served(self, bucket_len: int, latency_s: float):
        with self._lock:
            self.served += 1
            self._bucket_hist(bucket_len).observe(latency_s)
        self._m_outcomes.inc(outcome="served")
        self._m_latency.observe(latency_s, bucket_len=bucket_len)

    def record_batch(self, bucket_len: int, batch_size: int, n_real: int,
                     real_tokens: int, padding_waste: float,
                     batch_latency_s: float, queue_depth: int,
                     cache_store: Optional[dict] = None):
        """One executed batch; emits the JSONL record. `cache_store` is
        the FoldCache.snapshot() of the scheduler's result store (None
        when caching is off): the JSONL cache section combines the
        submit-side counters here with the store's resident bytes and
        evictions so one record answers "is the cache working"."""
        with self._lock:
            self.batches += 1
            self.queue_depth = queue_depth
            self.exec_busy_s += float(batch_latency_s)
            self._real_tokens += real_tokens
            self._padded_tokens += batch_size * bucket_len
            lat = self._bucket_hist(bucket_len)
            record = dict(
                bucket_len=bucket_len,
                batch_size=batch_size,
                n_real=n_real,
                queue_depth=queue_depth,
                padding_waste=padding_waste,
                batch_latency_s=batch_latency_s,
                p50_latency_s=lat.percentile(50),
                p90_latency_s=lat.percentile(90),
                p99_latency_s=lat.percentile(99),
            )
            if cache_store is not None:
                cache = self._cache_view()
                cache["bytes_resident"] = cache_store.get(
                    "bytes_resident", 0)
                cache["evictions"] = cache_store.get("evictions", 0)
                record["cache"] = cache
            step = self.batches
            logger = self._logger
        self._m_batches.inc()
        self._m_tokens.inc(real_tokens, kind="real")
        self._m_tokens.inc(batch_size * bucket_len - real_tokens,
                           kind="padding")
        self._m_queue_depth.set(queue_depth)
        if logger is not None:
            try:
                logger.log(step=step, **record)
            except Exception:
                # the JSONL sink is observability, not serving: a full
                # disk under the metrics file must not lose the counter
                # updates above or propagate into the serving worker
                pass

    # -- views -----------------------------------------------------------

    def padding_waste_fraction(self) -> float:
        with self._lock:
            if self._padded_tokens == 0:
                return 0.0
            return 1.0 - self._real_tokens / float(self._padded_tokens)

    def snapshot(self) -> dict:
        """Health-check view: counters + per-bucket latency tails."""
        with self._lock:
            per_bucket = {
                str(b): {"count": h.count(),
                         "p50_s": h.percentile(50),
                         "p90_s": h.percentile(90),
                         "p99_s": h.percentile(99)}
                for b, h in sorted(self._latencies.items())
            }
            padded = self._padded_tokens
            waste = (1.0 - self._real_tokens / float(padded)) if padded \
                else 0.0
            grid = self._step_grid_tokens
            waste_admitted = (1.0 - self._step_real_tokens / float(grid)) \
                if grid else 0.0
            admit_pad = {
                "count": self._admit_pad_hist.count(),
                "p50": self._admit_pad_hist.percentile(50),
                "p99": self._admit_pad_hist.percentile(99),
            }
            out = {
                "enqueued": self.enqueued,
                "served": self.served,
                "shed": self.shed,
                "errors": self.errors,
                "cancelled": self.cancelled,
                "rejected": self.rejected,
                "degraded": self.degraded,
                "poisoned": self.poisoned,
                "retried": self.retried,
                "too_large": self.too_large,
                "batches": self.batches,
                "queue_depth": self.queue_depth,
                "exec_busy_s": self.exec_busy_s,
                "padding_waste": waste,
                # occupancy-weighted over executed recycle steps
                # (0.0 when the step loop never ran — ISSUE 13)
                "padding_waste_admitted": waste_admitted,
                "row_admits": self.row_admits,
                "admit_pad_fraction": admit_pad,
                "latency_by_bucket": per_bucket,
                "cache": self._cache_view(),
            }
            if self.preempted:
                # only after a reclaim: the never-preempted snapshot
                # stays byte-identical (the identity pin reads it)
                out["preempted"] = self.preempted
            return out

    def close(self):
        if self._logger is not None:
            self._logger.close()


class KeyFrequencyLog:
    """Served-traffic key frequencies as a cache_warm profile (ISSUE 16).

    Every ingress submit (forwarded hops excluded — each user request
    counts once, at the replica that received it) is aggregated by its
    (seq, msa) content digest and periodically flushed as JSONL in
    EXACTLY the profile format `tools/cache_warm.py` reads:

        {"seq": [tokens...], "count": n}
        {"seq": [tokens...], "msa": [[tokens...]], "count": n}

    so telemetry-driven warming is the same code path as offline
    warming — the controller (or `cache_warm --from-serve-log`) tails
    these files and folds the head into the ring owners' caches.
    Flushes are atomic full rewrites (tmp + os.replace): a reader never
    sees a torn file, and counts are cumulative per unique key, not
    append-per-request — the file stays O(unique keys).

    Off by default everywhere: nothing constructs one unless asked
    (`Scheduler(key_log=)`, ProcFleet `key_log=True`), so the no-log
    serving path is byte-identical.
    """

    def __init__(self, path: str, flush_every: int = 16):
        self.path = path
        self.flush_every = max(1, int(flush_every))
        self.observed = 0
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}   # digest -> profile record

    def observe(self, seq, msa=None):
        import hashlib

        import numpy as np

        try:
            seq_arr = np.asarray(seq)
            h = hashlib.blake2b(digest_size=16)
            h.update(seq_arr.astype(np.int64, copy=False).tobytes())
            msa_arr = None
            if msa is not None:
                msa_arr = np.asarray(msa)
                h.update(b"|msa|")
                h.update(msa_arr.astype(np.int64, copy=False).tobytes())
            digest = h.hexdigest()
        except Exception:
            return             # unkeyable traffic is never worth a crash
        with self._lock:
            ent = self._entries.get(digest)
            if ent is None:
                rec = {"seq": seq_arr.tolist(), "count": 1}
                if msa_arr is not None:
                    rec["msa"] = msa_arr.tolist()
                self._entries[digest] = rec
            else:
                ent["count"] += 1
            self.observed += 1
            due = self.observed % self.flush_every == 0
        if due:
            self.flush()

    def flush(self):
        """Atomic full rewrite, hottest keys first."""
        import json
        import os

        with self._lock:
            records = sorted(self._entries.values(),
                             key=lambda r: -r["count"])
            records = [dict(r) for r in records]
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                for rec in records:
                    fh.write(json.dumps(rec) + "\n")
            os.replace(tmp, self.path)
        except OSError:
            pass               # telemetry is best-effort, serving wins

    def snapshot(self) -> dict:
        with self._lock:
            return {"path": self.path,
                    "observed": self.observed,
                    "unique": len(self._entries)}
