"""Speculative model cascade: a draft tier in front of the flagship
(ISSUE 19).

HelixFold's tiered-efficiency results say most traffic does not need
the flagship config: a small trunk with short recycles produces an
acceptable structure for the easy majority of sequences, at a fraction
of the accelerator-seconds. The cascade makes that a SERVING property
instead of a modeling one:

1. every cascaded submit folds on the DRAFT scheduler first (its own
   small model, its own `model_tag`, its own isolated metrics);
2. a confidence gate (serve/confidence.py — mean pLDDT, optionally
   distogram entropy) judges the draft result from outputs the model
   already emits;
3. an accepted draft resolves the caller's ticket as `tier="draft"`;
   a rejected (or errored) one ESCALATES: the original request
   re-enters the flagship scheduler through the ordinary submit seam —
   priority-boosted, deadline re-anchored to what remains — and
   resolves as `tier="flagship", escalated=True`.

Tier isolation is by construction, then double-checked at runtime:
the two tiers share one `FoldCache`, but `fold_key` embeds
`model_tag`, so a draft result can never be read under a flagship key
or vice versa. The scheduler still compares the two keys per cascaded
submit and counts any collision in
`serve_cascade_cross_tier_hits_total` — the smoke test pins that
counter to 0, so a future keying regression fails loudly instead of
silently serving draft structures to flagship callers.

Everything here is data + wiring helpers; the flow itself lives in
`Scheduler._submit_cascade` (it needs the scheduler's queue/cache/
trace internals). `Scheduler(cascade=None)` — the default — is
byte-for-byte PR-18 behavior, pinned by scrubbed-stats and
metric-name-set identity tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from alphafold2_tpu.serve.confidence import ConfidenceGate

__all__ = ["CascadePolicy", "build_draft_scheduler"]


@dataclass
class CascadePolicy:
    """Knobs for the speculative cascade (Scheduler(cascade=...)).

    draft: the draft-tier scheduler — anything with the Scheduler
        submit/model_tag/start/stop surface. It MUST carry a model_tag
        distinct from the flagship's (attach raises otherwise: the
        shared FoldCache keys tiers apart by tag alone).
    gate: the accept/escalate predicate over the draft's confidence.
    escalation_priority: added to the request's own priority when it
        escalates — the flagship already made this caller wait out a
        draft fold, so the escalation must not also queue behind fresh
        arrivals of equal priority.
    draft_deadline_s: cap on the DRAFT attempt's deadline. The draft
        request carries min(remaining request deadline, this cap):
        a draft that cannot fold quickly should fail over to the
        flagship while the caller's budget still covers a real fold.
        None = the draft inherits the caller's deadline unchanged.
    manage_draft: the flagship's start()/stop() also start/stop the
        draft scheduler — one lifecycle for callers that treat the
        cascade as a unit (ProcFleet replicas do). Turn off when the
        draft's lifecycle is owned elsewhere.
    """

    draft: object = None
    gate: ConfidenceGate = field(default_factory=ConfidenceGate)
    escalation_priority: int = 10
    draft_deadline_s: Optional[float] = None
    manage_draft: bool = True

    def __post_init__(self):
        if self.draft is None or not hasattr(self.draft, "submit"):
            raise ValueError(
                "CascadePolicy.draft must be a scheduler-like object "
                "with .submit()")
        if not hasattr(self.draft, "model_tag"):
            raise ValueError(
                "CascadePolicy.draft must expose .model_tag (cross-tier "
                "cache isolation keys on it)")
        if self.escalation_priority < 0:
            raise ValueError("escalation_priority must be >= 0")
        if self.draft_deadline_s is not None and self.draft_deadline_s <= 0:
            raise ValueError("draft_deadline_s must be > 0")

    def draft_deadline(self, remaining_s: Optional[float]) -> Optional[float]:
        """Effective deadline for the draft attempt given the caller's
        remaining budget (None = unbounded)."""
        if self.draft_deadline_s is None:
            return remaining_s
        if remaining_s is None:
            return self.draft_deadline_s
        return min(remaining_s, self.draft_deadline_s)


def build_draft_scheduler(executor, buckets, config=None,
                          model_tag: str = "draft",
                          cache=None, tracer=None, **kwargs):
    """Construct a draft-tier Scheduler on an ISOLATED metrics registry.

    The draft must not share the flagship's registry: `ServeMetrics`
    mirrors into registry counters dedup'd by NAME, so a shared
    registry would silently sum draft and flagship series (latency
    histograms, outcome counters) and corrupt both the flagship's SLO
    window and the identity tests. The draft's own numbers stay
    reachable through `serve_stats()["cascade"]["draft"]`.

    cache: pass the FLAGSHIP's FoldCache to share the result store —
        the draft writes under its own model_tag, so sharing is safe
        by construction and lets a repeated draft fold hit.
    confidence_summary is forced on (unless the caller pins it) so the
    gate can read distogram entropy, not just pLDDT.
    """
    from alphafold2_tpu.obs.registry import MetricsRegistry
    from alphafold2_tpu.serve.metrics import ServeMetrics
    from alphafold2_tpu.serve.scheduler import Scheduler, SchedulerConfig

    if config is None:
        config = SchedulerConfig(confidence_summary=True)
    reg = MetricsRegistry()
    return Scheduler(executor, buckets, config=config,
                     metrics=ServeMetrics(registry=reg),
                     cache=cache, model_tag=model_tag, tracer=tracer,
                     registry=reg, **kwargs)
