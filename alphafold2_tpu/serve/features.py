"""Feature-pipeline disaggregation: a CPU featurize pool feeding the
fold scheduler (ISSUE 10; ParaFold's core result, FastFold's overlap).

At millions-of-users scale AF2 serving time is dominated by CPU-side
feature work — tokenize, MSA prep, feature construction (and, in a full
deployment, the MSA search itself) — not the accelerator fold. Folding
them through one path couples the two: every submit pays featurization
inline, the accelerator idles while features cook, and feature work
dedups exactly never. This module splits serving into an explicit
two-stage pipeline:

    raw job --> FeaturePool (CPU workers)  --> Scheduler (accelerator)
                 |  feature cache tier           |  fold cache tier
                 |  (cache.FeatureCache,         |  (cache.FoldCache,
                 |   keyed by feature_key)       |   keyed by fold_key)
                 `- in-flight featurize          `- in-flight fold
                    coalescing                      coalescing

- `RawFoldRequest` is the raw unit of work: an AA string (or
  untokenized token array) plus an optional raw MSA (aligned strings or
  token rows), with the same QoS knobs as `FoldRequest`.
- `FeaturePool` runs featurization on a configurable worker pool OFF
  the submit hot path, with its own content-addressed cache tier
  (`cache.feature_key` keyed UPSTREAM of `fold_key` — no fold config in
  the key, so one feature entry serves every downstream fold variant)
  and in-flight featurize coalescing (duplicate raw traffic featurizes
  exactly once, independently of fold-level dedup). Completed features
  become `FoldRequest`s fed into the scheduler's existing queue; the
  caller's `FoldTicket` (returned synchronously from submit_raw)
  resolves off the fold ticket, progressive results included.
- `PipelineScheduler` is the thin two-stage front owning both.

QoS composition: a raw job's `deadline_s` covers the WHOLE pipeline —
time spent featurizing (queueing included) is deducted from the
deadline handed to the fold scheduler, and a job whose deadline expires
before its features are ready is shed without touching the queue
(`feature_deadline_exceeded`), the same
fold-dead-work-is-the-most-expensive-miss logic the scheduler applies.

Fleet composition: with a router on the scheduler, a raw job is routed
by its FEATURE key before featurizing — the ring owner featurizes
replica-side and folds (one bounded hop, `RawFoldRequest.forwarded`),
so the owner's feature cache concentrates the raw duplicate traffic the
same way its fold cache concentrates fold traffic. Any forwarding
trouble degrades to featurizing locally, never to an error.

Off by default, everywhere: a `Scheduler` without a `feature_pool` is
byte-for-byte today's behavior (`submit_raw` then featurizes inline,
which is exactly what callers hand-rolled before), and `serve_stats()`
carries a "featurize" section only when a pool is attached.

Obs: every raw job's request trace grows a `featurize` span (queue +
work in the pool; tools/obs_report.py STAGE_ORDER renders it ahead of
submit), the pool reports `serve_featurize_*` counters and a
queue-depth gauge, and featurize latency lands in a registry histogram
(`serve_featurize_seconds`).
"""

from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence, Union

import numpy as np

from alphafold2_tpu import constants
from alphafold2_tpu.cache.features import FeatureCache, FeaturizedInput
from alphafold2_tpu.cache.keys import feature_key
from alphafold2_tpu.data.featurize import tokenize
from alphafold2_tpu.obs.registry import (DEFAULT_LATENCY_BUCKETS,
                                         Histogram, MetricsRegistry,
                                         get_registry)
from alphafold2_tpu.serve.request import (FoldRequest, FoldResponse,
                                          FoldTicket, _next_request_id)
from alphafold2_tpu.utils.hashing import stable_digest

# bump when the featurizer's BEHAVIOR changes (token mapping, MSA prep
# convention): the config digest lands in every feature_key, so stale
# cached features miss cleanly instead of serving the old mapping
FEATURIZE_VERSION = 1


def featurizer_config_digest() -> str:
    """Digest of everything that determines tokenize/MSA-prep output
    for a given raw input — part of every `feature_key`, so a tokenizer
    or alphabet change can never serve a stale featurized form."""
    from alphafold2_tpu.data.featurize import GAP_CHARS
    return stable_digest("featurizer", FEATURIZE_VERSION,
                         constants.AA_ALPHABET, GAP_CHARS)


@dataclass
class RawFoldRequest:
    """One RAW fold job: the pre-featurization unit of work.

    seq: an AA string ("MKV...") or an untokenized 1-D int array.
    msa: optional raw MSA — a sequence of aligned AA strings (query
        row first, trrosetta convention) or an (m, n) int token array.
        Depth handling (msa_depth truncation/padding) stays the fold
        scheduler's job; featurization preserves every row.
    priority / deadline_s: FoldRequest semantics; the deadline covers
        the WHOLE pipeline, featurize time included.
    forwarded: this job already took its one feature-key routing hop
        (fleet mode) — the receiver featurizes and folds locally.
    qos: FoldRequest semantics, plus the raw-path meaning of
        "express" (ISSUE 19): skip MSA prep entirely — the pool's
        embedding-injection featurizer (FeaturePool(express=...))
        builds single-sequence features, and the fold rides the
        express deadline/SLO class. "online" (default) is byte-
        for-byte the pre-express path.
    """

    seq: Union[str, np.ndarray]
    msa: Optional[Union[Sequence[str], np.ndarray]] = None
    request_id: str = field(default_factory=_next_request_id)
    priority: int = 0
    deadline_s: Optional[float] = None
    forwarded: bool = False
    qos: str = "online"

    def __post_init__(self):
        if self.qos not in ("online", "bulk", "express"):
            raise ValueError(
                f"RawFoldRequest.qos must be 'online', 'bulk' or "
                f"'express', got {self.qos!r}")

    @property
    def length(self) -> int:
        return (len(self.seq.strip()) if isinstance(self.seq, str)
                else int(np.asarray(self.seq).shape[0]))


def featurize_raw(raw: RawFoldRequest) -> FeaturizedInput:
    """Tokenize + MSA-prep one raw job into the arrays `FoldRequest`
    consumes. Pure host-side numpy (data/featurize.tokenize); raises
    ValueError on malformed input — the pool maps that to an error
    terminal, the inline path to the caller."""
    seq = raw.seq
    if isinstance(seq, str):
        tokens = tokenize(seq.strip())
    else:
        tokens = np.asarray(seq, np.int32)
    if tokens.ndim != 1 or tokens.shape[0] == 0:
        raise ValueError(
            f"raw seq must featurize to a non-empty 1-D token array, "
            f"got shape {tokens.shape}")
    msa = raw.msa
    if msa is None:
        return FeaturizedInput(seq=tokens, msa=None)
    if not isinstance(msa, np.ndarray) and len(msa) > 0 \
            and all(isinstance(r, str) for r in msa):
        rows = []
        for i, r in enumerate(msa):
            row = tokenize(r.strip())
            if row.shape[0] != tokens.shape[0]:
                raise ValueError(
                    f"raw MSA row {i} has length {row.shape[0]}, "
                    f"expected aligned length {tokens.shape[0]}")
            rows.append(row)
        msa_tokens = np.stack(rows, 0).astype(np.int32)
    else:
        msa_tokens = np.asarray(msa, np.int32)
    if msa_tokens.ndim != 2 or msa_tokens.shape[1] != tokens.shape[0]:
        raise ValueError(
            f"raw MSA must featurize to (m, {tokens.shape[0]}), got "
            f"{msa_tokens.shape}")
    return FeaturizedInput(seq=tokens, msa=msa_tokens)


# -- express lane: MSA-free featurization (ISSUE 19) ----------------------


class StubEmbedder:
    """Deterministic stand-in for a pretrained single-sequence embedder
    (the `embeds.py` ESM/ProtTran wrappers' `embed_batch` contract):
    per-position embeddings derived from the tokens by pure integer
    numpy, byte-stable across processes and platforms — what CPU tests
    and the loadtest need where a real language model would load
    checkpoints. dim: embedding width (kept tiny; express features
    only quantize it back down)."""

    def __init__(self, dim: int = 16):
        if dim < 1:
            raise ValueError("StubEmbedder dim must be >= 1")
        self.dim = int(dim)

    @property
    def digest(self) -> str:
        """Identity folded into express feature keys — a different
        embedder must never share cached features."""
        return f"stub-embedder-v1-d{self.dim}"

    def embed_batch(self, seq, msa=None):
        """(n,) int tokens -> ((n, dim) float32 embedding, None).
        Mirrors the reference wrappers' (seq_embed, msa_embed) return
        shape; the stub has no MSA track."""
        tokens = np.asarray(seq, dtype=np.int64).reshape(-1)
        pos = np.arange(tokens.shape[0], dtype=np.int64)[:, None]
        ch = np.arange(self.dim, dtype=np.int64)[None, :]
        # LCG-style integer mix: deterministic, alphabet-sized inputs
        # spread over the full int range before the float squash
        mixed = (tokens[:, None] * 2654435761 + pos * 40503
                 + ch * 69621 + 12345) % 2147483647
        embed = (mixed.astype(np.float32) / 2147483647.0) * 2.0 - 1.0
        return embed, None


def express_featurize(raw: RawFoldRequest, embedder) -> FeaturizedInput:
    """MSA-free express featurization: tokenize the sequence, embed it
    with the single-sequence embedder, and inject the embedding into
    the MSA track as one pseudo-row behind the query (HelixFold-
    single's trick: the MSA transformer reads a derived row instead of
    a real alignment, so the model runs at constant shallow depth with
    no search — two rows here, query-first per the bucketing
    convention). The
    pseudo-row is the embedding quantized back into the token
    alphabet — deterministic for a deterministic embedder, which is
    what the byte-determinism test pins. Any raw MSA on the request is
    IGNORED by design: express means "don't wait for alignments"."""
    seq = raw.seq
    tokens = tokenize(seq.strip()) if isinstance(seq, str) \
        else np.asarray(seq, np.int32)
    if tokens.ndim != 1 or tokens.shape[0] == 0:
        raise ValueError(
            f"express seq must featurize to a non-empty 1-D token "
            f"array, got shape {tokens.shape}")
    embed, _ = embedder.embed_batch(tokens)
    embed = np.asarray(embed)
    if embed.ndim != 2 or embed.shape[0] != tokens.shape[0]:
        raise ValueError(
            f"embedder returned shape {embed.shape}, expected "
            f"({tokens.shape[0]}, d)")
    # quantize each position's embedding into the token vocabulary:
    # scale the per-position mean into [0, 1), then index the alphabet
    vocab = len(constants.AA_ALPHABET)
    mean = embed.mean(axis=-1)
    lo, hi = float(mean.min()), float(mean.max())
    span = hi - lo
    if span <= 0:
        pseudo = np.zeros_like(tokens)
    else:
        unit = (mean - lo) / span
        pseudo = np.minimum((unit * vocab).astype(np.int32), vocab - 1)
    msa = np.stack([tokens, pseudo], 0).astype(np.int32)
    return FeaturizedInput(seq=tokens, msa=msa)


class _Waiter:
    """One raw job parked on an in-flight featurize leader."""

    __slots__ = ("raw", "ticket", "trace", "t0", "scheduler")

    def __init__(self, raw, ticket, trace, t0, scheduler):
        self.raw = raw
        self.ticket = ticket
        self.trace = trace
        self.t0 = t0
        self.scheduler = scheduler


class FeaturePool:
    """CPU featurize pool feeding a fold scheduler's queue.

    workers: featurize worker threads — ParaFold's point is that this
        scales independently of both the submit path and the
        accelerator: size it so feature throughput matches fold
        throughput (README "Feature pipeline").
    cache: optional `cache.FeatureCache` — the feature tier. A hit
        skips featurization entirely (the raw job goes straight to the
        fold scheduler). Off when None.
    latency_s: synthetic extra featurize latency per EXECUTION — the
        benchmarking knob (`serve_loadtest --feature-latency-ms`) that
        stands in for real MSA-search cost on the tiny test model; 0
        (the default) adds nothing.
    featurize_fn: override the featurize implementation
        (RawFoldRequest -> FeaturizedInput); defaults to
        `featurize_raw`. The seam real MSA pipelines plug into.
    config_digest: feature-key config namespace; defaults to
        `featurizer_config_digest()` (pass your own when overriding
        featurize_fn — different featurizers must not share keys).
    faults: optional serve.faults.FaultPlan — chaos hook fired before
        each featurize execution (injected exceptions fan out to every
        coalesced waiter exactly like a real featurize failure;
        injected latency exercises the feature-deadline path). None
        (default) costs nothing.
    executor: "thread" (default — byte-identical behavior) or
        "process": featurize COMPUTATIONS run on a shared
        ProcessPoolExecutor, sidestepping the GIL (the prerequisite
        for real jackhmmer/mmseqs featurizers whose parsing is
        CPU-bound Python). All coordination — coalescing, cache,
        deadlines, traces, fold handoff — stays on the thread pool;
        only the pure `featurize_fn(raw)` call crosses the process
        boundary, so the semantics are identical. An unpicklable
        featurize_fn/raw or a broken child degrades that job to
        in-thread featurization (counted in snapshot
        "process_fallbacks"), never to an error.
    express: optional single-sequence embedder (the `embed_batch`
        contract — StubEmbedder, or a real ESM/ProtTran wrapper)
        enabling `RawFoldRequest(qos="express")`: MSA prep is bypassed
        via `express_featurize`, keyed under the embedder's own digest
        namespace so express and online features never collide. None
        (default): express raw jobs resolve as errors.
    express_deadline_s: cap on the FOLD deadline of express jobs (the
        express lane's promise is tight tail latency — an express fold
        that can't run promptly sheds instead of queueing). None =
        no cap beyond the request's own deadline.

    Duplicate raw traffic dedups at this tier independently of fold
    traffic: an in-flight featurize of the same feature key coalesces
    (one execution, every waiter fed), a finished one hits the cache.
    Each deduped job still submits its OWN FoldRequest downstream —
    identical tokens, so the fold tier's cache/coalescing then dedups
    the folds exactly as if the callers had submitted tokens directly.
    """

    def __init__(self, workers: int = 2,
                 cache: Optional[FeatureCache] = None,
                 latency_s: float = 0.0,
                 featurize_fn: Optional[Callable] = None,
                 config_digest: Optional[str] = None,
                 faults=None,
                 registry: Optional[MetricsRegistry] = None,
                 executor: str = "thread",
                 express=None,
                 express_deadline_s: Optional[float] = None):
        if workers < 1:
            raise ValueError("FeaturePool needs at least 1 worker")
        if executor not in ("thread", "process"):
            raise ValueError(
                f"FeaturePool executor must be 'thread' or 'process', "
                f"got {executor!r}")
        if express_deadline_s is not None and express_deadline_s <= 0:
            raise ValueError("express_deadline_s must be > 0")
        self.workers = int(workers)
        self.cache = cache
        self.faults = faults
        self.latency_s = float(latency_s)
        self.featurize_fn = featurize_fn or featurize_raw
        self.config_digest = (featurizer_config_digest()
                              if config_digest is None else config_digest)
        self.executor = executor
        self.express = express
        self.express_deadline_s = express_deadline_s
        # express features key under the embedder's identity, never the
        # online featurizer's — a cached express pseudo-MSA must not
        # serve an online job for the same sequence (or vice versa)
        self._express_digest = None
        if express is not None:
            self._express_digest = stable_digest(
                "express-featurizer", FEATURIZE_VERSION,
                constants.AA_ALPHABET,
                getattr(express, "digest", type(express).__name__))
        self._proc_pool = (ProcessPoolExecutor(max_workers=self.workers)
                           if executor == "process" else None)
        self.process_fallbacks = 0     # jobs degraded to in-thread
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="featurize")
        self._lock = threading.Lock()
        # feature_key -> list of parked _Waiter (leader excluded: it is
        # carried by the pool work item itself)
        self._inflight: dict = {}
        self._depth = 0                # queued + running pool jobs
        self._stopped = False
        self._retired_pools: list = []  # pre-resize executors, draining
        self.resizes = 0               # in-place worker-count changes
        # lifetime counters (lock-guarded; snapshot reads are racy-ok)
        self.submissions = 0
        self.executions = 0            # featurize runs (dedup excluded)
        self.cache_hits = 0
        self.coalesced = 0
        self.errors = 0
        self.shed = 0                  # deadlines dead before features
        self.forwarded = 0             # raw jobs routed to their owner
        reg = registry or get_registry()
        self._c_total = reg.counter(
            "serve_featurize_total",
            "featurize executions by the feature pool")
        self._c_hits = reg.counter(
            "serve_featurize_cache_hits_total",
            "raw jobs served from the feature cache tier")
        self._c_coalesced = reg.counter(
            "serve_featurize_coalesced_total",
            "raw jobs coalesced onto an in-flight featurize")
        self._c_errors = reg.counter(
            "serve_featurize_errors_total",
            "raw jobs failed in featurization")
        self._g_depth = reg.gauge(
            "serve_featurize_queue_depth",
            "raw jobs queued or running in the feature pool")
        self._h_latency = reg.histogram(
            "serve_featurize_seconds",
            "featurize execution latency (work only, not queueing)",
            reservoir=4096)
        # instance-scoped reservoir answering THIS pool's snapshot()
        self._latency = Histogram("serve_featurize_seconds",
                                  "featurize latency",
                                  buckets=DEFAULT_LATENCY_BUCKETS,
                                  reservoir=4096)

    # -- lifecycle -------------------------------------------------------

    def stop(self):
        """Drain the worker pool (in-flight featurize jobs finish and
        feed their folds; nothing new is accepted)."""
        with self._lock:
            self._stopped = True
            pools = [self._pool] + self._retired_pools
            self._retired_pools = []
        for pool in pools:
            pool.shutdown(wait=True)
        # the process pool last: thread workers above may still be
        # awaiting results from it
        if self._proc_pool is not None:
            self._proc_pool.shutdown(wait=True)

    def resize(self, workers: int) -> int:
        """Resize the worker pool IN PLACE (ISSUE 16 `/admin/resize`):
        swap in a fresh executor at the new width and retire the old
        one without waiting — its queued + running jobs drain on its
        own threads, new submissions land on the new pool, and no job
        is dropped or re-run. Callers racing the swap and losing
        (submit on a just-shutdown pool) already fall back to inline
        featurize in `_enqueue_local`. Returns the new width."""
        workers = int(workers)
        if workers < 1:
            raise ValueError("FeaturePool needs at least 1 worker")
        with self._lock:
            if self._stopped:
                raise RuntimeError("feature pool stopped")
            if workers == self.workers:
                return self.workers
            old = self._pool
            self._pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="featurize")
            # keep a handle so stop() still waits for the stragglers
            self._retired_pools.append(old)
            self.workers = workers
            self.resizes += 1
            old_proc = self._proc_pool
            if old_proc is not None:
                self._proc_pool = ProcessPoolExecutor(
                    max_workers=workers)
        old.shutdown(wait=False)     # drains queued jobs, blocks nothing
        if self.executor == "process" and old_proc is not None:
            old_proc.shutdown(wait=False)
        return workers

    def __enter__(self) -> "FeaturePool":
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- submission ------------------------------------------------------

    def submit_raw(self, raw: RawFoldRequest, scheduler,
                   trace=None) -> FoldTicket:
        """Accept one raw job; returns the caller's FoldTicket NOW (the
        same ticket type Scheduler.submit returns — result(), progress
        callbacks, and done callbacks all behave identically). The
        pipeline behind it: feature cache -> in-flight coalesce ->
        worker featurize -> scheduler.submit, with the request trace
        carrying a `featurize` span for the first two stages' miss
        path. `trace`: an already-started obs.Trace to continue (a
        remote hop's continued trace, ISSUE 15); None mints one."""
        ticket = FoldTicket(raw.request_id)
        if trace is None:
            trace = scheduler.tracer.start_trace(raw.request_id)
        t0 = time.monotonic()
        with self._lock:
            self.submissions += 1
            stopped = self._stopped
        if stopped:
            self._resolve_error(ticket, trace, raw,
                                "feature pool stopped")
            return ticket
        if getattr(raw, "qos", "online") == "express" \
                and self.express is None:
            # the async seam's ValueError: an express job without an
            # embedder must fail loudly, not silently serve the full
            # prep path under an express deadline it cannot meet
            self._resolve_error(
                ticket, trace, raw,
                "qos='express' needs FeaturePool(express=...) — no "
                "embedding-injection featurizer is configured")
            return ticket
        key = None
        try:
            key = feature_key(raw.seq, raw.msa,
                              config_digest=self._digest_for(raw))
        except Exception:
            pass          # unkeyable: featurize without dedup/caching
        if self._maybe_forward_raw(raw, key, scheduler, ticket, trace,
                                   t0):
            return ticket
        self._enqueue_local(raw, key, scheduler, ticket, trace, t0)
        return ticket

    def _enqueue_local(self, raw, key, scheduler, ticket, trace, t0):
        trace.begin("featurize")
        if key is not None:
            with self._lock:
                waiting = self._inflight.get(key)
                if waiting is not None:
                    # coalesce: the in-flight leader's execution feeds
                    # this waiter too — zero duplicate featurize work
                    waiting.append(_Waiter(raw, ticket, trace, t0,
                                           scheduler))
                    self.coalesced += 1
                    self._c_coalesced.inc()
                    trace.event("featurize_coalesced")
                    return
                self._inflight[key] = []
            # cache check AFTER claiming leadership, never before: an
            # unlocked check-then-claim would race a completing leader
            # (put + settle between our miss and our claim) into a
            # SECOND featurize execution of an already-cached key.
            # Having claimed, any racing duplicate coalesces behind us
            # and is fed by whichever path we take below.
            if self.cache is not None:
                feats = self.cache.get(key, trace=trace)
                if feats is not None:
                    with self._lock:
                        self.cache_hits += 1
                    self._c_hits.inc()
                    waiters = self._settle(key)   # release the claim
                    trace.end("featurize", cached=True)
                    self._submit_fold(scheduler, raw, feats, ticket,
                                      trace, t0)
                    for w in waiters:
                        w.trace.end("featurize", coalesced=True)
                        self._submit_fold(w.scheduler, w.raw, feats,
                                          w.ticket, w.trace, w.t0)
                    return
        self._advance_depth(+1)
        try:
            self._pool.submit(self._run, key, raw, ticket, trace, t0,
                              scheduler)
        except BaseException:
            # pool shut down in the submit/enqueue race: featurize
            # inline — slower beats lost
            self._advance_depth(-1)
            self._run(key, raw, ticket, trace, t0, scheduler,
                      count_depth=False)

    def _advance_depth(self, delta: int):
        with self._lock:
            self._depth += delta
            depth = self._depth
        self._g_depth.set(depth)

    def _digest_for(self, raw) -> str:
        """Feature-key config namespace for one raw job: the express
        embedder's digest for express jobs, the featurizer's for
        everything else — the two representations must never share
        cache entries."""
        if getattr(raw, "qos", "online") == "express" \
                and self._express_digest is not None:
            return f"express:{self._express_digest}"
        return self.config_digest

    def _fn_for(self, raw) -> Callable:
        """The featurize implementation one raw job runs: the express
        embedding-injection path for express jobs, the configured
        featurize_fn otherwise. functools.partial keeps it picklable
        for the process executor."""
        if getattr(raw, "qos", "online") == "express":
            return functools.partial(express_featurize,
                                     embedder=self.express)
        return self.featurize_fn

    def _featurize_exec(self, raw, fn) -> FeaturizedInput:
        """Run the pure featurize computation on the configured
        executor. Process mode crosses the pickle boundary; anything
        that breaks the CROSSING (unpicklable fn/raw, a killed child)
        degrades to in-thread featurization — a real featurize failure
        inside fn propagates either way."""
        if self._proc_pool is None:
            return fn(raw)
        try:
            return self._proc_pool.submit(fn, raw).result()
        except Exception:
            # pickling trouble, a killed child, a shut-down pool — and
            # genuine featurize failures — all surface here; rather
            # than classify exception types, re-run in-thread: a
            # crossing problem succeeds inline, a real featurize
            # failure raises the same error with its real reason
            with self._lock:
                self.process_fallbacks += 1
            return fn(raw)

    # -- worker ----------------------------------------------------------

    def _run(self, key, raw, ticket, trace, t0, scheduler,
             count_depth: bool = True):
        try:
            t_work = time.monotonic()
            try:
                if self.faults is not None:
                    # chaos hook (ISSUE 14): an injected featurize
                    # failure takes the SAME path a real one does —
                    # _settle_error fans it to every coalesced waiter
                    self.faults.on_featurize(key)
                if self.latency_s > 0:
                    time.sleep(self.latency_s)
                feats = self._featurize_exec(raw, self._fn_for(raw))
            except Exception as exc:
                self._settle_error(key, ticket, trace, raw,
                                   f"featurize failed: {exc!r}")
                return
            dur = time.monotonic() - t_work
            with self._lock:
                self.executions += 1
            self._c_total.inc()
            self._h_latency.observe(dur)
            self._latency.observe(dur)
            if key is not None and self.cache is not None:
                try:
                    self.cache.put(key, feats.seq, feats.msa)
                except Exception:
                    pass      # a broken cache costs recomputes, never jobs
            waiters = self._settle(key)
            trace.end("featurize")
            self._submit_fold(scheduler, raw, feats, ticket, trace, t0)
            for w in waiters:
                w.trace.end("featurize", coalesced=True)
                self._submit_fold(w.scheduler, w.raw, feats, w.ticket,
                                  w.trace, w.t0)
        finally:
            if count_depth:
                self._advance_depth(-1)

    def _settle(self, key) -> list:
        if key is None:
            return []
        with self._lock:
            return self._inflight.pop(key, [])

    def _settle_error(self, key, ticket, trace, raw, error: str):
        """Featurize failed: the leader AND every coalesced waiter get
        the error terminal (a waiter that attached to a failing leader
        must see that failure, never hang)."""
        waiters = self._settle(key)
        self._resolve_error(ticket, trace, raw, error)
        for w in waiters:
            self._resolve_error(w.ticket, w.trace, w.raw, error)

    def _resolve_error(self, ticket, trace, raw, error: str):
        with self._lock:
            self.errors += 1
        self._c_errors.inc()
        trace.finish("error", error=error)
        ticket._resolve(FoldResponse(
            request_id=raw.request_id, status="error", error=error))

    # -- stage handoff ---------------------------------------------------

    def _submit_fold(self, scheduler, raw, feats: FeaturizedInput,
                     ticket, trace, t0: float):
        """Features ready: hand the job to the fold scheduler and chain
        its ticket (terminal + progressive) onto the caller's. The
        remaining deadline is re-anchored: featurize time already spent
        counts against the raw job's budget."""
        qos = getattr(raw, "qos", "online")
        deadline = raw.deadline_s
        if deadline is not None:
            deadline = deadline - (time.monotonic() - t0)
            if deadline <= 0:
                with self._lock:
                    self.shed += 1
                trace.event("feature_deadline_exceeded")
                trace.finish("shed",
                             error="deadline expired before features "
                                   "were ready (feature_deadline_"
                                   "exceeded)")
                ticket._resolve(FoldResponse(
                    request_id=raw.request_id, status="shed",
                    latency_s=time.monotonic() - t0,
                    error="deadline expired before features were ready "
                          "(feature_deadline_exceeded)"))
                return
        if qos == "express" and self.express_deadline_s is not None:
            # the express promise is tail latency: the FOLD gets at
            # most the express cap, even when the caller's own budget
            # is looser — better an honest early shed than a p99 blown
            # by queueing behind long folds
            deadline = (self.express_deadline_s if deadline is None
                        else min(deadline, self.express_deadline_s))
        try:
            request = FoldRequest(
                seq=feats.seq, msa=feats.msa,
                request_id=raw.request_id, priority=raw.priority,
                deadline_s=deadline, forwarded=raw.forwarded,
                qos=qos)
            inner = scheduler.submit(request, trace=trace)
        except Exception as exc:
            # the async seam cannot raise backpressure at the caller
            # the way a synchronous submit does: rejected/draining/
            # stopped all terminate the ticket with the scheduler's
            # reason. finish() here is idempotent cover for failures
            # BEFORE submit adopts the trace (e.g. the bucket_for
            # fail-fast on an over-length sequence) — without it that
            # request would vanish from obs with no terminal record
            with self._lock:
                self.errors += 1
            self._c_errors.inc()
            trace.finish("error",
                         error=f"fold submit rejected after "
                               f"featurize: {exc!r}")
            ticket._resolve(FoldResponse(
                request_id=raw.request_id, status="error",
                latency_s=time.monotonic() - t0,
                error=f"fold submit rejected after featurize: {exc!r}"))
            return
        inner.add_progress_callback(ticket._publish_progress)
        inner.add_done_callback(ticket._resolve)

    # -- fleet routing ---------------------------------------------------

    def _maybe_forward_raw(self, raw, key, scheduler, ticket, trace,
                           t0) -> bool:
        """Route the RAW job by its feature key: when the scheduler has
        a router and the key's ring owner is another healthy replica
        with a raw-capable transport, forward the raw job there — the
        owner featurizes replica-side, so its feature cache (and fold
        cache) concentrate the key's traffic. One bounded hop
        (raw.forwarded); ANY trouble means featurize locally."""
        router = getattr(scheduler, "router", None)
        if router is None or raw.forwarded or key is None:
            return False
        forward_raw = getattr(router, "forward_raw", None)
        if forward_raw is None:
            return False
        try:
            decision = router.route(key)
        except Exception:
            return False
        if decision is None or decision.is_local:
            return False
        owner = decision.owner_id
        trace.event("routed_raw", owner=owner, reason=decision.reason)
        trace.begin("forward")
        try:
            remote = forward_raw(
                owner,
                RawFoldRequest(seq=raw.seq, msa=raw.msa,
                               request_id=raw.request_id,
                               priority=raw.priority,
                               deadline_s=raw.deadline_s,
                               forwarded=True,
                               qos=getattr(raw, "qos", "online")),
                trace=trace)
        except Exception:
            try:
                router.note_fallback("forward_raw_error")
            except Exception:
                pass
            trace.end("forward", failed=True)
            return False
        with self._lock:
            self.forwarded += 1

        def _on_remote(resp: FoldResponse):
            trace.end("forward", owner=owner)
            if resp is None:
                # defensive: a done callback always carries a response
                # today, but a half-guarded None would otherwise raise
                # below and leave the caller's ticket unresolved forever
                trace.finish("error", source="forwarded",
                             error="raw forward returned nothing")
                ticket._resolve(FoldResponse(
                    request_id=raw.request_id, status="error",
                    latency_s=time.monotonic() - t0, source="forwarded",
                    error="raw forward returned nothing"))
                return
            # transport death is failover-eligible: the work is viable,
            # only the owner died — featurize locally (the marker
            # string is fleet.rpc.RPC_TRANSPORT_MARKER, spelled
            # literally because serve must not import fleet)
            if resp.status == "error" and resp.error \
                    and "rpc_transport" in resp.error:
                trace.event("failover_local_raw", owner=owner)
                try:
                    self._enqueue_local(raw, key, scheduler, ticket,
                                        trace, t0)
                    return
                except Exception:
                    pass      # fall through: resolve the transport error
            trace.finish(resp.status, source="forwarded",
                         error=resp.error)
            ticket._resolve(FoldResponse(
                request_id=raw.request_id,
                status=resp.status,
                coords=resp.coords, confidence=resp.confidence,
                bucket_len=resp.bucket_len,
                latency_s=time.monotonic() - t0,
                error=resp.error, source="forwarded",
                attempts=getattr(resp, "attempts", 1),
                recycles=getattr(resp, "recycles", None)))

        remote.add_done_callback(_on_remote)
        return True

    # -- views -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            out = {"workers": self.workers,
                   "queue_depth": self._depth,
                   "submissions": self.submissions,
                   "executions": self.executions,
                   "cache_hits": self.cache_hits,
                   "coalesced": self.coalesced,
                   "errors": self.errors,
                   "shed": self.shed,
                   "forwarded": self.forwarded,
                   "latency_s_injected": self.latency_s}
        if self.resizes:
            # only after a resize: an untouched pool's snapshot stays
            # byte-identical to PR 15 (controller-off stats pin)
            out["resizes"] = self.resizes
        if self.executor != "thread":
            # non-default executors only: the thread-pool snapshot
            # stays byte-identical to PR 18
            out["executor"] = self.executor
            out["process_fallbacks"] = self.process_fallbacks
        if self.express is not None:
            out["express"] = {
                "embedder": getattr(self.express, "digest",
                                    type(self.express).__name__),
                "deadline_s": self.express_deadline_s,
            }
        out["featurize_p50_s"] = self._latency.percentile(50)
        out["featurize_p99_s"] = self._latency.percentile(99)
        if self.cache is not None:
            out["cache"] = self.cache.snapshot()
        return out


class PipelineScheduler:
    """The two-stage serving front: one FeaturePool + one Scheduler as
    a single object with the Scheduler's surface plus `submit_raw`.

        pool = serve.FeaturePool(workers=4, cache=FeatureCache(...))
        pipe = serve.PipelineScheduler(scheduler, pool)
        with pipe:
            ticket = pipe.submit_raw(serve.RawFoldRequest("MKV...",
                                                          msa=rows))
            response = ticket.result(timeout=120)

    Construction ATTACHES the pool to the scheduler (equivalent to
    `Scheduler(..., feature_pool=pool)`), so `serve_stats()["featurize"]`
    and `Scheduler.submit_raw` work whichever handle you hold.
    Lifecycle owns both stages: stop() drains the feature pool FIRST
    (in-flight featurize jobs feed their folds), then the scheduler.
    """

    def __init__(self, scheduler, feature_pool: FeaturePool):
        self.scheduler = scheduler
        self.feature_pool = feature_pool
        scheduler.feature_pool = feature_pool

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "PipelineScheduler":
        self.scheduler.start()
        return self

    def stop(self, drain: bool = True):
        # pool first: its workers submit into the scheduler, and a
        # drained pool guarantees no featurize job races a stopping
        # queue
        self.feature_pool.stop()
        self.scheduler.stop(drain=drain)

    def __enter__(self) -> "PipelineScheduler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- passthrough surface ---------------------------------------------

    @property
    def tracer(self):
        """The scheduler's tracer — a FrontDoorServer fronting this
        object continues inbound trace contexts through it (ISSUE 15),
        for tokenized submits exactly like raw ones."""
        return self.scheduler.tracer

    def submit(self, request: FoldRequest, trace=None) -> FoldTicket:
        return self.scheduler.submit(request, trace=trace)

    def submit_raw(self, raw: RawFoldRequest, trace=None) -> FoldTicket:
        return self.feature_pool.submit_raw(raw, self.scheduler,
                                            trace=trace)

    def warmup(self, *args, **kwargs) -> int:
        return self.scheduler.warmup(*args, **kwargs)

    def drain(self, timeout_s: float = 30.0) -> bool:
        self.feature_pool.stop()
        return self.scheduler.drain(timeout_s)

    def health(self) -> dict:
        return self.scheduler.health()

    def serve_stats(self) -> dict:
        return self.scheduler.serve_stats()
