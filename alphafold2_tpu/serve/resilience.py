"""Failure-domain policy for the serving stack: retry, poison
isolation, watchdog, circuit breaker.

The happy-path server (scheduler/executor/cache/fleet) treats every
executor exception the same way: error-resolve the whole batch cohort.
At fleet scale that conflates four failure classes that need four
different answers:

- TRANSIENT device trouble (preemption, RESOURCE_EXHAUSTED, a flaky
  interconnect): the work is fine, the attempt was unlucky — retry the
  batch with bounded exponential backoff + jitter (`RetryPolicy`)
  instead of erroring N innocent requests;
- POISON inputs (an outlier length that OOMs, a degenerate MSA that
  NaNs the structure module): deterministic failures that will fail on
  every retry. A failing batch is BISECTED — split in half, each half
  retried as its own isolation group — so a single poison request is
  cornered in <= log2(batch) extra executions, then quarantined
  (`Quarantine`): its key resolves status "poisoned" immediately on
  every future submit instead of re-folding garbage;
- HUNG executions (driver deadlock, a wedged device): no exception
  ever comes back, so the scheduler guards `executor.run` with a
  per-batch wall-clock deadline (`run_with_watchdog`); on expiry the
  batch is retry-resolved as transient and the executor is REBUILT —
  a hung device's compiled state is not trustworthy;
- SYSTEMIC failure (every batch failing): retrying harder makes it
  worse. A `CircuitBreaker` counts consecutive transient/watchdog
  batch failures; at the threshold it OPENS and the scheduler enters
  degraded mode — new non-duplicate submits are fast-shed with status
  "degraded" (cache and coalesce hits still serve), and after a
  cooldown the breaker goes HALF-OPEN, letting one probe batch through:
  success closes it, failure re-opens it.

Everything here is policy + small thread-safe state machines; the
scheduler owns the mechanics (re-enqueueing, group batching,
settlement). All of it is OFF by default — a `Scheduler` built without
`retry=RetryPolicy(...)` behaves exactly as before this module existed.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry


class TransientExecutorError(RuntimeError):
    """An executor failure worth retrying (device hiccup, not input)."""


class WatchdogTimeout(TransientExecutorError):
    """executor.run exceeded its per-batch wall-clock deadline."""


def run_with_watchdog(fn: Callable[[], object], timeout_s: float):
    """Run `fn()` on a helper thread, bounded by `timeout_s` seconds.

    Returns fn's result or re-raises its exception. On timeout raises
    `WatchdogTimeout` and ABANDONS the helper thread (daemon): a hung
    device call cannot be cancelled from Python, only outlived — the
    caller is expected to rebuild the executor so the zombie thread's
    eventual result (if any) lands in an object nothing references.
    One thread per call is deliberate: a persistent worker would be
    wedged by the very hang this function exists to survive, and
    batches are seconds-granular so the spawn cost is noise.
    """
    outcome: dict = {}
    done = threading.Event()

    def _target():
        try:
            outcome["value"] = fn()
        except BaseException as exc:     # noqa: BLE001 — relayed below
            outcome["exc"] = exc
        finally:
            done.set()

    t = threading.Thread(target=_target, daemon=True,
                         name="serve-watchdog-call")
    t.start()
    if not done.wait(timeout_s):
        raise WatchdogTimeout(
            f"executor.run exceeded watchdog deadline {timeout_s}s")
    if "exc" in outcome:
        raise outcome["exc"]
    return outcome["value"]


@dataclass
class RetryPolicy:
    """The scheduler's whole failure-domain configuration in one knob.

    max_attempts: total executions one entry may participate in before
        a persistent TRANSIENT failure becomes terminal
        (`retry_exhausted`). Deterministic failures skip the budget and
        go straight to bisection.
    backoff_base_s / backoff_max_s / jitter: exponential backoff for
        transient re-enqueues — base * 2^(attempts-1), capped, then
        stretched by up to `jitter` fraction (seeded; thundering-herd
        protection matters even inside one process when the device is
        the shared resource).
    bisect: poison isolation by batch bisection (see module docstring).
        False = a deterministic batch failure error-resolves everyone,
        exactly the pre-resilience behavior.
    nan_poison_threshold: how many non-finite outputs a key produces
        before it is quarantined. 1 (default): NaN coords are treated
        as a deterministic property of the input under fixed weights.
    watchdog_s: per-batch deadline on executor.run; None disables the
        watchdog. On expiry the executor is rebuilt and the batch is
        handled as a transient failure.
    breaker_threshold: consecutive transient/watchdog BATCH failures
        that flip the scheduler into degraded mode; 0 disables the
        circuit breaker.
    breaker_cooldown_s: open -> half-open delay.
    checkpoint_every: step-loop carry checkpointing (ISSUE 14; only
        meaningful under a RecyclePolicy). Every N recycles — and at
        every row-admission gap, where the host fetch is already
        paid — the scheduler snapshots the FoldStepState carry plus
        each row's age to host memory; a transient step failure or
        watchdog fire mid-loop then RESUMES the survivors at their
        checkpointed ages (executor rebuilt first when the watchdog
        fired) instead of requeueing everyone to recycle 0, bounding
        progress loss at `checkpoint_every` recycles per failure. A
        resume is byte-equal to the uninterrupted loop when the
        checkpoint sits at the failure step. 0 (default) disables
        checkpointing: every failure path is byte-for-byte the PR-5
        requeue-from-zero behavior.
    checkpoint_spill: durable checkpoint spill directory (ISSUE 18;
        "" disables — the default). With a path set, every host-memory
        carry checkpoint ALSO spills per-row npz payloads through
        `cache.checkpoints.CheckpointStore` to the disk tier keyed by
        (fold_key, model_tag, age) — written at the same
        `checkpoint_every` cadence (which must therefore be >= 1),
        pruned to the newest age, and discarded when the fold
        resolves. A restarted replica (boot discovery), a re-submitted
        duplicate (submit consult), or a failover peer (the
        `kind=checkpoint` peer route) then RESUMES the fold at its
        checkpointed age instead of refolding from recycle 0 —
        resume-at-age is byte-equal to the uninterrupted loop, per
        row, through PR 14's restore path. `Scheduler.drain()` spills
        every in-flight loop's current carry before exiting, so drain
        becomes checkpoint-and-hand-off (the preemptible/spot
        contract). "" keeps scrubbed serve_stats() and the registry
        metric-name set byte-identical to spill-off.
    row_isolation: per-row poison isolation in the step loop
        (ISSUE 14). A per-step non-finite scan retires ONLY the
        offending row the moment its output goes non-finite (strike
        toward `nan_poison_threshold` via the keyed Quarantine), and
        a deterministic failure that attributes itself to specific
        batch rows (`FaultInjected.rows` — content-addressed chaos
        does; real XLA errors do not) quarantines and retires exactly
        those rows while the survivors keep stepping uninterrupted —
        the freed rows refill via continuous admission like any early
        exit. Batch bisection stays the fallback for the opaque path
        and for unattributed deterministic failures. False (default)
        keeps the PR-5 whole-cohort behavior.
    transient_types / transient_markers: extra classification — any
        exception instance of a listed type, or whose repr contains a
        marker substring (case-insensitive), is treated as transient.
        `TransientExecutorError`/`WatchdogTimeout` always are.
    xla_classify: consult the `serve.xla_errors` payload classifier
        (ISSUE 20) when — and only when — the marker list has no
        opinion. Default-on but inert: every payload the legacy
        markers already decide keeps its exact legacy verdict; the
        classifier only adds verdicts on XLA/TPU shapes the flat list
        never matched (program aborts, CHECK failures, ABORTED slice
        halts). False restores the pure-marker classification.
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0
    bisect: bool = True
    nan_poison_threshold: int = 1
    watchdog_s: Optional[float] = None
    breaker_threshold: int = 0
    breaker_cooldown_s: float = 5.0
    checkpoint_every: int = 0
    checkpoint_spill: str = ""
    row_isolation: bool = False
    transient_types: Tuple[type, ...] = ()
    transient_markers: Tuple[str, ...] = (
        "transient", "resource_exhausted", "deadline_exceeded",
        "unavailable", "connection reset")
    xla_classify: bool = True
    _rng: random.Random = field(init=False, repr=False, compare=False,
                                default=None)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0 \
                or self.jitter < 0:
            raise ValueError("backoff/jitter must be >= 0")
        if self.nan_poison_threshold < 1:
            raise ValueError("nan_poison_threshold must be >= 1")
        if self.watchdog_s is not None and self.watchdog_s <= 0:
            # catch the CLI convention "0 = off" leaking in here: a
            # 0-second deadline would fail EVERY batch instantly
            raise ValueError("watchdog_s must be > 0 (None disables)")
        if self.checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0 (0 disables)")
        if self.checkpoint_spill and self.checkpoint_every < 1:
            raise ValueError(
                "checkpoint_spill rides the checkpoint cadence: set "
                "checkpoint_every >= 1 (the spill directory alone "
                "never checkpoints anything)")
        self._rng = random.Random(self.seed)

    def is_transient(self, exc: BaseException) -> bool:
        if isinstance(exc, TransientExecutorError):
            return True
        if self.transient_types and isinstance(exc, self.transient_types):
            return True
        r = repr(exc).lower()
        if any(m.lower() in r for m in self.transient_markers):
            return True
        if self.xla_classify:
            # XLA payload shapes the flat marker list never matched
            # (ISSUE 20) — consulted last so legacy verdicts are
            # untouched; no opinion falls through to the legacy False
            from alphafold2_tpu.serve.xla_errors import classify
            verdict = classify(repr(exc))
            if verdict is not None:
                return verdict.transient
        return False

    def delay_s(self, attempts: int,
                rng: Optional[random.Random] = None) -> float:
        """Backoff before re-enqueueing a batch whose entries have
        executed `attempts` times. The default jitter stream assumes a
        single caller thread; when one policy object is shared across
        schedulers (fleet.InProcessFleet passes the same `retry` to
        every replica), each worker passes its OWN seeded `rng` so the
        draws stay deterministic per worker instead of racing on one
        stream."""
        base = min(self.backoff_base_s * (2.0 ** max(0, attempts - 1)),
                   self.backoff_max_s)
        if self.jitter:
            base *= 1.0 + self.jitter * (rng or self._rng).random()
        return base


class Quarantine:
    """Keyed poison set: quarantined fold keys fail fast forever.

    Keys are the same content-addressed `fold_key` digests the cache
    uses, so quarantine naturally covers coalesced followers and every
    future duplicate of a poison request — one bad input costs the
    isolation executions once, then O(1) rejections. `strike()` is the
    accumulating path (non-finite outputs count toward poisoning);
    `add()` quarantines unconditionally (a deterministic batch-of-one
    failure IS the proof).

    `path` makes the set durable: every quarantined key appends one
    JSONL line ({"key", "reason"}) and construction replays the file,
    so a RESTARTED replica fails known poison fast instead of re-paying
    the isolation executions — the bisection proof survives the
    process. Append-only by design (quarantine has no remove path);
    strikes are deliberately NOT persisted — a sub-threshold NaN count
    is suspicion, not proof, and suspicion resets with the process.
    File trouble of any kind degrades to an in-memory-only set: the
    failure domain must never take down serving over a disk error.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 path: Optional[str] = None):
        self._lock = threading.Lock()
        self._keys: dict = {}            # key -> reason
        self._strikes: dict = {}
        self._path = path
        self._m_quarantined = (registry or get_registry()).counter(
            "serve_poison_quarantined_total",
            "fold keys quarantined as poison inputs")
        self.loaded = 0                  # keys replayed from disk
        if path:
            try:
                with open(path) as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                            self._keys[rec["key"]] = rec.get(
                                "reason", "poison_input")
                        except Exception:
                            continue     # torn tail line: skip, keep rest
                self.loaded = len(self._keys)
            except OSError:
                pass                     # no file yet / unreadable: empty

    def _persist(self, key: str, reason: str):
        """Caller does NOT hold the lock (file I/O off the hot section);
        append-only JSONL, one fsync-free line per quarantined key —
        a torn tail line is skipped at load, losing at most the last
        quarantine, which the next failure re-proves."""
        if not self._path:
            return
        try:
            d = os.path.dirname(os.path.abspath(self._path))
            os.makedirs(d, exist_ok=True)
            with open(self._path, "a") as fh:
                fh.write(json.dumps({"key": key, "reason": reason})
                         + "\n")
        except OSError:
            pass

    def add(self, key: str, reason: str = "poison_input") -> bool:
        """Quarantine `key`; True when newly added."""
        with self._lock:
            if key in self._keys:
                return False
            self._keys[key] = reason
            self._strikes.pop(key, None)
        self._m_quarantined.inc()
        self._persist(key, reason)
        return True

    def strike(self, key: str, threshold: int,
               reason: str = "nonfinite_output") -> bool:
        """Count one poison signal against `key`; quarantines (and
        returns True) when the key reaches `threshold` strikes."""
        with self._lock:
            if key in self._keys:
                return True
            n = self._strikes.get(key, 0) + 1
            if n < threshold:
                self._strikes[key] = n
                return False
            self._keys[key] = reason
            self._strikes.pop(key, None)
        self._m_quarantined.inc()
        self._persist(key, reason)
        return True

    def reason(self, key: str) -> Optional[str]:
        with self._lock:
            return self._keys.get(key)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._keys

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def snapshot(self) -> dict:
        with self._lock:
            return {"quarantined": len(self._keys),
                    "striked": len(self._strikes),
                    "loaded_from_disk": self.loaded,
                    "persisted": self._path is not None}


class CircuitBreaker:
    """closed -> open -> half-open -> {closed, open} batch-failure gate.

    Counts CONSECUTIVE transient/watchdog batch failures; at
    `failure_threshold` it opens (degraded mode: the scheduler fast-
    sheds novel submits and stops executing). After `cooldown_s` the
    next observation moves it to half-open, where exactly one probe
    batch may execute: success closes the breaker (full service),
    failure re-opens it for another cooldown. Deterministic failures
    and successful batches both count as proof of device health
    (`record_success`) — a poison input must not blow the breaker.

    Thread-safe; the execute-side methods are only ever called by the
    single scheduler worker, the submit-side by caller threads.
    """

    STATES = ("closed", "half_open", "open")

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.opens = 0
        self.closes = 0
        reg = registry or get_registry()
        self._m_state = reg.gauge(
            "serve_breaker_state",
            "scheduler circuit breaker: 0 closed, 1 half-open, 2 open")
        self._m_transitions = reg.counter(
            "serve_breaker_transitions_total",
            "breaker state transitions", ("to",))
        self._m_state.set(0)

    def _to(self, state: str):
        """Caller holds self._lock."""
        if state == self._state:
            return
        self._state = state
        self._m_state.set(self.STATES.index(state))
        self._m_transitions.inc(to=state)

    def _advance(self):
        """Caller holds self._lock: open + cooldown elapsed -> half-open."""
        if self._state == "open" \
                and self._clock() - self._opened_at >= self.cooldown_s:
            self._to("half_open")
            self._probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            self._advance()
            return self._state

    def allow_submit(self) -> bool:
        """False = degraded mode: fast-shed novel submits. Half-open
        admits submits — when the queue drained while open, the probe
        has to come from somewhere."""
        with self._lock:
            self._advance()
            return self._state != "open"

    def allow_execute(self) -> bool:
        """May the worker execute a batch right now? (No side effects —
        the probe slot is claimed separately via begin_probe, so a poll
        that finds nothing ready cannot leak the slot.)"""
        with self._lock:
            self._advance()
            if self._state == "closed":
                return True
            if self._state == "half_open":
                return not self._probe_inflight
            return False

    def begin_probe(self):
        """The worker committed to executing a batch while half-open."""
        with self._lock:
            if self._state == "half_open":
                self._probe_inflight = True

    def record_success(self):
        """Device proved healthy (batch completed, or failed
        deterministically — the device RAN it)."""
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != "closed":
                self.closes += 1
                self._to("closed")

    def record_failure(self):
        """One transient/watchdog batch failure."""
        with self._lock:
            self._advance()
            self._probe_inflight = False
            if self._state == "half_open" or (
                    self._state == "closed"
                    and self._failures + 1 >= self.failure_threshold):
                self.opens += 1
                self._opened_at = self._clock()
                self._failures = 0
                self._to("open")
            elif self._state == "closed":
                self._failures += 1

    def snapshot(self) -> dict:
        with self._lock:
            self._advance()
            return {"state": self._state,
                    "consecutive_failures": self._failures,
                    "opens": self.opens, "closes": self.closes,
                    "failure_threshold": self.failure_threshold,
                    "cooldown_s": self.cooldown_s}
