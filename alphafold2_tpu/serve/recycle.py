"""Recycle-aware iteration-level scheduling policy (ISSUE 9).

Until now a fold was an opaque unit of work: `predict.fold` runs every
recycle inside one `lax.scan`, so a fold that converged after recycle 1
still pays for all N, and a flagship batch holds its device slice
hostage until the last recycle finishes. ParaFold's workload analysis
(PAPERS.md) makes recycle count the dominant inference-throughput lever,
and the recycling loop is a natural scheduling quantum — the same
insight iteration-level LLM servers exploit between decode tokens.

`RecyclePolicy` makes the SCHEDULER own that loop. With
`Scheduler(recycle_policy=RecyclePolicy(...))` the executor compiles an
embed+first-pass executable plus a single-recycle step executable
(`FoldExecutor.run_init` / `run_step`; `predict.fold_init` /
`fold_step` are the underlying programs, one function with the scan
body, so step-loop numerics match the `lax.scan` path EXACTLY when no
early exit fires), and between steps the scheduler can:

- EARLY-EXIT converged elements: when an element's inter-recycle delta
  (max of mean-abs CA displacement over its real residues and max-abs
  confidence change) drops below `converge_tol`, its ticket resolves
  NOW with the current coords/confidence (`FoldResponse.recycles` says
  how many iterations it actually ran) and the survivor batch is
  re-packed; when every real element has converged the remaining steps
  are skipped entirely (`serve_recycles_skipped_total`);
- PREEMPT between recycles: tight-deadline traffic lands between a
  flagship batch's steps instead of behind its last recycle
  (`serve_preemptions_total`), so both traffic classes coexist on one
  fleet;
- STREAM progressive results: every step publishes a `FoldProgress`
  (coords + confidence + recycle index) to the caller's `FoldTicket`,
  and the fleet front door exposes the latest one on the existing
  long-poll (`GET /v1/result/<id>?progress=1` -> 206 + X-Recycle);
- ADMIT new work into freed rows (`continuous=True`, ISSUE 11): a row
  freed by early exit (or never filled at batch formation) is refilled
  mid-loop with a pending same-bucket request via a row-masked init
  program — the vLLM/Orca iteration-level pattern with recycles as our
  decode tokens; a saturated bucket's slice never idles a row
  (`serve_row_admissions_total`, `serve_rows_occupied_fraction`).

`converge_tol=0.0` (the default) disables early exit — every element
runs the full `num_recycles`, and because the step body IS the scan
body the served numerics are bit-identical to the opaque path. Only a
policy with `converge_tol > 0` can serve a result that differs from
the fixed-recycle fold, which is why the scheduler keys such results
under distinct cache keys (`RecyclePolicy.key_extras` feeds
`fold_key(extras=)` — an early-exited result can never be served to a
caller demanding full recycles).

`Scheduler(recycle_policy=None)` — the default — is byte-for-byte the
pre-ISSUE-9 behavior: one opaque `lax.scan` fold per batch, no step
executables, identical scrubbed `serve_stats()`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class RecyclePolicy:
    """How the scheduler drives the recycle loop.

    converge_tol: per-element convergence threshold on the
        inter-recycle delta (see `element_deltas`); an element retires
        as soon as its delta <= tol after at least `min_recycles`
        steps. 0.0 = never (early exit off; numerics match the opaque
        `lax.scan` fold exactly).
    min_recycles: floor on recycle iterations before early exit may
        fire (the embed pass is iteration 0 and never counts as a
        recycle). Only meaningful with converge_tol > 0.
    preempt: allow tight-deadline pending work to execute between this
        batch's recycles (it jumps the max_wait window — the whole
        point is jumping the queue). A preempting batch cannot itself
        be preempted, so preemption depth is bounded at 1.
    stream: publish per-recycle FoldProgress updates (coords +
        confidence) to each element's FoldTicket. Costs one host copy
        of the element's rows per step; off by default.
    continuous: continuous batching (ISSUE 11) — when early exit (or
        an under-filled batch) leaves rows free mid-loop, ADMIT new
        same-bucket pending requests into those rows between recycles
        via the row-masked init program (`predict.fold_init_rows` /
        `FoldExecutor.run_init_rows`) instead of padding until the
        batch's last survivor finishes: survivor rows keep stepping at
        their own recycle depth, each row carries its own iteration
        index, and a hot bucket's slice never idles a row. Admission
        pulls from the pending queue in deadline/priority order
        through the existing cache -> coalesce -> HBM-admission front
        (a store hit never burns a row; an in-flight duplicate parks
        as a coalescing follower), and it composes with preemption:
        urgent same-bucket folds claim freed rows first, without
        needing a batch gap. Off by default; continuous=False is
        byte-for-byte the PR-9/10 step-loop behavior (scrubbed
        serve_stats identity regression-pinned). Row-admitted results
        are row-independent through the model, so `continuous` never
        changes what is computed and does not split cache keys.
    cross_bucket: cross-bucket continuous batching (ISSUE 13; needs
        `continuous`) — when a host batch's freed rows outnumber its
        own bucket's pending queue, admit a pending request from a
        SHORTER bucket at the host batch's shape: the candidate is
        padded to the host bucket edge (the same per-row padding masks
        that already fold mixed lengths within a bucket), runs the
        row-masked init, and retires against its own age, byte-equal
        to folding the same request alone at the host shape. Every
        cross-bucket admit is PRICED by `serve.meshpolicy.
        AdmissionPricer`: padded step cost x the loop extension it
        causes vs the candidate's projected native-bucket queue delay,
        deadline urgency as a tiebreak, `cross_bucket_max_pad_frac` as
        the hard guard, and the HBM admission guard re-prices at the
        host shape. Off by default; cross_bucket=False is byte-for-
        byte the PR-11 same-bucket behavior (scrubbed serve_stats
        identity regression-pinned).
    cross_bucket_max_pad_frac: refuse a cross-bucket candidate whose
        pad fraction at the host edge (1 - length/host_edge) exceeds
        this — a 12-residue fold in a 512 host row is almost all
        padding, and no queue delay justifies it.
    eager_form: admission-aware batch formation (ISSUE 13; needs
        `continuous`) — when a bucket's queue is thin, form its batch
        IMMEDIATELY instead of waiting out max_wait_ms, counting on
        mid-loop row admission to top the under-filled batch up:
        max_wait becomes a fallback, not a latency floor. Off by
        default.
    """

    converge_tol: float = 0.0
    min_recycles: int = 0
    preempt: bool = True
    stream: bool = False
    continuous: bool = False
    cross_bucket: bool = False
    cross_bucket_max_pad_frac: float = 0.75
    eager_form: bool = False

    def __post_init__(self):
        if self.converge_tol < 0:
            raise ValueError(
                f"converge_tol must be >= 0, got {self.converge_tol}")
        if self.min_recycles < 0:
            raise ValueError(
                f"min_recycles must be >= 0, got {self.min_recycles}")
        if not 0.0 <= self.cross_bucket_max_pad_frac <= 1.0:
            raise ValueError(
                f"cross_bucket_max_pad_frac must be in [0, 1], got "
                f"{self.cross_bucket_max_pad_frac}")
        if self.cross_bucket and not self.continuous:
            raise ValueError(
                "cross_bucket admission rides the continuous batcher: "
                "RecyclePolicy(cross_bucket=True) needs continuous=True")
        if self.eager_form and not self.continuous:
            raise ValueError(
                "eager_form counts on mid-loop admission to top the "
                "under-filled batch up: RecyclePolicy(eager_form=True) "
                "needs continuous=True")

    def affects_results(self) -> bool:
        """True when this policy can serve a result that differs from
        the fixed-full-recycle fold — exactly the converge_tol > 0
        case. Preemption and streaming change WHEN work happens, never
        what is computed, so they do not split cache keys."""
        return self.converge_tol > 0.0

    def key_extras(self) -> Optional[tuple]:
        """Cache-key contribution (`cache.keys.fold_key(extras=)`).
        None when the policy cannot change results, so tol-0 /
        policy-off schedulers (and offline `fold_and_write` callers)
        keep sharing entries; a result-affecting policy keys under
        ("recycle_policy", tol, min_recycles) so an early-exited result
        is never served to a caller demanding full recycles."""
        if not self.affects_results():
            return None
        return ("recycle_policy", float(self.converge_tol),
                int(self.min_recycles))

    def snapshot(self) -> dict:
        return {"converge_tol": self.converge_tol,
                "min_recycles": self.min_recycles,
                "preempt": self.preempt,
                "stream": self.stream,
                "continuous": self.continuous,
                "cross_bucket": self.cross_bucket,
                "cross_bucket_max_pad_frac":
                    self.cross_bucket_max_pad_frac,
                "eager_form": self.eager_form}


def element_deltas(prev_coords: np.ndarray, prev_conf: np.ndarray,
                   coords: np.ndarray, conf: np.ndarray,
                   lengths: Sequence[int],
                   rows: Optional[Sequence[int]] = None) -> List[float]:
    """Per-element convergence signal between two consecutive recycle
    states: max(mean |Δcoords| over the element's real residues,
    max |Δconfidence|). Mean-abs displacement (not max) for coords so
    one flexible terminal residue cannot hold a converged core hostage;
    max for confidence because it is already per-residue bounded in
    [0, 1]. Padding rows/residues are excluded — they carry garbage
    that must not gate real elements. `rows` maps element position to
    its batch row (default: position == row — the dense-prefix case);
    the scheduler passes the live row map when retired rows stay in
    place (multi-chip leases skip physical repacking)."""
    out = []
    for i, n in enumerate(lengths):
        n = int(n)
        r = i if rows is None else int(rows[i])
        if n <= 0:
            out.append(0.0)
            continue
        dc = float(np.abs(coords[r, :n] - prev_coords[r, :n]).mean())
        df = float(np.abs(conf[r, :n] - prev_conf[r, :n]).max())
        out.append(max(dc, df))
    return out


def repack_rows(state, rows: Sequence[int], batch_size: int):
    """Gather survivor rows to the front of the carried FoldStepState
    (and return the same row order for the batch tensors): retired
    rows stop occupying live row slots, so the survivor batch stays a
    dense prefix and per-step host fetches/convergence bookkeeping
    slice `[:len(rows)]`. The batch shape is CLOSED (always padded to
    max_batch_size), so rows must be padded back to `batch_size`; the
    pad index repeats the last survivor — its output is never read.

    Device-side gather on the batch axis only: the pair/msa sharded
    axes are untouched, so the same repack works on a mesh-sharded
    carry (the caller decides whether to bother — see the scheduler's
    step loop)."""
    import jax
    import jax.numpy as jnp

    if not rows:
        raise ValueError("repack_rows needs at least one survivor")
    idx_list = list(rows) + [rows[-1]] * (batch_size - len(rows))
    idx = jnp.asarray(np.asarray(idx_list, np.int32))
    return jax.tree_util.tree_map(lambda x: jnp.take(x, idx, axis=0),
                                  state), idx_list


def repack_batch(batch: dict, idx_list: Sequence[int]) -> dict:
    """Re-pack the assembled batch tensors with the same row order
    `repack_rows` chose, so the step executable's inputs and its
    carried state stay row-aligned. Only the canonical input keys are
    carried over — auxiliary keys (e.g. the executor's cached device
    placement) are row-stale by definition and must be dropped."""
    import jax
    import jax.numpy as jnp

    idx = jnp.asarray(np.asarray(idx_list, np.int32))
    return {k: (None if batch[k] is None
                else jnp.take(batch[k], idx, axis=0))
            for k in ("seq", "mask", "msa", "msa_mask")}


def steps_saved(num_recycles: int, executed: int) -> int:
    """Batch-level recycle steps skipped by early exit."""
    return max(0, int(num_recycles) - int(executed))
