"""Preemption notices: turning a spot/TPU reclaim into a scheduled
migration (ISSUE 20).

A spot VM's death is announced — GCE flips the instance's `preempted`
metadata key and delivers ACPI shutdown (SIGTERM) roughly 30 seconds
before the hard power-off. The serving stack so far treats that window
as ordinary shutdown: `Scheduler.drain()` tries to FINISH every
in-flight fold, which under a 30 s notice silently loses any loop whose
remaining recycles don't fit. This module is the replica-side half of
making preemption first-class:

- `PreemptionNotice`: one immutable fact — "this process dies in
  `grace_s` seconds" — tagged with which source saw it;
- notice SOURCES, pluggable and polled: `MetadataNoticeSource` (the
  GCE metadata server's `instance/preempted` key, URL-overridable so a
  local HTTP stub tests the real code path), `SignalNoticeSource`
  (SIGTERM/ACPI — the notice every cloud delivers even when metadata
  is unreachable), and `FileNoticeSource` (a JSON file; the ProcFleet
  chaos verb and unit tests drive this one);
- `PreemptionWatcher`: one daemon thread polling every source; the
  FIRST notice wins (later ones are ignored — grace must never be
  extended by a duplicate announcement), flips the scheduler into
  reclaim mode via `Scheduler.preempt_notice(grace_s)`, and fires an
  optional `on_notice` callback exactly once (the process harness uses
  it to begin the grace-budgeted drain + manifest publish + exit).

Everything here is OFF unless a watcher is constructed and started —
no scheduler state, no metrics, no threads otherwise. The scheduler
side (reclaim mode, the grace-budgeted drain, the spill-over-finish
decision) lives in serve/scheduler.py; the fleet side (orphan manifest
adoption) in fleet/controlplane.py.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

# the documented default grace GCE gives a preempted spot VM; sources
# that learn only THAT preemption happened (metadata flag, SIGTERM)
# assume it, sources that carry their own budget (file notices) say so
DEFAULT_GRACE_S = 30.0

# GCE metadata server: "TRUE" once the instance has been preempted
METADATA_PREEMPTED_URL = ("http://metadata.google.internal/"
                          "computeMetadata/v1/instance/preempted")


@dataclass(frozen=True)
class PreemptionNotice:
    """One reclaim announcement: the process dies in `grace_s` seconds
    (measured from `received_s`, a monotonic stamp)."""

    source: str                    # "metadata" | "signal" | "file" | ...
    grace_s: float = DEFAULT_GRACE_S
    detail: str = ""
    received_s: float = field(default_factory=time.monotonic)

    @property
    def deadline_s(self) -> float:
        """Monotonic instant the hard kill lands."""
        return self.received_s + self.grace_s


class FileNoticeSource:
    """Notice-by-file: `poll()` reports a notice once `path` exists.
    The file may be empty (defaults apply) or hold a JSON object with
    optional `grace_s` / `detail` keys — exactly what the ProcFleet
    `preempt()` chaos verb writes. Unreadable/torn content still
    notices with the defaults: a half-written announcement of death is
    still an announcement of death."""

    name = "file"

    def __init__(self, path: str, grace_s: float = DEFAULT_GRACE_S):
        self.path = path
        self.grace_s = float(grace_s)

    def poll(self) -> Optional[PreemptionNotice]:
        if not os.path.exists(self.path):
            return None
        grace, detail = self.grace_s, ""
        try:
            with open(self.path) as fh:
                raw = fh.read().strip()
            if raw:
                rec = json.loads(raw)
                grace = float(rec.get("grace_s", grace))
                detail = str(rec.get("detail", ""))
        except Exception:
            pass
        return PreemptionNotice(source=self.name, grace_s=grace,
                                detail=detail or self.path)


class SignalNoticeSource:
    """Notice-by-signal (the ACPI shutdown path): `install()` hooks a
    signal handler that marks the flag; `poll()` reports it. The
    handler only sets a bool — everything slow (spill, manifest, exit)
    runs on the watcher thread, so the source is safe from the
    signal-handler context. `notify()` is the test/harness seam: the
    same flag without delivering a real signal."""

    name = "signal"

    def __init__(self, grace_s: float = DEFAULT_GRACE_S):
        self.grace_s = float(grace_s)
        self._fired = threading.Event()
        self._signum: Optional[int] = None
        self._prev_handler = None

    def install(self, signum: int = signal.SIGTERM) -> "SignalNoticeSource":
        """Hook `signum` (main thread only — signal.signal's rule).
        The previous handler is chained so a harness that ALSO wires
        SIGTERM to its stop event keeps working."""
        self._signum = signum
        self._prev_handler = signal.signal(signum, self._handle)
        return self

    def _handle(self, signum, frame):
        self._fired.set()
        prev = self._prev_handler
        if callable(prev):
            prev(signum, frame)

    def notify(self, detail: str = ""):
        self._fired.set()
        self._detail = detail

    def poll(self) -> Optional[PreemptionNotice]:
        if not self._fired.is_set():
            return None
        return PreemptionNotice(
            source=self.name, grace_s=self.grace_s,
            detail=getattr(self, "_detail", "")
            or (f"signal {self._signum}" if self._signum else "signal"))


class MetadataNoticeSource:
    """Notice-by-metadata: poll the GCE metadata server's
    `instance/preempted` key (body "TRUE" once the reclaim is
    scheduled). `url` is overridable so tests point it at a local HTTP
    stub and exercise the real request path; any transport trouble is
    simply 'no notice yet' — an unreachable metadata server must never
    preempt a healthy replica."""

    name = "metadata"

    def __init__(self, url: str = METADATA_PREEMPTED_URL,
                 grace_s: float = DEFAULT_GRACE_S,
                 timeout_s: float = 1.0):
        self.url = url
        self.grace_s = float(grace_s)
        self.timeout_s = float(timeout_s)

    def poll(self) -> Optional[PreemptionNotice]:
        import urllib.request
        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                body = resp.read(64).decode("utf-8", "replace").strip()
        except Exception:
            return None
        if body.upper() not in ("TRUE", "1", "PREEMPTED"):
            return None
        return PreemptionNotice(source=self.name, grace_s=self.grace_s,
                                detail=self.url)


class PreemptionWatcher:
    """Poll every source; on the FIRST notice, flip the scheduler into
    reclaim mode and fire `on_notice(notice)` once. The watcher is
    deliberately dumb — it never drains, spills, or exits; it only
    ANNOUNCES, and the owning process decides what the grace window
    buys (the ProcFleet replica runs the grace-budgeted drain and the
    manifest publish off this callback).

    scheduler: anything with `preempt_notice(grace_s)` (serve.Scheduler)
        or None — a watcher can drive a bare callback in tests.
    on_notice: called exactly once, on the watcher thread.
    poll_s: source polling cadence. A 30 s grace window makes
        sub-second polling pointless; 0.25 s keeps the chaos e2e fast.
    """

    def __init__(self, sources: List, scheduler=None,
                 on_notice: Optional[Callable] = None,
                 poll_s: float = 0.25):
        if not sources:
            raise ValueError("PreemptionWatcher needs >= 1 source")
        self.sources = list(sources)
        self.scheduler = scheduler
        self.on_notice = on_notice
        self.poll_s = float(poll_s)
        self.notice: Optional[PreemptionNotice] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "PreemptionWatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-preempt-watch")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- polling ---------------------------------------------------------

    def check(self) -> Optional[PreemptionNotice]:
        """One synchronous polling round (the thread calls this; tests
        call it directly to stay deterministic). Idempotent after the
        first notice."""
        if self.notice is not None:
            return self.notice
        for source in self.sources:
            try:
                notice = source.poll()
            except Exception:
                continue       # a broken source never kills the watch
            if notice is None:
                continue
            self.notice = notice
            self._announce(notice)
            return notice
        return None

    def _announce(self, notice: PreemptionNotice):
        if self.scheduler is not None:
            try:
                self.scheduler.preempt_notice(notice.grace_s,
                                              source=notice.source)
            except Exception:
                pass           # announcing must never crash the watch
        cb, self.on_notice = self.on_notice, None
        if cb is not None:
            try:
                cb(notice)
            except Exception:
                pass

    def _run(self):
        while not self._stop.is_set():
            if self.check() is not None:
                return         # announced: the watch is done
            self._stop.wait(self.poll_s)
