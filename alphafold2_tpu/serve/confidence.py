"""Confidence scoring for the speculative cascade (ISSUE 19).

The draft tier's accept/escalate decision has to come from signals the
model ALREADY emits — adding a dedicated confidence head would change
the draft architecture and its params, defeating the point of a cheap
tier. Two such signals exist in every `predict.FoldResult`:

- **predicted lDDT** — `FoldResult.confidence` is a per-residue score
  in [0, 1] (the serve path's `FoldResponse.confidence` array). Its
  mean is the classic pLDDT acceptance signal: HelixFold-style tiered
  serving accepts drafts whose own confidence clears a bar.
- **distogram entropy** — the distogram head's per-pair categorical
  over distance bins. A confident fold commits to narrow distance
  distributions; a confused one smears mass across bins. Mean
  per-pair entropy, normalized by log(bins), lands in [0, 1] where
  LOW is confident — the complement signal to pLDDT (a model can be
  pointwise confident but globally undecided).

Both scores are pure numpy over arrays the batch already produced, so
the gate costs microseconds against fold-seconds. The gate itself
(`ConfidenceGate`) is a tiny predicate object so `CascadePolicy` can
carry it as data and tests can exercise thresholds without a model.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = [
    "plddt_score",
    "distogram_entropy",
    "ConfidenceScore",
    "score_response",
    "ConfidenceGate",
]


def plddt_score(confidence, mask=None) -> float:
    """Mean predicted-lDDT over real residues, in [0, 1].

    confidence: per-residue scores, any shape (the serve path hands
    (n,) for one sequence, (b, n) for a batch). mask: optional same-
    shape 0/1 validity mask — padded rows of a bucketed batch must not
    dilute the mean. Raises ValueError on empty input (an empty fold
    has no confidence, and a silent 0.0 would always escalate while a
    silent 1.0 would always accept — neither is a decision this module
    should make).
    """
    conf = np.asarray(confidence, dtype=np.float64)
    if conf.size == 0:
        raise ValueError("plddt_score: empty confidence array")
    if mask is None:
        return float(conf.mean())
    m = np.asarray(mask, dtype=np.float64)
    if m.shape != conf.shape:
        raise ValueError(
            f"plddt_score: mask shape {m.shape} != confidence {conf.shape}")
    denom = m.sum()
    if denom <= 0:
        raise ValueError("plddt_score: mask selects no residues")
    return float((conf * m).sum() / denom)


def distogram_entropy(logits, mask=None) -> float:
    """Mean per-pair distogram entropy normalized to [0, 1].

    logits: (..., bins) raw distogram logits (predict.FoldResult
    .distogram is (b, n, n, bins)). Softmax is computed here in
    float64 with the max-subtraction trick — the serve path may hand
    bf16 logits and a naive exp overflows. mask: optional (...,) pair
    validity mask matching the leading shape. Normalization by
    log(bins) makes the score bucket-layout independent: 0 = every
    pair is a delta, 1 = every pair is uniform.
    """
    lg = np.asarray(logits, dtype=np.float64)
    if lg.ndim < 1 or lg.shape[-1] < 2:
        raise ValueError(
            f"distogram_entropy: need (..., bins>=2) logits, got {lg.shape}")
    lg = lg - lg.max(axis=-1, keepdims=True)
    p = np.exp(lg)
    p /= p.sum(axis=-1, keepdims=True)
    # x*log(x) -> 0 at x=0; clip keeps log finite without biasing the sum
    ent = -(p * np.log(np.clip(p, 1e-30, None))).sum(axis=-1)
    ent /= np.log(lg.shape[-1])
    if mask is None:
        return float(ent.mean())
    m = np.asarray(mask, dtype=np.float64)
    if m.shape != ent.shape:
        raise ValueError(
            f"distogram_entropy: mask shape {m.shape} != pairs {ent.shape}")
    denom = m.sum()
    if denom <= 0:
        raise ValueError("distogram_entropy: mask selects no pairs")
    return float((ent * m).sum() / denom)


@dataclass(frozen=True)
class ConfidenceScore:
    """One draft result's gate inputs. `entropy` is None when the
    serving path did not carry the distogram summary (the scheduler
    only computes it under SchedulerConfig(confidence_summary=True) —
    the distogram is batch-sized and never rides FoldResponse
    itself)."""

    plddt: float
    entropy: Optional[float] = None

    @property
    def score(self) -> float:
        """Single scalar for reporting: pLDDT penalized by entropy
        when present. Gates threshold the components, not this."""
        if self.entropy is None:
            return self.plddt
        return self.plddt * (1.0 - self.entropy)


def score_response(response) -> ConfidenceScore:
    """Score one ok FoldResponse from the draft tier. Reads the
    per-residue `confidence` array and, when the draft scheduler ran
    with confidence_summary, the precomputed `distogram_entropy`
    scalar."""
    if response.confidence is None:
        raise ValueError("score_response: response carries no confidence")
    return ConfidenceScore(
        plddt=plddt_score(response.confidence),
        entropy=getattr(response, "distogram_entropy", None))


@dataclass(frozen=True)
class ConfidenceGate:
    """Accept/escalate predicate over a ConfidenceScore.

    accept_plddt: minimum mean pLDDT to accept a draft. The 0.70
        default tracks the common "confident" band of lDDT-Ca
        calibration.
    max_entropy: optional ceiling on normalized distogram entropy;
        only consulted when the score carries one, so gates stay
        meaningful on drafts served without the distogram summary.
    """

    accept_plddt: float = 0.70
    max_entropy: Optional[float] = None

    def __post_init__(self):
        if not 0.0 <= self.accept_plddt <= 1.0:
            raise ValueError("accept_plddt must be in [0, 1]")
        if self.max_entropy is not None and not 0.0 <= self.max_entropy <= 1.0:
            raise ValueError("max_entropy must be in [0, 1]")

    def accepts(self, score: ConfidenceScore) -> bool:
        if score.plddt < self.accept_plddt:
            return False
        if (self.max_entropy is not None and score.entropy is not None
                and score.entropy > self.max_entropy):
            return False
        return True
