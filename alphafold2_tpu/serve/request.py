"""Serving request/response types.

The unit of work for `serve.Scheduler` is one sequence (plus optional
MSA), not a padded batch: batching, padding, and shape selection are the
server's job (bucketing.py / scheduler.py), so callers submit ragged
requests and get back exact-length results. Deadlines are wall-relative
at submit time and enforced by the scheduler (expired requests are shed,
not folded — ParaFold-style load shedding beats folding dead work).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

_req_counter = itertools.count()


def _next_request_id() -> str:
    return f"req-{next(_req_counter)}"


@dataclass
class FoldRequest:
    """One fold job: a token sequence, optional MSA, QoS knobs.

    seq: (n,) int tokens (featurize.tokenize output).
    msa: optional (m, n) int tokens; rows beyond the scheduler's view are
        padded/masked per batch, never truncated.
    priority: higher folds first when a batch is formed from a backlog.
    deadline_s: wall-clock budget from submit; past it the request is
        shed with status "shed" instead of occupying accelerator time.
    forwarded: this request already took its one fleet-routing hop
        (fleet.ConsistentHashRouter); the receiving scheduler serves it
        locally regardless of its own ring view, so divergent membership
        views can bounce a request once, never loop it.
    qos: "online" (the default — every pre-bulk caller, byte-for-byte
        the old behavior), "bulk" (lowest-QoS sweep work that rides
        the scheduler's BulkQueue, admitted only by work-stealing and
        throttled by online burn rate, ISSUE 18; ignored by schedulers
        constructed without a BulkPolicy), or "express" (interactive
        single-sequence traffic, ISSUE 19: rides the online queue —
        same admission, same shedding — but is tallied under its own
        metric/SLO class so tight-deadline traffic is observable and
        burn-rate-gated separately; the MSA bypass itself lives in
        serve.features, not here).
    """

    seq: np.ndarray
    msa: Optional[np.ndarray] = None
    request_id: str = field(default_factory=_next_request_id)
    priority: int = 0
    deadline_s: Optional[float] = None
    forwarded: bool = False
    qos: str = "online"

    def __post_init__(self):
        if self.qos not in ("online", "bulk", "express"):
            raise ValueError(
                f"FoldRequest.qos must be 'online', 'bulk' or 'express', "
                f"got {self.qos!r}")
        self.seq = np.asarray(self.seq, dtype=np.int32)
        if self.seq.ndim != 1:
            raise ValueError(
                f"FoldRequest.seq must be 1-D (n,), got {self.seq.shape}; "
                "the scheduler owns batching")
        if self.msa is not None:
            self.msa = np.asarray(self.msa, dtype=np.int32)
            if self.msa.ndim != 2 or self.msa.shape[1] != self.seq.shape[0]:
                raise ValueError(
                    f"FoldRequest.msa must be (m, {self.seq.shape[0]}), "
                    f"got {self.msa.shape}")

    @property
    def length(self) -> int:
        return int(self.seq.shape[0])


@dataclass
class FoldResponse:
    """Result of one FoldRequest, unpadded back to the request length.

    status: "ok" | "shed" (deadline expired before folding) |
            "error" (executor raised, retries exhausted, or the output
            failed validation; see .error) |
            "cancelled" (scheduler stopped without draining) |
            "degraded" (circuit breaker open: novel fold fast-shed at
            submit while the scheduler recovers) |
            "poisoned" (the request's content key is quarantined as a
            poison input — it failed deterministically in isolation or
            produced non-finite output; duplicates fail fast forever) |
            "too_large" (mesh-aware scheduler only: the analytic HBM
            footprint exceeds the largest configured device slice, so
            the fold is rejected at submit instead of OOMing mid-batch) |
            "preempted" (the replica received a spot-reclaim notice and
            spilled this fold's mid-loop checkpoint instead of finishing
            it; resubmit anywhere — a survivor resumes from the spilled
            recycle, or the controller adopts it automatically).
    source: how the result was obtained — "fold" (ran on the
            accelerator), "cache" (content-addressed result store hit),
            "coalesced" (attached to an identical in-flight fold; for
            non-ok statuses this marks leader-state propagation),
            "forwarded" (routed to its fleet owner replica, which
            folded/served it; the local process never touched the
            accelerator for it).
    attempts: executor batch executions this request participated in
            (> 1 iff a RetryPolicy re-enqueued or bisected its batch;
            stays at the default 1 for results that never had to
            retry — including cache/coalesced/shed resolutions).
    """

    request_id: str
    status: str
    coords: Optional[np.ndarray] = None       # (n, 3) CA trace
    confidence: Optional[np.ndarray] = None   # (n,) in [0, 1]
    bucket_len: Optional[int] = None
    latency_s: Optional[float] = None
    error: Optional[str] = None
    source: str = "fold"
    attempts: int = 1
    # recycle iterations actually executed for this result (step-mode
    # scheduling only — serve.recycle.RecyclePolicy; None everywhere
    # else, including cache/coalesced/forwarded serves and the classic
    # opaque-fold path). With early exit this can be < the configured
    # num_recycles: the element converged and skipped the rest.
    recycles: Optional[int] = None
    # cascade provenance (ISSUE 19) — defaults are the non-cascade
    # values, so every pre-cascade serving path is byte-identical.
    # tier: "" outside a cascade; "draft" when a draft-tier result was
    #       accepted by the confidence gate, "flagship" when the
    #       flagship tier produced/served it under a cascade.
    # escalated: the draft result failed the gate (or errored) and
    #       this response came from the flagship escalation.
    # confidence_score: the gate's scalar (ConfidenceScore.score) for
    #       cascade-served results; None everywhere else.
    tier: str = ""
    escalated: bool = False
    confidence_score: Optional[float] = None
    # mean normalized distogram entropy, computed at batch finish only
    # under SchedulerConfig(confidence_summary=True) — the distogram
    # itself is (n, n, bins) and never rides a response
    distogram_entropy: Optional[float] = None

    @property
    def ok(self) -> bool:
        return self.status == "ok"


@dataclass
class FoldProgress:
    """One progressive (per-recycle) result published to a FoldTicket
    by the step-mode scheduler (RecyclePolicy(stream=True)): the
    element's coords + confidence after `recycle` iterations
    (0 = the embed/first pass). `converged` marks the update that
    retired the element early — its terminal FoldResponse carries the
    same arrays."""

    request_id: str
    recycle: int
    coords: np.ndarray                # (n, 3), unpadded
    confidence: np.ndarray            # (n,)
    converged: bool = False


class FoldTicket:
    """Future handed back by Scheduler.submit(); resolves to FoldResponse."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._event = threading.Event()
        self._response: Optional[FoldResponse] = None
        self._lock = threading.Lock()
        self._callbacks: list = []
        self._progress: list = []           # FoldProgress, oldest first
        self._progress_callbacks: list = []
        # optional hook fired (best-effort, once per expiry) when
        # result(timeout=) gives up on this ticket — fleet transports
        # use it to send the remote owner a cancel so a caller that
        # stopped waiting does not leave a parked result behind
        # (fleet.rpc.HttpTransport; counted fleet_remote_cancels_total)
        self._timeout_callback = None

    def _resolve(self, response: FoldResponse):
        self._response = response
        self._event.set()
        with self._lock:
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb(response)
            except Exception:
                pass          # a broken observer never blocks resolution

    def add_done_callback(self, fn):
        """Run `fn(response)` when (or immediately if) this ticket
        resolves. Callbacks run on the resolving thread (the scheduler
        worker for folded requests) — keep them short and never let
        them block; exceptions are swallowed. This is the chaining seam
        fleet forwarding uses: a local ticket resolves off the remote
        replica's ticket without parking a waiter thread per request."""
        run_now = False
        with self._lock:
            if self._event.is_set():
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            try:
                fn(self._response)
            except Exception:
                pass

    def _publish_progress(self, progress: FoldProgress):
        """Step-mode scheduler hook: record one per-recycle progressive
        result and fan it out to progress observers. Runs on the
        executing thread between recycles — observers must be short and
        never block; their exceptions are swallowed like done-callback
        ones."""
        with self._lock:
            self._progress.append(progress)
            callbacks = list(self._progress_callbacks)
        for cb in callbacks:
            try:
                cb(progress)
            except Exception:
                pass

    def add_progress_callback(self, fn):
        """Run `fn(FoldProgress)` for every progressive update,
        including (immediately) any already published."""
        with self._lock:
            backlog = list(self._progress)
            self._progress_callbacks.append(fn)
        for p in backlog:
            try:
                fn(p)
            except Exception:
                pass

    def progress(self) -> list:
        """All progressive updates published so far, oldest first
        (empty unless the scheduler runs RecyclePolicy(stream=True))."""
        with self._lock:
            return list(self._progress)

    def latest_progress(self) -> Optional[FoldProgress]:
        with self._lock:
            return self._progress[-1] if self._progress else None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> FoldResponse:
        if not self._event.wait(timeout):
            cb = self._timeout_callback
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass    # cancel is advisory; the timeout still raises
            raise TimeoutError(
                f"FoldTicket.result timed out for {self.request_id}")
        assert self._response is not None
        return self._response
