"""Classify XLA/TPU error payloads: transient vs deterministic, with
best-effort per-row attribution (ISSUE 20, ROADMAP open item).

The retry stack so far classifies failures with a flat marker list
(`RetryPolicy.transient_markers`: substring match over `repr(exc)`).
That works for the gRPC-style status prefixes JAX surfaces
(RESOURCE_EXHAUSTED, DEADLINE_EXCEEDED, UNAVAILABLE) but has no opinion
on the rest of the zoo a real TPU serving fleet sees — program aborts,
`Check failed:` CHECK crashes, TPU halt messages, compile-time
INVALID_ARGUMENTs — and it can never attribute a failure to specific
batch rows, so every opaque deterministic failure pays the full batch
bisection.

This module is a pure-function parser over the error PAYLOAD STRING
(`repr(exc)` or a captured log line); it imports nothing heavy and
raises never. Three verdicts:

- transient: worth retrying in place (capacity/queueing trouble —
  RESOURCE_EXHAUSTED allocation failures, ABORTED slice halts from a
  maintenance event, transport resets);
- deterministic: retrying the same bytes reproduces it (shape/dtype
  INVALID_ARGUMENT, FAILED_PRECONDITION, CHECK failures, program
  aborts, non-finite detections) — the batch-bisection / row-isolation
  path should run instead of the retry loop;
- no opinion (`classify` returns None): the payload matches no known
  shape; the caller keeps its legacy default.

Row attribution: many XLA/runtime messages name the offending batch
position ("batch index 3", "row=2", "at batch row 5: non-finite").
`attributed_rows` extracts them so the scheduler's existing
`FaultInjected.rows`-style isolation path (quarantine + retire exactly
those rows, survivors keep stepping) works on REAL errors, not just
injected ones.

Wiring (default-on but inert): `RetryPolicy.is_transient` consults
`classify` only AFTER the legacy marker list has no opinion, so every
payload the markers already decide keeps its exact legacy verdict; and
`Scheduler._isolate_poison_rows` falls back to `attributed_rows` only
when the exception carries no explicit `.rows`. With neither novel
payloads nor row_isolation in play, behavior and stats are
byte-identical.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional, Tuple

# -- payload shapes ------------------------------------------------------

# transient: capacity or infrastructure trouble — the same bytes may
# well succeed on retry (in place or elsewhere). Ordered: first match
# wins, so more specific shapes precede generic status codes.
_TRANSIENT_SHAPES: Tuple[Tuple[str, str], ...] = (
    (r"resource[_ ]exhausted", "resource_exhausted"),
    (r"out of memory allocating", "hbm_oom"),
    (r"failed to allocate request", "hbm_oom"),
    (r"deadline[_ ]exceeded", "deadline_exceeded"),
    (r"\bunavailable\b", "unavailable"),
    (r"\baborted\b", "aborted"),
    (r"connection reset", "connection_reset"),
    (r"socket closed", "connection_reset"),
    (r"tpu.{0,40}(?:maintenance|terminated|preempt)", "tpu_reclaim"),
    (r"slice health", "slice_health"),
)

# deterministic: the program or its inputs are wrong — retrying the
# same batch reproduces the failure; isolation/bisection should run.
_DETERMINISTIC_SHAPES: Tuple[Tuple[str, str], ...] = (
    (r"invalid[_ ]argument", "invalid_argument"),
    (r"failed[_ ]precondition", "failed_precondition"),
    (r"out[_ ]of[_ ]range", "out_of_range"),
    (r"unimplemented", "unimplemented"),
    (r"check failed", "check_failed"),
    (r"program (?:abort|halt)", "program_abort"),
    (r"tpu program (?:abort|halt)", "program_abort"),
    (r"core halted", "program_abort"),
    (r"halt(?:ed|ing)? unexpectedly", "program_abort"),
    (r"\bnan\b|non-?finite", "non_finite"),
    (r"internal: .{0,80}(?:hlo|xla)", "xla_internal"),
)

# row attribution: "batch index 3", "batch row 5", "row=2", "row: 7"
_ROW_RE = re.compile(
    r"(?:batch(?:\s+index|\s+row)?|row)[ =:]+(\d+)", re.IGNORECASE)


@dataclass(frozen=True)
class XlaErrorClass:
    """One classified payload: retryable or not, why, and (best-effort)
    which batch rows the runtime blamed."""

    transient: bool
    reason: str
    rows: Tuple[int, ...] = ()


def attributed_rows(payload: str) -> Tuple[int, ...]:
    """Batch rows the payload names, sorted and deduplicated; () when
    the message attributes nothing (most real XLA errors)."""
    try:
        return tuple(sorted({int(m) for m in _ROW_RE.findall(payload)}))
    except Exception:
        return ()


def classify(payload: str) -> Optional[XlaErrorClass]:
    """Classify one error payload string; None = no opinion (caller
    keeps its legacy default). Never raises."""
    try:
        low = payload.lower()
    except Exception:
        return None
    for pattern, reason in _TRANSIENT_SHAPES:
        if re.search(pattern, low):
            return XlaErrorClass(transient=True, reason=reason,
                                 rows=attributed_rows(payload))
    for pattern, reason in _DETERMINISTIC_SHAPES:
        if re.search(pattern, low):
            return XlaErrorClass(transient=False, reason=reason,
                                 rows=attributed_rows(payload))
    return None
