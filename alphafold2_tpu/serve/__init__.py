"""alphafold2_tpu.serve — length-bucketed batching inference server.

The serving stack, bottom-up:

- request:   FoldRequest/FoldResponse/FoldTicket — ragged in, exact out
- features:  FeaturePool/PipelineScheduler — the two-stage pipeline
             front: RAW jobs (strings + raw MSA) featurize on a CPU
             worker pool with their own cache tier (cache.FeatureCache,
             feature_key upstream of fold_key) + in-flight coalescing,
             then feed the fold queue (README "Feature pipeline")
- bucketing: BucketPolicy — ragged lengths onto a closed shape set
- executor:  FoldExecutor — LRU cache of compiled fold executables
- scheduler: Scheduler — dynamic batching, deadlines, backpressure,
             optional result cache + in-flight coalescing
- metrics:   ServeMetrics — counters, padding waste, latency tails, JSONL
             KeyFrequencyLog — served-key frequencies in the
             cache_warm profile format (`Scheduler(key_log=)`)
             (all mirrored into the process-wide obs.MetricsRegistry;
             pass `Scheduler(..., tracer=obs.Tracer(...))` for
             request-scoped traces — README "Observability")
- meshpolicy: MeshPolicy/FoldMemoryModel/DeviceSliceAllocator — pass
             `Scheduler(..., mesh_policy=MeshPolicy.from_model(...))`
             for multi-chip serving: per-bucket device slices (short
             folds single-chip, long folds pair-sharded over a
             `parallel.mesh`), concurrent disjoint-slice execution, and
             the analytic-HBM admission guard (README "Multi-chip
             serving")
- kernelpolicy: KernelPolicy — pass `Scheduler(kernel_policy=
             KernelPolicy.from_buckets(...))` and each length bucket
             routes onto its own attention kernel: short buckets dense,
             long buckets the block-skipping Pallas kernel
             (ops/block_sparse.py), with optional per-target
             contact-prior masks re-planned from recycle-1 pair
             activations (README "Kernel selection")
- recycle:   RecyclePolicy — pass `Scheduler(recycle_policy=
             RecyclePolicy(converge_tol=...))` and the scheduler owns
             the recycle loop: early-exit converged folds, preempt
             between recycles for deadline traffic, stream per-recycle
             progressive results, and — with `continuous=True` —
             refill freed rows mid-loop with pending requests via the
             row-masked init program, so a hot bucket's slice never
             idles a row; `cross_bucket=True` additionally lets a
             freed row serve a SHORTER bucket's pending fold at the
             host shape (priced per admit by meshpolicy's
             AdmissionPricer) and `eager_form=True` launches thin
             queues' batches immediately, counting on admission to
             top them up (README "Iteration-level scheduling" /
             "Continuous batching")
- cascade:   CascadePolicy/build_draft_scheduler + confidence:
             ConfidenceGate/score_response — pass `Scheduler(cascade=
             CascadePolicy(draft=build_draft_scheduler(...)))` and
             interactive submits fold on a small draft tier first; a
             confidence gate (mean pLDDT, optional distogram entropy)
             accepts the draft or escalates to the flagship through
             the ordinary submit seam. `qos="express"` +
             `FeaturePool(express=StubEmbedder())` adds the MSA-free
             express lane with its own metric/SLO class (README
             "Model cascade & express lane")
- resilience: RetryPolicy/CircuitBreaker/Quarantine — pass
             `Scheduler(..., retry=RetryPolicy(...))` for transient-
             batch retry, poison isolation by bisection + quarantine,
             non-finite output validation, the executor watchdog, and
             degraded mode (README "Failure handling & degraded mode")
- preemption: PreemptionWatcher + notice sources (metadata/signal/
             file) — spot reclaim as a scheduled migration: the notice
             flips the scheduler into reclaim mode, `drain(grace_s=)`
             spills every in-flight loop it cannot finish, and the
             checkpoint store publishes an orphan manifest the fleet
             controller adopts onto survivors (README "Spot &
             preemptible serving")
- xla_errors: classify/attributed_rows — pure-function XLA/TPU error
             payload parser: transient-vs-deterministic verdicts plus
             best-effort per-row attribution feeding the row-isolation
             path; consulted by RetryPolicy only where the legacy
             marker list has no opinion
- faults:    FaultPlan — seeded chaos injection threaded through
             FoldExecutor / FoldCache / fleet.PeerCacheClient behind
             no-op defaults (tools/serve_loadtest.py --chaos)

`FoldCache` (re-exported from alphafold2_tpu.cache) makes the server
content-addressed: pass `Scheduler(..., cache=FoldCache(...),
model_tag=...)` and duplicate requests are served from the store or
coalesced onto the in-flight fold instead of re-folding (README
"Result cache & deduplication"). Off by default.

Minimal use (see README "Serving"):

    from alphafold2_tpu import serve
    executor = serve.FoldExecutor(model, params)
    sched = serve.Scheduler(executor, serve.BucketPolicy((64, 128, 256)),
                            serve.SchedulerConfig(msa_depth=5),
                            cache=serve.FoldCache(),
                            model_tag="demo@params-v1")
    with sched:
        sched.warmup()
        ticket = sched.submit(serve.FoldRequest(seq_tokens, msa=msa_tokens))
        response = ticket.result(timeout=120)
"""

from alphafold2_tpu.cache import (FeatureCache, FoldCache,  # noqa: F401
                                  feature_key, fold_key)
from alphafold2_tpu.obs import (MetricsRegistry, Tracer,  # noqa: F401
                                get_registry, prometheus_text)
from alphafold2_tpu.serve.bucketing import BucketPolicy, default_policy  # noqa: F401
from alphafold2_tpu.serve.bulk import BulkPolicy, BulkQueue  # noqa: F401
from alphafold2_tpu.serve.cascade import (CascadePolicy,  # noqa: F401
                                          build_draft_scheduler)
from alphafold2_tpu.serve.confidence import (ConfidenceGate,  # noqa: F401
                                             ConfidenceScore,
                                             distogram_entropy,
                                             plddt_score, score_response)
from alphafold2_tpu.serve.executor import FoldExecutor  # noqa: F401
from alphafold2_tpu.serve.faults import FaultInjected, FaultPlan  # noqa: F401
from alphafold2_tpu.serve.features import (FeaturePool,  # noqa: F401
                                           PipelineScheduler,
                                           RawFoldRequest, StubEmbedder,
                                           express_featurize,
                                           featurize_raw,
                                           featurizer_config_digest)
from alphafold2_tpu.ops.block_sparse import KernelSpec  # noqa: F401
from alphafold2_tpu.serve.kernelpolicy import KernelPolicy  # noqa: F401
from alphafold2_tpu.serve.meshpolicy import (AdmissionDecision,  # noqa: F401
                                             AdmissionPricer,
                                             DeviceSliceAllocator,
                                             FoldMemoryModel, MeshPolicy,
                                             SliceLease)
from alphafold2_tpu.serve.metrics import (KeyFrequencyLog,  # noqa: F401
                                          ServeMetrics)
from alphafold2_tpu.serve.preemption import (FileNoticeSource,  # noqa: F401
                                             MetadataNoticeSource,
                                             PreemptionNotice,
                                             PreemptionWatcher,
                                             SignalNoticeSource)
from alphafold2_tpu.serve.recycle import RecyclePolicy  # noqa: F401
from alphafold2_tpu.serve.request import (FoldProgress, FoldRequest,  # noqa: F401
                                          FoldResponse, FoldTicket)
from alphafold2_tpu.serve.resilience import (CircuitBreaker,  # noqa: F401
                                             Quarantine, RetryPolicy,
                                             TransientExecutorError,
                                             WatchdogTimeout)
from alphafold2_tpu.serve.scheduler import (DrainingError,  # noqa: F401
                                            QueueFullError, Scheduler,
                                            SchedulerConfig)
from alphafold2_tpu.serve.xla_errors import (XlaErrorClass,  # noqa: F401
                                             attributed_rows,
                                             classify)
