"""alphafold2_tpu.serve — length-bucketed batching inference server.

The serving stack, bottom-up:

- request:   FoldRequest/FoldResponse/FoldTicket — ragged in, exact out
- bucketing: BucketPolicy — ragged lengths onto a closed shape set
- executor:  FoldExecutor — LRU cache of compiled fold executables
- scheduler: Scheduler — dynamic batching, deadlines, backpressure
- metrics:   ServeMetrics — counters, padding waste, latency tails, JSONL

Minimal use (see README "Serving"):

    from alphafold2_tpu import serve
    executor = serve.FoldExecutor(model, params)
    sched = serve.Scheduler(executor, serve.BucketPolicy((64, 128, 256)),
                            serve.SchedulerConfig(msa_depth=5))
    with sched:
        sched.warmup()
        ticket = sched.submit(serve.FoldRequest(seq_tokens, msa=msa_tokens))
        response = ticket.result(timeout=120)
"""

from alphafold2_tpu.serve.bucketing import BucketPolicy, default_policy  # noqa: F401
from alphafold2_tpu.serve.executor import FoldExecutor  # noqa: F401
from alphafold2_tpu.serve.metrics import ServeMetrics  # noqa: F401
from alphafold2_tpu.serve.request import (FoldRequest, FoldResponse,  # noqa: F401
                                          FoldTicket)
from alphafold2_tpu.serve.scheduler import (QueueFullError, Scheduler,  # noqa: F401
                                            SchedulerConfig)
