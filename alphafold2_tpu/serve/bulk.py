"""The bulk tier: proteome-scale sweep folding as a background QoS
class (ISSUE 18).

ParaFold folded 19,704 proteins in one batch campaign. Serving that
kind of backfill on the same fleet as latency-bound traffic needs a
QoS class that is structurally incapable of hurting the online
classes, not one that merely sorts behind them in a shared queue:

- **Own queue, own bound.** `qos="bulk"` submissions land in a
  `BulkQueue`, never in the scheduler's `_incoming`/`_pending`: bulk
  backlog cannot push the online queue into its full policy, cannot
  trip queue-depth alerts, and is bounded by `BulkPolicy.max_pending`
  on its own.
- **Work-stealing admission only.** Bulk folds ride freed batch rows
  through the PR 11/13 continuous-admission front, taken ONLY after
  every online candidate (same-bucket and cross-bucket) came up
  empty. A bulk batch may be FOUNDED only when no online work is
  pending anywhere — an all-bulk fleet folds at full throughput, a
  busy one contributes exactly its idle row-steps.
- **Burn-rate throttling.** The PR 15 SLO engine's own report gates
  the tier: when any online class's latency burn rate crosses
  `BulkPolicy.max_burn`, new bulk admits stop, and in-flight bulk
  rows checkpoint-and-yield at the next admission gap — spill to the
  durable `cache.checkpoints.CheckpointStore` and requeue as
  resumable (`Scheduler._yield_bulk_rows`), freeing their rows for
  the online work that is burning budget. Without a spill store
  (`RetryPolicy(checkpoint_spill=)` off) a yield would refold from
  zero, so bulk rows run to completion instead and only NEW admits
  gate.

Campaign tooling (`tools/bulk_submit.py`) layers the durable ledger
and idempotent re-runs on top; this module is just the queue and the
policy knobs. The queue stores scheduler entries opaquely — it never
imports the scheduler.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class BulkPolicy:
    """Knobs for the bulk tier (Scheduler(bulk=...)).

    max_burn: online latency burn-rate ceiling — above it, bulk
        admission gates and in-flight bulk rows checkpoint-and-yield.
        1.0 means "gating starts exactly when any online class starts
        spending error budget faster than it accrues". Only
        meaningful with an SLOEngine attached (no engine, no burn
        signal, no gating).
    max_pending: bulk queue bound; submits past it raise
        QueueFullError (campaign drivers throttle on it).
    check_interval_s: how long one SLO report's burn verdict is
        cached — report() walks registry histograms, so the steal
        path must not pay it per freed row.
    """

    max_burn: float = 1.0
    max_pending: int = 10000
    check_interval_s: float = 1.0

    def __post_init__(self):
        if self.max_burn <= 0:
            raise ValueError("max_burn must be > 0")
        if self.max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if self.check_interval_s < 0:
            raise ValueError("check_interval_s must be >= 0")


class BulkQueue:
    """Thread-safe per-bucket FIFO of bulk entries. Items are opaque
    (the scheduler stores its `_Entry`s); ordering is FIFO per bucket
    with `push_front` for yielded loops — a resumable fold goes back
    to the head so its spilled checkpoint is consumed before it ages
    out, not behind the whole campaign."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Dict[int, deque] = {}
        self._n = 0

    def push(self, bucket_len: int, item) -> None:
        with self._lock:
            self._pending.setdefault(int(bucket_len), deque()).append(item)
            self._n += 1

    def push_front(self, bucket_len: int, item) -> None:
        with self._lock:
            self._pending.setdefault(int(bucket_len),
                                     deque()).appendleft(item)
            self._n += 1

    def take(self, bucket_len: int):
        """Pop the bucket's head, or None."""
        with self._lock:
            q = self._pending.get(int(bucket_len))
            if not q:
                return None
            self._n -= 1
            return q.popleft()

    def buckets(self) -> List[int]:
        """Non-empty buckets, oldest head first (insertion order is
        FIFO, so the head of each deque is its oldest) — founding
        drains the longest-waiting campaign slice first. Ties and
        opaque items degrade to bucket order."""
        with self._lock:
            entries = [(b, q[0]) for b, q in self._pending.items() if q]

        def age_key(pair):
            b, head = pair
            return (getattr(head, "enqueued_at", 0.0), b)

        return [b for b, _ in sorted(entries, key=age_key)]

    def pending_for(self, bucket_len: int) -> int:
        with self._lock:
            q = self._pending.get(int(bucket_len))
            return len(q) if q else 0

    def drain(self) -> list:
        """Remove and return everything (stop/crash paths: every
        ticket still owed a terminal state)."""
        with self._lock:
            out = []
            for q in self._pending.values():
                out.extend(q)
                q.clear()
            self._n = 0
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"pending": self._n,
                    "buckets": {b: len(q)
                                for b, q in sorted(self._pending.items())
                                if q}}

    def __len__(self) -> int:
        with self._lock:
            return self._n
