"""Compiled-executable cache around `predict.fold`.

One compiled executable per (bucket_len, batch_size, msa_depth,
num_recycles) key: because the scheduler feeds each key exactly one
shape signature, the executor compiles ahead-of-time
(`jax.jit(...).lower(args).compile()`) and caches the resulting
`Compiled` object — so LRU-evicting a key actually frees its executable
(a single shared jit fn would pin every shape it ever saw in its
internal cache — no eviction handle), and compilation is a separately
observable phase: `run(..., trace=)` records a `compile` span only when
a key is built fresh and a `fold` span for the device execution, which
is how a request trace attributes XLA time vs accelerator time
(obs/trace.py). On TPU the executables for big buckets are HBM-heavy;
`max_entries` bounds the resident set and `warmup()` pre-pays compiles
before traffic arrives instead of on the first unlucky request.

`stats()` exposes hits/misses/evictions; misses == distinct XLA
compilations triggered through this executor, the number the e2e test
pins to the bucket count.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterable, Tuple

import jax
import jax.numpy as jnp

from alphafold2_tpu.obs.trace import NULL_TRACE
from alphafold2_tpu.predict import FoldResult, fold
from alphafold2_tpu.serve.bucketing import msa_depth_of

# (bucket_len, batch_size, msa_depth, num_recycles)
ExecKey = Tuple[int, int, int, int]


class FoldExecutor:
    """LRU cache of compiled fold executables, keyed by shape signature.

    faults: optional serve.faults.FaultPlan — chaos-injection hook
        (exceptions / latency spikes before the device call, NaN
        mutation after); None (default) costs nothing on the hot path.
    """

    def __init__(self, model, params, max_entries: int = 8, faults=None):
        assert model.predict_coords, "serving needs predict_coords=True"
        self.model = model
        self.params = params
        self.max_entries = max(1, int(max_entries))
        self.faults = faults
        self._cache: "OrderedDict[ExecKey, callable]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def rebuild(self) -> "FoldExecutor":
        """Fresh executor over the same (model, params): empty
        executable cache, zeroed counters. The scheduler's watchdog
        swaps a hung executor for this — compiled state owned by a
        wedged device call is not trustworthy, and the zombie watchdog
        thread keeps the OLD instance alive until it dies, so its late
        result can never land in the serving path."""
        return FoldExecutor(self.model, self.params,
                            max_entries=self.max_entries,
                            faults=self.faults)

    def _build(self, num_recycles: int):
        def run(params, seq, mask, msa, msa_mask) -> FoldResult:
            return fold(self.model, params, seq, msa=msa, mask=mask,
                        msa_mask=msa_mask, num_recycles=num_recycles)

        return jax.jit(run)

    def _compile(self, key: ExecKey, args):
        """AOT-compile the key's executable OUTSIDE the cache lock (an
        XLA compile can take seconds; holding the lock would stall
        concurrent hit lookups) and insert it. Falls back to the lazily
        compiling jitted callable on JAX versions/paths where AOT
        lowering refuses the argument structure."""
        jitted = self._build(key[3])
        try:
            fn = jitted.lower(*args).compile()
        except Exception:
            fn = jitted          # first call will compile lazily
        with self._lock:
            self.misses += 1
            existing = self._cache.get(key)
            if existing is not None:
                # raced with another compiler of the same key: keep the
                # resident one (both are valid; counters stay honest)
                self._cache.move_to_end(key)
                return existing
            self._cache[key] = fn
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)
                self.evictions += 1
        return fn

    def _lookup(self, key: ExecKey):
        with self._lock:
            fn = self._cache.get(key)
            if fn is not None:
                self.hits += 1
                self._cache.move_to_end(key)
            return fn

    def key_for(self, batch: dict, num_recycles: int) -> ExecKey:
        b, n = batch["seq"].shape
        return (int(n), int(b), msa_depth_of(batch), int(num_recycles))

    def run(self, batch: dict, num_recycles: int,
            trace=NULL_TRACE) -> FoldResult:
        """Fold one assembled batch; blocks until device results land so
        the caller's latency measurement is honest. `trace` (a Trace /
        MultiTrace; NULL_TRACE default is zero-cost) gets a `compile`
        span when this signature is built fresh and a `fold` span for
        the execution itself."""
        key = self.key_for(batch, num_recycles)
        args = (self.params, batch["seq"], batch["mask"], batch["msa"],
                batch["msa_mask"])
        fn = self._lookup(key)
        if fn is None:
            with trace.span("compile", bucket_len=key[0],
                            batch_size=key[1], msa_depth=key[2],
                            num_recycles=key[3]):
                fn = self._compile(key, args)
        with trace.span("fold", bucket_len=key[0]):
            if self.faults is not None:
                # injected exceptions/latency fire BEFORE the device
                # call (a chaos fault must not waste real accelerator
                # time); NaN-poison rows are patched in after
                self.faults.on_executor_run(batch)
            result = fn(*args)
            result = jax.block_until_ready(result)
            if self.faults is not None:
                result = self.faults.mutate_result(batch, result)
            return result

    def warmup(self, keys: Iterable[ExecKey],
               timer=None) -> int:
        """Compile (and discard) each (len, batch, msa_depth, recycles)
        signature with a zero batch. Returns the number of fresh
        compiles. Optional `timer` is a profiling.StepTimer measuring
        each warmup (== compile+first-run) wall time."""
        fresh = 0
        for key in keys:
            bucket_len, batch_size, msa_depth, num_recycles = key
            before = self.misses
            batch = {
                "seq": jnp.zeros((batch_size, bucket_len), jnp.int32),
                "mask": jnp.zeros((batch_size, bucket_len), bool),
                "msa": None, "msa_mask": None,
            }
            if msa_depth:
                batch["msa"] = jnp.zeros(
                    (batch_size, msa_depth, bucket_len), jnp.int32)
                batch["msa_mask"] = jnp.zeros(
                    (batch_size, msa_depth, bucket_len), bool)
            if timer is not None:
                with timer.measure():
                    self.run(batch, num_recycles)
            else:
                self.run(batch, num_recycles)
            fresh += self.misses - before
        return fresh

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "resident": len(self._cache),
                    "max_entries": self.max_entries,
                    "keys": list(self._cache.keys())}
