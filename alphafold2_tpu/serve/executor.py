"""Compiled-executable cache around `predict.fold`.

One compiled executable per (bucket_len, batch_size, msa_depth,
num_recycles, mesh_shape, model_tag, variant) key: because the scheduler feeds
each key exactly one shape signature, the executor compiles ahead-of-
time (`jax.jit(...).lower(args).compile()`) and caches the resulting
`Compiled` object — so LRU-evicting a key actually frees its executable
(a single shared jit fn would pin every shape it ever saw in its
internal cache — no eviction handle), and compilation is a separately
observable phase: `run(..., trace=)` records a `compile` span only when
a key is built fresh and a `fold` span for the device execution, which
is how a request trace attributes XLA time vs accelerator time
(obs/trace.py). On TPU the executables for big buckets are HBM-heavy;
`max_entries` bounds the resident set and `warmup()` pre-pays compiles
before traffic arrives instead of on the first unlucky request.

The key's mesh_shape/model_tag elements close two staleness holes
(ISSUE 7): `model_tag` means a weight rollout (the scheduler re-tags
the executor) can never serve an executable compiled against the
previous weights' identity, and `mesh_shape` keeps single-chip and
mesh-sharded executables for the same bucket coexisting in the LRU.

The `variant` element (ISSUE 9, see MIGRATING) names WHICH compiled
program serves the key: "fold" is the classic opaque executable (all
recycles inside one `lax.scan`), "init" is the embed+first-pass
executable and "step" the single-recycle executable of the
scheduler-owned recycle loop (`run_init`/`run_step`, driven by
`serve.recycle.RecyclePolicy`). init/step keys pin num_recycles to 0 —
the step program is recycle-count-independent by construction, so one
step executable serves every configured recycle depth.

Multi-chip execution (`run(..., devices=, mesh_shape=)` — driven by the
scheduler's `serve.meshpolicy.MeshPolicy`): the fold lowers under
`parallel.mesh.make_mesh` with the model's own `shard_pair/shard_msa`
constraints live (FastFold-style 2-D pair sharding at inference),
params placed once per (device slice, model_tag) via
`parallel.sharding.shard_pytree_tp` and reused across executables,
inputs placed per `parallel.sharding.fold_input_shardings`. A 1-device
slice skips the mesh entirely and just pins args to that device, so
several short folds run concurrently on disjoint chips. `devices=None`
(the default) is byte-for-byte the single-chip behavior this file
always had.

`stats()` exposes hits/misses/evictions; misses == distinct XLA
compilations triggered through this executor, the number the e2e test
pins to the bucket count.
"""

from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict
from typing import Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from alphafold2_tpu.obs.trace import NULL_TRACE
from alphafold2_tpu.parallel.mesh import make_mesh
from alphafold2_tpu.parallel.sharding import (fold_input_shardings,
                                              shard_pytree_tp, use_mesh)
from alphafold2_tpu.predict import (FoldResult, FoldStepState, fold,
                                    fold_init, fold_init_rows, fold_step)
from alphafold2_tpu.serve.bucketing import msa_depth_of
from alphafold2_tpu.serve.meshpolicy import MeshShape, factor_chips, \
    mesh_label

# (bucket_len, batch_size, msa_depth, num_recycles, mesh_shape,
#  model_tag, variant, kernel) — variant in ("fold", "init", "step",
#  "init_rows"); init_rows (ISSUE 11) is the row-masked admission
#  program of the continuous batcher, warmed alongside the init+step
#  pair so a mid-loop row admission never pays a serving-path compile.
#  kernel (ISSUE 12, see MIGRATING) names WHICH attention kernel the
#  executable was lowered with: "dense" (the classic path) or a
#  KernelSpec.label ("bs128x16-s1a2b3c4d" — block size, pattern
#  content, and backend all in the digest), so a kernel-policy flip or
#  a contact-prior re-plan re-lowers instead of serving a stale
#  program — the same staleness invariant mesh_shape/model_tag carry.
ExecKey = Tuple[int, int, int, int, MeshShape, str, str, str]

_SINGLE: MeshShape = (1, 1)
_DENSE = "dense"
_BATCH_INPUTS = ("seq", "mask", "msa", "msa_mask")


class FoldExecutor:
    """LRU cache of compiled fold executables, keyed by shape signature.

    model_tag: weight identity baked into every ExecKey; reassigning it
        (the scheduler does on a rollout) makes every prior executable
        unreachable by construction — no stale compiled state can serve
        the new tag.
    faults: optional serve.faults.FaultPlan — chaos-injection hook
        (exceptions / latency spikes before the device call, NaN
        mutation after); None (default) costs nothing on the hot path.
    """

    def __init__(self, model, params, max_entries: int = 8, faults=None,
                 model_tag: str = ""):
        assert model.predict_coords, "serving needs predict_coords=True"
        self.model = model
        self.params = params
        self.max_entries = max(1, int(max_entries))
        self.faults = faults
        # executable cache key: ExecKey + concrete device ids (an
        # executable is bound to the devices it lowered for; two
        # disjoint 1-chip slices need two executables)
        self._cache: "OrderedDict[tuple, callable]" = OrderedDict()
        # (device_ids, model_tag) -> (mesh_or_None, placed_params):
        # params are transferred/sharded ONCE per slice and reused by
        # every executable compiled on that slice
        self._placed: dict = {}
        self._lock = threading.Lock()
        self.model_tag = model_tag
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def model_tag(self) -> str:
        return self._model_tag

    @model_tag.setter
    def model_tag(self, tag: str):
        """A rollout re-tags the executor (the scheduler's own
        model_tag setter forwards here): besides re-keying every
        future ExecKey, drop param placements minted under any OTHER
        tag NOW — a slice that sees no post-rollout traffic must not
        keep the rolled-out weights' copies pinned in device memory."""
        self._model_tag = tag
        with self._lock:
            for k in [k for k in self._placed if k[1] != tag]:
                del self._placed[k]

    def rebuild(self) -> "FoldExecutor":
        """Fresh executor over the same (model, params): empty
        executable cache, zeroed counters. The scheduler's watchdog
        swaps a hung executor for this — compiled state owned by a
        wedged device call is not trustworthy, and the zombie watchdog
        thread keeps the OLD instance alive until it dies, so its late
        result can never land in the serving path."""
        return FoldExecutor(self.model, self.params,
                            max_entries=self.max_entries,
                            faults=self.faults,
                            model_tag=self.model_tag)

    def _build(self, num_recycles: int, kernel=None):
        def run(params, seq, mask, msa, msa_mask) -> FoldResult:
            return fold(self.model, params, seq, msa=msa, mask=mask,
                        msa_mask=msa_mask, num_recycles=num_recycles,
                        kernel=kernel)

        return jax.jit(run)

    def _builder(self, variant: str, num_recycles: int, kernel=None):
        """The jitted callable for one ExecKey variant: "fold" is the
        opaque all-recycles program, "init"/"step" the two halves of
        the scheduler-owned recycle loop (predict.fold_init/fold_step —
        the scan body as its own executable, so step-mode numerics
        match the scan path exactly). `kernel` (a static
        ops.block_sparse.KernelSpec, or None = dense) closes into the
        program — it is part of WHAT gets compiled, which is why its
        label lives in the ExecKey."""
        if variant == "fold":
            return self._build(num_recycles, kernel=kernel)
        if variant == "init":
            def run_init(params, seq, mask, msa,
                         msa_mask) -> FoldStepState:
                return fold_init(self.model, params, seq, msa=msa,
                                 mask=mask, msa_mask=msa_mask,
                                 kernel=kernel)

            return jax.jit(run_init)
        if variant == "init_rows":
            def run_init_rows(params, seq, mask, msa, msa_mask,
                              row_mask, state) -> FoldStepState:
                return fold_init_rows(self.model, params, seq, row_mask,
                                      state, msa=msa, mask=mask,
                                      msa_mask=msa_mask, kernel=kernel)

            return jax.jit(run_init_rows)
        if variant != "step":
            raise ValueError(f"unknown executable variant {variant!r}")

        def run_step(params, seq, mask, msa, msa_mask,
                     recyclables) -> FoldStepState:
            return fold_step(self.model, params, seq, recyclables,
                             msa=msa, mask=mask, msa_mask=msa_mask,
                             kernel=kernel)

        return jax.jit(run_step)

    def _compile(self, cache_key: tuple, num_recycles: int, args,
                 mesh=None, variant: str = "fold", kernel=None):
        """AOT-compile the key's executable OUTSIDE the cache lock (an
        XLA compile can take seconds; holding the lock would stall
        concurrent hit lookups) and insert it. Falls back to the lazily
        compiling jitted callable on JAX versions/paths where AOT
        lowering refuses the argument structure. `mesh` (multi-chip
        slices only) is entered during lowering so the model's sharding
        constraints bake into the executable."""
        jitted = self._builder(variant, num_recycles, kernel=kernel)
        ctx = use_mesh(mesh) if mesh is not None \
            else contextlib.nullcontext()
        try:
            with ctx:
                fn = jitted.lower(*args).compile()
        except Exception:
            fn = jitted          # first call will compile lazily
        with self._lock:
            self.misses += 1
            existing = self._cache.get(cache_key)
            if existing is not None:
                # raced with another compiler of the same key: keep the
                # resident one (both are valid; counters stay honest)
                self._cache.move_to_end(cache_key)
                return existing
            self._cache[cache_key] = fn
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)
                self.evictions += 1
        return fn

    def _lookup(self, cache_key: tuple):
        with self._lock:
            fn = self._cache.get(cache_key)
            if fn is not None:
                self.hits += 1
                self._cache.move_to_end(cache_key)
            return fn

    def key_for(self, batch: dict, num_recycles: int,
                mesh_shape: Optional[MeshShape] = None,
                variant: str = "fold", kernel=None) -> ExecKey:
        b, n = batch["seq"].shape
        shape = _SINGLE if mesh_shape is None \
            else tuple(int(x) for x in mesh_shape)
        # init/step programs are recycle-count-independent: pinning the
        # recycles element to 0 means one step executable serves every
        # configured depth instead of minting one per config
        recycles = int(num_recycles) if variant == "fold" else 0
        return (int(n), int(b), msa_depth_of(batch), recycles,
                shape, self.model_tag, variant,
                _DENSE if kernel is None else kernel.label)

    def _normalize_key(self, key) -> ExecKey:
        """Accept legacy 4-tuple (len, batch, msa_depth, recycles),
        5-tuple (+ mesh_shape), 6-tuple (+ model_tag), and 7-tuple
        (+ variant) keys alongside the full 8-tuple — `warmup()`
        callers predate the mesh/model_tag/variant/kernel elements."""
        key = tuple(key)
        if len(key) == 4:
            return key + (_SINGLE, self.model_tag, "fold", _DENSE)
        if len(key) == 5:
            return key[:4] + (tuple(key[4]), self.model_tag, "fold",
                              _DENSE)
        if len(key) == 6:
            return key[:4] + (tuple(key[4]), key[5], "fold", _DENSE)
        if len(key) == 7:
            return key[:4] + (tuple(key[4]),) + tuple(key[5:7]) \
                + (_DENSE,)
        return key[:4] + (tuple(key[4]),) + tuple(key[5:8])

    # -- device-slice plumbing -------------------------------------------

    def _placed_params(self, devices: Sequence, mesh_shape: MeshShape):
        """(mesh_or_None, params placed on the slice), computed once per
        (device slice, model_tag). Placements for rolled-out tags are
        pruned eagerly by the model_tag setter."""
        dev_ids = tuple(int(d.id) for d in devices)
        cache_k = (dev_ids, self.model_tag)
        with self._lock:
            placed = self._placed.get(cache_k)
        if placed is not None:
            return placed
        if len(devices) == 1:
            mesh = None
            params = jax.device_put(self.params, devices[0])
        else:
            mesh = make_mesh(1, mesh_shape[0], mesh_shape[1],
                             devices=devices)
            params = shard_pytree_tp(self.params, mesh)
        with self._lock:
            existing = self._placed.get(cache_k)
            if existing is not None:
                return existing          # raced: keep the resident copy
            self._placed[cache_k] = (mesh, params)
        return mesh, params

    def _place_inputs(self, batch: dict, mesh, devices: Sequence):
        if mesh is None:
            dev = devices[0]
            return tuple(None if batch[k] is None
                         else jax.device_put(batch[k], dev)
                         for k in _BATCH_INPUTS)
        shardings = fold_input_shardings(mesh, batch)
        return tuple(None if batch[k] is None
                     else jax.device_put(batch[k], shardings[k])
                     for k in _BATCH_INPUTS)

    # -- execution -------------------------------------------------------

    def run(self, batch: dict, num_recycles: int,
            trace=NULL_TRACE, devices: Optional[Sequence] = None,
            mesh_shape: Optional[MeshShape] = None,
            kernel=None) -> FoldResult:
        """Fold one assembled batch; blocks until device results land so
        the caller's latency measurement is honest. `trace` (a Trace /
        MultiTrace; NULL_TRACE default is zero-cost) gets a `compile`
        span when this signature is built fresh and a `fold` span for
        the execution itself.

        devices: optional device slice (a SliceLease's devices). None —
        the default — is the single-chip path, unchanged. With a slice,
        `mesh_shape` (i, j) factorizes it (default: squarest face); the
        trace additionally gets a `shard` span covering params/input
        placement and the fold span is tagged with the mesh label.

        kernel: optional ops.block_sparse.KernelSpec (ISSUE 12) — the
        attention kernel this batch's executable runs. Part of the
        ExecKey, so dense and block-sparse executables for the same
        bucket coexist in the LRU; fold spans are tagged with the
        kernel label. None (default) is byte-for-byte the dense path.
        """
        if devices:
            return self._run_on_slice(batch, num_recycles, trace,
                                      list(devices), mesh_shape, kernel)
        key = self.key_for(batch, num_recycles, kernel=kernel)
        args = (self.params, batch["seq"], batch["mask"], batch["msa"],
                batch["msa_mask"])
        cache_key = key + ((),)
        ktag = {} if kernel is None else {"kernel": kernel.label}
        fn = self._lookup(cache_key)
        if fn is None:
            with trace.span("compile", bucket_len=key[0],
                            batch_size=key[1], msa_depth=key[2],
                            num_recycles=key[3], **ktag):
                fn = self._compile(cache_key, key[3], args,
                                   kernel=kernel)
        with trace.span("fold", bucket_len=key[0], **ktag):
            return self._invoke(fn, args, batch)

    def _run_on_slice(self, batch: dict, num_recycles: int, trace,
                      devices, mesh_shape, kernel=None) -> FoldResult:
        if mesh_shape is None:
            mesh_shape = factor_chips(len(devices))
        mesh_shape = tuple(int(x) for x in mesh_shape)
        label = mesh_label(mesh_shape)
        key = self.key_for(batch, num_recycles, mesh_shape=mesh_shape,
                           kernel=kernel)
        dev_ids = tuple(int(d.id) for d in devices)
        cache_key = key + (dev_ids,)
        ktag = {} if kernel is None else {"kernel": kernel.label}
        with trace.span("shard", mesh=label, devices=len(devices)):
            mesh, params = self._placed_params(devices, mesh_shape)
            args = (params,) + self._place_inputs(batch, mesh, devices)
        fn = self._lookup(cache_key)
        if fn is None:
            with trace.span("compile", bucket_len=key[0],
                            batch_size=key[1], msa_depth=key[2],
                            num_recycles=key[3], mesh=label, **ktag):
                fn = self._compile(cache_key, key[3], args, mesh=mesh,
                                   kernel=kernel)
        with trace.span("fold", bucket_len=key[0], mesh=label, **ktag):
            # the lazy-compile fallback traces on first call, so the
            # mesh context must be live during invocation too
            ctx = use_mesh(mesh) if mesh is not None \
                else contextlib.nullcontext()
            with ctx:
                return self._invoke(fn, args, batch)

    # -- step-mode execution (scheduler-owned recycle loop) --------------

    def run_init(self, batch: dict, trace=NULL_TRACE,
                 devices: Optional[Sequence] = None,
                 mesh_shape: Optional[MeshShape] = None,
                 kernel=None) -> FoldStepState:
        """The embed+first-pass executable: recycle iteration 0 of the
        scheduler-owned loop (`serve.recycle.RecyclePolicy`). Blocks
        until the device result lands. Spans: `compile` when the
        init-variant signature is built fresh, `fold` for the execution
        itself (the obs checker's accelerator-time rule keys off a
        non-zero fold span, and this IS the fold's first pass).
        `kernel` — see run()."""
        return self._run_stepmode("init", batch, (), trace, devices,
                                  mesh_shape, span="fold", attrs={},
                                  kernel=kernel)

    def run_init_rows(self, batch: dict, state: FoldStepState,
                      row_mask, trace=NULL_TRACE,
                      devices: Optional[Sequence] = None,
                      mesh_shape: Optional[MeshShape] = None,
                      kernel=None,
                      span_attrs: Optional[dict] = None) -> FoldStepState:
        """Row-masked admission init (continuous batching, ISSUE 11):
        rows where `row_mask` is True restart at iteration 0 from the
        batch tensors (which the scheduler just rewrote with newly
        admitted requests), rows where it is False pass the carried
        `state` through untouched — survivors keep stepping, nothing
        recompiles mid-loop because this variant was warmed with the
        init+step pair. Span: `admit` (the admission cost is its own
        waterfall stage — it is neither a fold nor a recycle).
        `span_attrs` merges extra attributes into the admit span (the
        cross-bucket scheduler tags the admitted rows' native buckets,
        ISSUE 13)."""
        mask_arr = jnp.asarray(row_mask, bool)
        attrs = {"rows": int(mask_arr.sum())}
        if span_attrs:
            attrs.update(span_attrs)
        return self._run_stepmode(
            "init_rows", batch, (mask_arr, state), trace, devices,
            mesh_shape, span="admit", attrs=attrs, kernel=kernel)

    def run_step(self, batch: dict, state: FoldStepState,
                 recycle_index: int, trace=NULL_TRACE,
                 devices: Optional[Sequence] = None,
                 mesh_shape: Optional[MeshShape] = None,
                 span_attrs: Optional[dict] = None,
                 kernel=None) -> FoldStepState:
        """One recycle iteration: feeds `state.recyclables` (from
        run_init or a previous run_step on the same slice) through the
        step executable. Span: `recycle`, tagged with the iteration
        index (and mesh label on a slice). `span_attrs` merges extra
        attributes into the recycle span (the continuous scheduler tags
        per-step row occupancy for the obs_report occupancy line)."""
        attrs = {"recycle": int(recycle_index)}
        if span_attrs:
            attrs.update(span_attrs)
        return self._run_stepmode(
            "step", batch, (state.recyclables,), trace, devices,
            mesh_shape, span="recycle", attrs=attrs, kernel=kernel)

    def _run_stepmode(self, variant: str, batch: dict, extra_args,
                      trace, devices, mesh_shape, span: str,
                      attrs: dict, kernel=None):
        """Shared lookup/compile/execute path for the init/step
        variants, covering both the single-chip and device-slice
        cases. `extra_args` (the step's carried recyclables) ride after
        the placed batch inputs; they are prior outputs of this very
        slice, so they are already resident where the executable
        expects them."""
        if kernel is not None:
            attrs = dict(attrs, kernel=kernel.label)
        if devices:
            devices = list(devices)
            if mesh_shape is None:
                mesh_shape = factor_chips(len(devices))
            mesh_shape = tuple(int(x) for x in mesh_shape)
            label = mesh_label(mesh_shape)
            key = self.key_for(batch, 0, mesh_shape=mesh_shape,
                               variant=variant, kernel=kernel)
            dev_ids = tuple(int(d.id) for d in devices)
            cache_key = key + (dev_ids,)
            # the batch inputs are identical across a step loop's
            # iterations, so their device placement is cached ON the
            # batch dict (keyed by slice identity): one host-to-slice
            # transfer + one `shard` span per loop, not one per step.
            # A repack mints a fresh batch dict (repack_batch copies
            # only the canonical keys), which drops the stale cache.
            place_key = ("_placed", dev_ids)
            mesh, params = self._placed_params(devices, mesh_shape)
            placed = batch.get(place_key)
            if placed is None:
                with trace.span("shard", mesh=label,
                                devices=len(devices)):
                    placed = self._place_inputs(batch, mesh, devices)
                batch[place_key] = placed
            args = (params,) + placed + tuple(extra_args)
            attrs = dict(attrs, mesh=label)
        else:
            mesh = None
            key = self.key_for(batch, 0, variant=variant,
                               kernel=kernel)
            cache_key = key + ((),)
            args = (self.params, batch["seq"], batch["mask"],
                    batch["msa"], batch["msa_mask"]) + tuple(extra_args)
        fn = self._lookup(cache_key)
        if fn is None:
            with trace.span("compile", bucket_len=key[0],
                            batch_size=key[1], msa_depth=key[2],
                            variant=variant,
                            **{k: attrs[k] for k in ("mesh", "kernel")
                               if k in attrs}):
                fn = self._compile(cache_key, 0, args, mesh=mesh,
                                   variant=variant, kernel=kernel)
        with trace.span(span, bucket_len=key[0], **attrs):
            ctx = use_mesh(mesh) if mesh is not None \
                else contextlib.nullcontext()
            with ctx:
                return self._invoke(fn, args, batch, variant=variant,
                                    recycle=attrs.get("recycle"))

    def _invoke(self, fn, args, batch, variant: str = "fold",
                recycle=None) -> FoldResult:
        if self.faults is not None:
            # injected exceptions/latency fire BEFORE the device
            # call (a chaos fault must not waste real accelerator
            # time); NaN-poison rows are patched in after. The fault
            # hook is step-aware (ISSUE 14): the variant + recycle
            # index let a chaos plan hit a SPECIFIC recycle depth
            self.faults.on_executor_run(batch, variant=variant,
                                        recycle=recycle)
        result = fn(*args)
        result = jax.block_until_ready(result)
        if self.faults is not None:
            result = self.faults.mutate_result(batch, result)
        return result

    def warmup(self, keys: Iterable,
               timer=None, devices: Optional[Sequence] = None,
               mesh_shape: Optional[MeshShape] = None,
               step_mode: bool = False,
               continuous: bool = False,
               kernel=None) -> int:
        """Compile (and discard) each key's signature with a zero batch.
        Keys may be legacy 4-tuples (len, batch, msa_depth, recycles) or
        full ExecKeys; `devices`/`mesh_shape` warm the slice-bound
        executable the scheduler will actually run (the mesh-aware
        scheduler warms per bucket with the bucket's own lease).
        `step_mode` warms the init+step executable PAIR instead of the
        opaque fold — what a scheduler driving the recycle loop
        (recycle_policy set) will actually execute. `continuous`
        (step_mode only) additionally warms the row-masked `init_rows`
        admission program, so a continuous batcher's first mid-loop row
        admission never triggers a mid-serving compile (ISSUE 11).
        `kernel` (a KernelSpec, ISSUE 12) warms the kernel-variant
        executable the kernel policy will actually route to this
        bucket — the scheduler passes each bucket's own spec.
        Returns the number of fresh compiles. Optional `timer` is a
        profiling.StepTimer measuring each warmup (== compile+first-run)
        wall time."""
        fresh = 0
        for key in keys:
            bucket_len, batch_size, msa_depth, num_recycles = \
                self._normalize_key(key)[:4]
            before = self.misses
            batch = {
                "seq": jnp.zeros((batch_size, bucket_len), jnp.int32),
                "mask": jnp.zeros((batch_size, bucket_len), bool),
                "msa": None, "msa_mask": None,
            }
            if msa_depth:
                batch["msa"] = jnp.zeros(
                    (batch_size, msa_depth, bucket_len), jnp.int32)
                batch["msa_mask"] = jnp.zeros(
                    (batch_size, msa_depth, bucket_len), bool)

            # a spec only covers its own bucket length: warming a key
            # of another bucket under it would label a dense program
            # with a sparse key — guard here so one warmup() call may
            # mix kernel'd and plain keys safely
            k_spec = kernel if (kernel is not None
                                and kernel.covers(bucket_len)) else None

            def _one():
                if step_mode:
                    state = self.run_init(batch, devices=devices,
                                          mesh_shape=mesh_shape,
                                          kernel=k_spec)
                    if continuous:
                        # shape-only warm: the mask values never change
                        # the compiled program, only which rows reinit
                        mask0 = jnp.zeros((batch_size,), bool)
                        state = self.run_init_rows(
                            batch, state, mask0, devices=devices,
                            mesh_shape=mesh_shape, kernel=k_spec)
                    self.run_step(batch, state, 0, devices=devices,
                                  mesh_shape=mesh_shape, kernel=k_spec)
                else:
                    self.run(batch, num_recycles, devices=devices,
                             mesh_shape=mesh_shape, kernel=k_spec)

            if timer is not None:
                with timer.measure():
                    _one()
            else:
                _one()
            fresh += self.misses - before
        return fresh

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "resident": len(self._cache),
                    "max_entries": self.max_entries,
                    "keys": [k[:-1] for k in self._cache.keys()],
                    "placed_param_slices": len(self._placed)}
