"""alphafold2-tpu: a TPU-native (JAX/XLA/Pallas/pjit) protein-structure
framework with the capabilities of lucidrains/alphafold2.

Public API parity with the reference
(/root/reference/alphafold2_pytorch/__init__.py:1):
    from alphafold2_tpu import Alphafold2, Evoformer
"""

__version__ = "0.1.0"

from alphafold2_tpu import constants  # noqa: F401

# Model classes are imported lazily-but-eagerly here; they only require jax.
from alphafold2_tpu.model.alphafold2 import Alphafold2  # noqa: F401
from alphafold2_tpu.model.evoformer import Evoformer  # noqa: F401
