from alphafold2_tpu.core import geometry, mds, nerf, quaternion, rigid  # noqa: F401
from alphafold2_tpu.core.mds import MDSResult, mdscaling  # noqa: F401
from alphafold2_tpu.core.nerf import nerf_place, sidechain_container  # noqa: F401
from alphafold2_tpu.core.rigid import Rigid  # noqa: F401
