from alphafold2_tpu.core import geometry, quaternion, rigid  # noqa: F401
from alphafold2_tpu.core.rigid import Rigid  # noqa: F401
