"""Quaternion algebra in pure JAX.

Replaces the reference's dependency on pytorch3d's C++/CUDA quaternion ops
(/root/reference/alphafold2_pytorch/alphafold2.py:20, :868, :886, :890).
Closed-form math — XLA fuses these into surrounding computation, so no
custom kernel is needed.

Convention: quaternions are (..., 4) with scalar part first, (w, x, y, z).
"""

from __future__ import annotations

import jax.numpy as jnp


def identity_quaternion(shape=(), dtype=jnp.float32) -> jnp.ndarray:
    """(1, 0, 0, 0) broadcast to shape + (4,)."""
    q = jnp.zeros((*shape, 4), dtype=dtype)
    return q.at[..., 0].set(1.0)


def quaternion_multiply(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Hamilton product a * b, both (..., 4) wxyz."""
    aw, ax, ay, az = jnp.moveaxis(a, -1, 0)
    bw, bx, by, bz = jnp.moveaxis(b, -1, 0)
    return jnp.stack(
        [
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ],
        axis=-1,
    )


def quaternion_to_matrix(q: jnp.ndarray) -> jnp.ndarray:
    """Unit-normalized rotation matrix from (..., 4) wxyz -> (..., 3, 3).

    Rows are the images of the basis vectors: `v @ R` rotates a row-vector v,
    matching the reference's `einsum('b n c, b n c d -> b n d', points, R)`
    usage (alphafold2.py:891) with pytorch3d matrices.
    """
    q = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
    w, x, y, z = jnp.moveaxis(q, -1, 0)
    r00 = 1 - 2 * (y * y + z * z)
    r01 = 2 * (x * y - z * w)
    r02 = 2 * (x * z + y * w)
    r10 = 2 * (x * y + z * w)
    r11 = 1 - 2 * (x * x + z * z)
    r12 = 2 * (y * z - x * w)
    r20 = 2 * (x * z - y * w)
    r21 = 2 * (y * z + x * w)
    r22 = 1 - 2 * (x * x + y * y)
    return jnp.stack(
        [
            jnp.stack([r00, r01, r02], axis=-1),
            jnp.stack([r10, r11, r12], axis=-1),
            jnp.stack([r20, r21, r22], axis=-1),
        ],
        axis=-2,
    )


def quaternion_invert(q: jnp.ndarray) -> jnp.ndarray:
    """Conjugate of a unit quaternion."""
    return q * jnp.asarray([1.0, -1.0, -1.0, -1.0], dtype=q.dtype)


def rotate_vector(q: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Rotate (..., 3) vectors by (..., 4) quaternions."""
    r = quaternion_to_matrix(q)
    return jnp.einsum("...c,...cd->...d", v, r)
