"""Differentiable NeRF (Natural Extension Reference Frame) atom placement
and backbone -> 14-atom sidechain build-out.

Replaces the reference's dependency on the external `mp_nerf` package
(/root/reference/alphafold2_pytorch/utils.py:24, :653-713
`sidechain_container`): given backbone coordinates, produce the full
sidechainnet 14-slot scaffold by chaining NeRF placements along each
residue's covalent-bond graph (constants.AA_DATA). Fully vectorized over
batch and residues — the only sequential dimension is the 14-slot chain,
unrolled (10 steps), so XLA sees a static graph; no per-residue Python
loops like mp_nerf's CPU-parallel design.

Geometry uses idealized bond lengths/angles by element pair (the reference
path inherits exact tables from sidechainnet; idealized values are within
~0.03 A and the decode path's own NaN-repair shows it is approximate by
design, utils.py:708-712). Chi torsions are free parameters (default
extended, 180 deg).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from alphafold2_tpu import constants

# ---------------------------------------------------------------------------
# NeRF primitive
# ---------------------------------------------------------------------------


def nerf_place(
    a: jnp.ndarray, b: jnp.ndarray, c: jnp.ndarray,
    bond_length, bond_angle, torsion,
) -> jnp.ndarray:
    """Place atom D given chain A-B-C and (|CD|, angle BCD, dihedral ABCD).

    All inputs broadcast; coordinates (..., 3), scalars (...,).
    Differentiable and jit/vmap-safe.
    """
    # eps INSIDE the sqrt: norm() at exactly 0 (degenerate frames of
    # masked-out slots) has an inf vjp that turns a zero cotangent into NaN
    safe_norm = lambda v: jnp.sqrt(jnp.sum(v * v, -1, keepdims=True) + 1e-12)
    bc = c - b
    bc = bc / safe_norm(bc)
    ab = b - a
    n = jnp.cross(ab, bc)
    n = n / safe_norm(n)
    m = jnp.cross(n, bc)

    shape = c.shape[:-1]
    bond_length = jnp.broadcast_to(jnp.asarray(bond_length, c.dtype), shape)
    bond_angle = jnp.broadcast_to(jnp.asarray(bond_angle, c.dtype), shape)
    torsion = jnp.broadcast_to(jnp.asarray(torsion, c.dtype), shape)

    ang = jnp.pi - bond_angle  # interior -> placement angle
    d_local = jnp.stack([
        jnp.cos(ang) * bond_length,
        jnp.sin(ang) * jnp.cos(torsion) * bond_length,
        jnp.sin(ang) * jnp.sin(torsion) * bond_length,
    ], axis=-1)
    frame = jnp.stack([bc, m, n], axis=-1)  # columns are the basis
    return c + jnp.einsum("...ij,...j->...i", frame, d_local)


# ---------------------------------------------------------------------------
# Per-AA build tables (slots 4..13 of the 14-atom layout)
# ---------------------------------------------------------------------------


def _element(atom_name: str) -> str:
    return atom_name[0]  # N/C/O/S in the 14-slot vocabulary


_BOND_LEN = {("C", "C"): 1.52, ("C", "N"): 1.47, ("N", "C"): 1.47,
             ("C", "O"): 1.43, ("O", "C"): 1.43, ("C", "S"): 1.81,
             ("S", "C"): 1.81}
_TET = np.deg2rad(111.0)

# Per-residue chemistry refinements over the element-pair defaults:
# {three-letter: {child atom: (bond length A, angle deg at parent)}}.
# Standard small-molecule/protein values (Engh & Huber-style): aromatic
# ring C-C ~1.39, carbonyl/carboxylate C=O 1.23-1.25, amide/guanidinium
# C-N ~1.33, thioether C-S ~1.80, hydroxyl C-O ~1.42. sp2 centers get
# ~120 deg, 5-ring members ~106-127 deg (exterior). Without these the
# generic 1.52/111 tables miss aromatic and carbonyl bonds by >0.1 A
# (round-1 VERDICT Weak #7; checked against a real structure in
# tests/test_decode.py::TestNerfAccuracy).
_CHEM = {
    "ARG": {"NE": (1.46, 112.0), "CZ": (1.33, 124.5),
            "NH1": (1.33, 120.0), "NH2": (1.33, 120.0)},
    "ASN": {"OD1": (1.23, 120.8), "ND2": (1.33, 116.5)},
    "ASP": {"OD1": (1.25, 118.5), "OD2": (1.25, 118.5)},
    "CYS": {"SG": (1.81, 114.0)},
    "GLN": {"OE1": (1.23, 120.8), "NE2": (1.33, 116.5)},
    "GLU": {"OE1": (1.25, 118.5), "OE2": (1.25, 118.5)},
    "HIS": {"CG": (1.50, 113.8), "ND1": (1.38, 122.7),
            "CD2": (1.36, 131.0), "CE1": (1.32, 109.0),
            "NE2": (1.37, 107.0)},
    "ILE": {"CD1": (1.51, 113.9)},
    "LYS": {"NZ": (1.49, 111.7)},
    "MET": {"SD": (1.80, 112.7), "CE": (1.79, 100.9)},
    "PHE": {"CG": (1.50, 113.8), "CD1": (1.39, 120.7),
            "CD2": (1.39, 120.7), "CE1": (1.39, 120.7),
            "CE2": (1.39, 120.7), "CZ": (1.39, 120.0)},
    "PRO": {"CG": (1.49, 104.5), "CD": (1.50, 106.1)},
    "SER": {"OG": (1.42, 111.1)},
    "THR": {"OG1": (1.43, 109.6)},
    "TRP": {"CG": (1.50, 113.6), "CD1": (1.37, 127.0),
            "CD2": (1.43, 126.9), "NE1": (1.38, 110.2),
            "CE2": (1.41, 107.2), "CE3": (1.40, 133.9),
            "CZ2": (1.40, 122.4), "CZ3": (1.39, 118.6),
            "CH2": (1.37, 117.5)},
    "TYR": {"CG": (1.51, 113.8), "CD1": (1.39, 120.8),
            "CD2": (1.39, 120.8), "CE1": (1.39, 121.1),
            "CE2": (1.39, 121.1), "CZ": (1.38, 119.5),
            "OH": (1.38, 119.9)},
}


# Authoritative sidechain bond topology: {three-letter: {child: parent}}.
# The shared AA_DATA bond lists (reference constants.py:34-113) are a graph
# -features vocabulary, NOT chemistry — they wire aromatic rings as a
# sequential slot cycle (PHE "CD1-CD2", "CD2-CE1": meta/para pairs, real
# distances 2.4-2.8 A) and ARG's CB to backbone C. Building atoms along
# those edges misplaces whole sidechains, so the NeRF build uses this
# chemically correct parent map instead (verified against a real crystal
# structure in tests/test_decode.py::TestNerfAccuracy).
_PARENTS = {
    "ALA": {"CB": "CA"},
    "ARG": {"CB": "CA", "CG": "CB", "CD": "CG", "NE": "CD", "CZ": "NE",
            "NH1": "CZ", "NH2": "CZ"},
    "ASN": {"CB": "CA", "CG": "CB", "OD1": "CG", "ND2": "CG"},
    "ASP": {"CB": "CA", "CG": "CB", "OD1": "CG", "OD2": "CG"},
    "CYS": {"CB": "CA", "SG": "CB"},
    "GLN": {"CB": "CA", "CG": "CB", "CD": "CG", "OE1": "CD", "NE2": "CD"},
    "GLU": {"CB": "CA", "CG": "CB", "CD": "CG", "OE1": "CD", "OE2": "CD"},
    "GLY": {},
    "HIS": {"CB": "CA", "CG": "CB", "ND1": "CG", "CD2": "CG",
            "CE1": "ND1", "NE2": "CD2"},
    "ILE": {"CB": "CA", "CG1": "CB", "CG2": "CB", "CD1": "CG1"},
    "LEU": {"CB": "CA", "CG": "CB", "CD1": "CG", "CD2": "CG"},
    "LYS": {"CB": "CA", "CG": "CB", "CD": "CG", "CE": "CD", "NZ": "CE"},
    "MET": {"CB": "CA", "CG": "CB", "SD": "CG", "CE": "SD"},
    "PHE": {"CB": "CA", "CG": "CB", "CD1": "CG", "CD2": "CG",
            "CE1": "CD1", "CE2": "CD2", "CZ": "CE1"},
    "PRO": {"CB": "CA", "CG": "CB", "CD": "CG"},
    "SER": {"CB": "CA", "OG": "CB"},
    "THR": {"CB": "CA", "OG1": "CB", "CG2": "CB"},
    "TRP": {"CB": "CA", "CG": "CB", "CD1": "CG", "CD2": "CG",
            "NE1": "CD1", "CE2": "CD2", "CE3": "CD2", "CZ2": "CE2",
            "CZ3": "CE3", "CH2": "CZ2"},
    "TYR": {"CB": "CA", "CG": "CB", "CD1": "CG", "CD2": "CG",
            "CE1": "CD1", "CE2": "CD2", "CZ": "CE1", "OH": "CZ"},
    "VAL": {"CB": "CA", "CG1": "CB", "CG2": "CB"},
}


def _build_tables():
    """For every AA token and slot >= 4: ancestor indices (a, b, c) within
    the residue, bond length and angle. Ancestors follow the chemical
    parent map (_PARENTS); backbone C-N-CA seeds the frame of the first
    sidechain atom."""
    n_aa = len(constants.AA_ALPHABET)
    k = constants.NUM_COORDS_PER_RES
    parent = np.zeros((n_aa, k), dtype=np.int32)
    grand = np.zeros((n_aa, k), dtype=np.int32)
    great = np.zeros((n_aa, k), dtype=np.int32)
    length = np.ones((n_aa, k), dtype=np.float32)
    angle = np.full((n_aa, k), _TET, dtype=np.float32)
    build = np.zeros((n_aa, k), dtype=np.float32)  # 1 if slot is built

    for ai, aa in enumerate(constants.AA_ALPHABET):
        if aa == "_":
            continue
        three = constants.ONE_TO_THREE[aa]
        atoms = constants.BACKBONE_ATOMS + constants.SIDECHAIN_ATOMS[three]
        slot_of = {name: i for i, name in enumerate(atoms)}
        par = {slot_of[c]: slot_of[p]
               for c, p in _PARENTS[three].items()}
        for slot in range(4, len(atoms)):
            p = par.get(slot, 1)
            if p == 1:
                # first sidechain atom off CA: frame seeded from C-N-CA
                g, gg = 0, 2
            else:
                g = par.get(p, 1)
                gg = 0 if g == 1 else par.get(g, 1)
            parent[ai, slot] = p
            grand[ai, slot] = g
            great[ai, slot] = gg
            el = (_element(atoms[p]), _element(atoms[slot]))
            length[ai, slot] = _BOND_LEN.get(el, 1.52)
            chem = _CHEM.get(three, {}).get(atoms[slot])
            if chem is not None:
                length[ai, slot] = chem[0]
                angle[ai, slot] = np.deg2rad(chem[1])
            build[ai, slot] = 1.0
    # numpy on purpose: jnp.asarray here would device_put at IMPORT time,
    # initializing the XLA backend before the user can call
    # jax.distributed.initialize (multihost.py's pod flow). jnp consumers
    # convert at use — constant-folded once under jit.
    return parent, grand, great, length, angle, build


_PARENT, _GRAND, _GREAT, _LENGTH, _ANGLE, _BUILD = _build_tables()

# branch torsion offsets: siblings bonded to the same parent fan out
_TORSION_BASE = np.deg2rad(180.0)


def _branch_offsets():
    """Per (aa, slot) torsion offset so atoms sharing a parent don't
    overlap: first child 180 deg, second +120, third -120."""
    n_aa = len(constants.AA_ALPHABET)
    k = constants.NUM_COORDS_PER_RES
    off = np.zeros((n_aa, k), dtype=np.float32)
    for ai, aa in enumerate(constants.AA_ALPHABET):
        if aa == "_":
            continue
        seen = {}
        for slot in range(4, k):
            p = int(_PARENT[ai, slot])
            if _BUILD[ai, slot] == 0:
                continue
            rank = seen.get(p, 0)
            off[ai, slot] = [0.0, 2 * np.pi / 3, -2 * np.pi / 3][rank % 3]
            seen[p] = rank + 1
    return off  # numpy: no device_put at import (see _build_tables)


_TORSION_OFF = _branch_offsets()


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def place_o(n_coords, ca_coords, c_coords):
    """Backbone carbonyl O from the N-CA-C frame (anti to N, sp2)."""
    torsion = jnp.full(c_coords.shape[:-1], jnp.pi)
    return nerf_place(n_coords, ca_coords, c_coords,
                      bond_length=1.23, bond_angle=np.deg2rad(121.0),
                      torsion=torsion)


def sidechain_container(
    backbone: jnp.ndarray,
    seq: jnp.ndarray,
    chi_torsions: Optional[jnp.ndarray] = None,
    cloud_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Backbone -> full 14-atom scaffold (reference sidechain_container,
    utils.py:653-713).

    backbone: (b, L, A, 3) with A in {1 (CA only), 3 (N, CA, C), 4 (+O)};
    seq: (b, L) int tokens; chi_torsions: optional (b, L, 14) torsions per
    slot (defaults to the extended/branch-offset conformation);
    cloud_mask: optional (b, L, 14) to zero out non-existent atom slots.
    Returns (b, L, 14, 3); slots never built stay at their parent position
    (then zeroed by the cloud mask).
    """
    b, l, a, _ = backbone.shape
    k = constants.NUM_COORDS_PER_RES

    if a == 1:
        # CA-only input: synthesize a virtual N/C frame along the chain.
        # The end residues must not collapse (N==CA) or go collinear
        # (N, CA, C on the chain step) — either degenerates their NeRF
        # frame, whose eps-regularized directions are NOT rotation-
        # equivariant (caught by the atom-refiner equivariance test,
        # r05). They borrow the ADJACENT step instead, so their virtual
        # N/C generically span a plane, and the construction stays a
        # function of difference vectors only (translation/rotation
        # equivariant by construction).
        ca = backbone[:, :, 0]
        if l > 2:
            step = ca[:, 1:] - ca[:, :-1]                  # (b, l-1, 3)
            prev_dir = jnp.concatenate([step[:, 1:2], step], axis=1)
            next_dir = jnp.concatenate([step, step[:, -2:-1]], axis=1)
        elif l == 2:
            step = ca[:, 1:] - ca[:, :-1]
            prev_dir = jnp.concatenate([step, step], axis=1)
            next_dir = prev_dir
        else:
            prev_dir = jnp.zeros_like(ca)
            next_dir = jnp.zeros_like(ca)
        n_at = ca - prev_dir * (1.46 / 3.8)
        c_at = ca + next_dir * (1.52 / 3.8)
    else:
        n_at, ca, c_at = backbone[:, :, 0], backbone[:, :, 1], backbone[:, :, 2]

    coords = jnp.zeros((b, l, k, 3), backbone.dtype)
    coords = coords.at[:, :, 0].set(n_at)
    coords = coords.at[:, :, 1].set(ca)
    coords = coords.at[:, :, 2].set(c_at)
    if a >= 4:
        coords = coords.at[:, :, 3].set(backbone[:, :, 3])
    else:
        coords = coords.at[:, :, 3].set(place_o(n_at, ca, c_at))

    # tables are host numpy (see _build_tables); convert for traced
    # gathers — folded to constants under jit
    parent = jnp.asarray(_PARENT)[seq]     # (b, l, 14)
    grand = jnp.asarray(_GRAND)[seq]
    great = jnp.asarray(_GREAT)[seq]
    length = jnp.asarray(_LENGTH)[seq]
    angle = jnp.asarray(_ANGLE)[seq]
    build = jnp.asarray(_BUILD)[seq]
    tors = jnp.asarray(_TORSION_OFF)[seq] + _TORSION_BASE
    if chi_torsions is not None:
        tors = tors + chi_torsions

    def gather_atom(coords, idx):
        # coords (b, l, 14, 3), idx (b, l) -> (b, l, 3)
        idx4 = jnp.broadcast_to(idx[..., None, None].astype(jnp.int32),
                                (*idx.shape, 1, 3))
        return jnp.take_along_axis(coords, idx4, axis=2)[:, :, 0]

    # chain the 10 sidechain slots; each step is fully vectorized over (b, l)
    for slot in range(4, k):
        p = gather_atom(coords, parent[:, :, slot])
        g = gather_atom(coords, grand[:, :, slot])
        gg = gather_atom(coords, great[:, :, slot])
        placed = nerf_place(gg, g, p, length[:, :, slot],
                            angle[:, :, slot], tors[:, :, slot])
        keep = build[:, :, slot][..., None]
        fallback = p  # unbuilt slots collapse onto the parent atom
        coords = coords.at[:, :, slot].set(placed * keep +
                                           fallback * (1 - keep))

    if cloud_mask is not None:
        coords = coords * cloud_mask[..., None].astype(coords.dtype)
    return coords
