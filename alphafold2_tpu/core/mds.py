"""Multidimensional scaling: distance matrix -> 3-D coordinates.

The legacy decode path of the reference (distogram -> central distances ->
MDS -> mirror fix; /root/reference/alphafold2_pytorch/utils.py:764-879,
1162-1201, 1254-1279). TPU-first differences:

- eigen initialization uses one batched `jnp.linalg.svd` (the reference
  loops svd_lowrank per sample, utils.py:785-791 — a CPU-side
  micro-optimization that is backwards on an accelerator);
- the Guttman-transform iteration runs under `lax.scan` with a fixed
  iteration count (static shapes; no data-dependent early exit inside jit —
  the converged iterations become cheap no-ops via a `done` flag);
- the chirality mirror fix flips the z-axis when fewer than half of the
  backbone phi dihedrals are negative (utils.py:917-956, 1172-1176),
  vectorized with `where` instead of index assignment.

Coordinates convention here: (..., N, 3) points-last like the rest of this
package (the reference returns (batch, 3, N)).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from alphafold2_tpu.core import geometry as geo


class MDSResult(NamedTuple):
    coords: jnp.ndarray          # (b, n, 3)
    stress_history: jnp.ndarray  # (iters, b) normalized stress per iteration


def eigen_init(dist_mat: jnp.ndarray) -> jnp.ndarray:
    """Classical-MDS initialization from the squared-distance Gram matrix
    (reference utils.py:783-791). dist_mat: (b, n, n) -> (b, n, 3)."""
    d2 = dist_mat ** 2
    m = 0.5 * (d2[:, :1, :] + d2[:, :, :1] - d2)
    u, s, _ = jnp.linalg.svd(m)
    coords = u * jnp.sqrt(jnp.abs(s))[..., None, :]
    return coords[..., :3]


def mds(
    dist_mat: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
    iters: int = 10,
    tol: float = 1e-5,
    eigen_only: bool = False,
) -> MDSResult:
    """Weighted MDS via eigen init + Guttman transform iterations
    (reference mds_torch, utils.py:766-833).

    dist_mat: (b, n, n) target distances; weights: (b, n, n) per-pair
    confidence (from `geometry.center_distogram`).
    """
    b, n, _ = dist_mat.shape
    coords = eigen_init(dist_mat)

    if eigen_only and weights is None:
        return MDSResult(coords, jnp.zeros((0, b), dist_mat.dtype))

    w = jnp.ones_like(dist_mat) if weights is None else weights
    eye = jnp.eye(n, dtype=dist_mat.dtype)

    def guttman(carry, _):
        coords, last_stress, done = carry
        cur = geo.cdist(coords, coords)
        stress = 0.5 * (w * (cur - dist_mat) ** 2).sum((-1, -2))

        cur_safe = jnp.where(cur <= 0, cur + 1e-7, cur)
        ratio = w * dist_mat / cur_safe
        # Guttman transform matrix: B = -ratio with row sums on the diagonal
        bmat = -ratio + eye * ratio.sum(-1)[..., None, :]

        new_coords = bmat @ coords / n
        norm = jnp.linalg.norm(new_coords, axis=(-1, -2))
        rel = stress / jnp.maximum(norm, 1e-9)

        # freeze once the relative improvement drops below tol (static-shape
        # replacement for the reference's Python `break`, utils.py:824-828)
        improved = (last_stress - rel) > tol
        new_done = done | ~improved
        coords = jnp.where(new_done[..., None, None], coords, new_coords)
        return (coords, jnp.where(new_done, last_stress, rel), new_done), rel

    init = (coords, jnp.full((b,), jnp.inf, dist_mat.dtype),
            jnp.zeros((b,), bool))
    (coords, _, _), history = jax.lax.scan(guttman, init, None, length=iters)
    return MDSResult(coords, history)


def mirror_fix(
    coords: jnp.ndarray,
    n_idx: jnp.ndarray,
    ca_idx: jnp.ndarray,
    c_idx: jnp.ndarray,
) -> jnp.ndarray:
    """Pick the correct chirality mirror: if fewer than half the phi
    dihedrals are negative, flip z (reference utils.py:1172-1176).

    coords: (b, n_points, 3) backbone point cloud; *_idx: static integer
    index arrays selecting N/CA/C atoms per residue (same length L).
    """
    nc = coords[:, n_idx]
    ca = coords[:, ca_idx]
    cc = coords[:, c_idx]
    frac_neg = geo.fraction_negative_phis(nc, ca, cc)
    flip = (frac_neg < 0.5)[..., None, None]
    return jnp.where(flip, coords * jnp.array([1.0, 1.0, -1.0]), coords)


def mdscaling(
    dist_mat: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
    iters: int = 10,
    tol: float = 1e-5,
    fix_mirror: bool = True,
    n_idx: Optional[jnp.ndarray] = None,
    ca_idx: Optional[jnp.ndarray] = None,
    c_idx: Optional[jnp.ndarray] = None,
    eigen_only: bool = False,
) -> MDSResult:
    """MDS + protein-specific mirror handling (reference mdscaling_torch,
    utils.py:1162-1180; public wrapper utils.py:1254-1279)."""
    result = mds(dist_mat, weights=weights, iters=iters, tol=tol,
                 eigen_only=eigen_only)
    if not fix_mirror:
        return result
    assert n_idx is not None and ca_idx is not None and c_idx is not None, \
        "mirror fixing needs N/CA/C index arrays"
    coords = mirror_fix(result.coords, n_idx, ca_idx, c_idx)
    return MDSResult(coords, result.stress_history)
