"""Rigid-body frames (rotation + translation) for the structure module.

The reference keeps frames as raw (quaternions, translations) tensors inside
`Alphafold2.forward` (alphafold2.py:857-891); here they are a first-class
pytree so they can flow through `lax.scan`, `jit` and shardings untouched.

Convention (matches the reference's einsums at alphafold2.py:887,891):
  global = local @ R + t      # row-vector application
with R = quaternion_to_matrix(q). Composition of an update (dq, dt) in the
local frame is q <- q * dq, t <- t + dt @ R.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from alphafold2_tpu.core import quaternion as quat


class Rigid(NamedTuple):
    """Batch of rigid frames; quaternions (..., 4) wxyz, translations (..., 3)."""

    quaternions: jnp.ndarray
    translations: jnp.ndarray

    @classmethod
    def identity(cls, shape=(), dtype=jnp.float32) -> "Rigid":
        return cls(
            quaternions=quat.identity_quaternion(shape, dtype),
            translations=jnp.zeros((*shape, 3), dtype=dtype),
        )

    @property
    def rotations(self) -> jnp.ndarray:
        return quat.quaternion_to_matrix(self.quaternions)

    def apply(self, points: jnp.ndarray) -> jnp.ndarray:
        """local (..., P, 3) -> global, broadcasting frames over P."""
        r = self.rotations
        return jnp.einsum("...pc,...cd->...pd", points, r) + \
            self.translations[..., None, :]

    def apply_single(self, points: jnp.ndarray) -> jnp.ndarray:
        """local (..., 3) -> global, one point per frame
        (reference alphafold2.py:891)."""
        return jnp.einsum("...c,...cd->...d", points, self.rotations) + \
            self.translations

    def invert_apply(self, points: jnp.ndarray) -> jnp.ndarray:
        """global (..., P, 3) -> local, broadcasting frames over P."""
        r = self.rotations
        local = points - self.translations[..., None, :]
        return jnp.einsum("...pd,...cd->...pc", local, r)

    def compose_update(self, dq: jnp.ndarray, dt: jnp.ndarray) -> "Rigid":
        """Apply a local-frame update (reference alphafold2.py:886-887):
        q <- q * dq (Hamilton), t <- t + dt @ R."""
        r = self.rotations
        new_q = quat.quaternion_multiply(self.quaternions, dq)
        new_t = self.translations + jnp.einsum("...c,...cd->...d", dt, r)
        return Rigid(new_q, new_t)
