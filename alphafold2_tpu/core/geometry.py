"""Geometry and structure-quality metrics, pure JAX.

Capability parity with the reference geometry layer
(/root/reference/alphafold2_pytorch/utils.py:45-50, 718-761, 881-1247,
1254-1344) — distance binning, distogram centering, dihedrals, Kabsch
alignment, RMSD / GDT / TM-score / lDDT, and the distance-matrix loss.

TPU-first design notes:
- everything is batched, mask-aware and static-shaped (no boolean indexing —
  the torch reference's `t[mask]` patterns do not compile under XLA);
- all functions are differentiable and `jit`/`vmap`-compatible;
- convention: coordinates are (..., N, 3) ("points-last-dim"), unlike the
  reference's (B, 3, N). The wrappers in this module accept (..., N, 3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from alphafold2_tpu import constants

# ---------------------------------------------------------------------------
# Pairwise distances & distogram targets
# ---------------------------------------------------------------------------


def cdist(x: jnp.ndarray, y: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """Pairwise Euclidean distances. x: (..., N, D), y: (..., M, D)."""
    d2 = jnp.sum((x[..., :, None, :] - y[..., None, :, :]) ** 2, axis=-1)
    # sqrt has an unstable gradient at exactly 0 (the diagonal); clamp.
    return jnp.sqrt(jnp.maximum(d2, eps))


def distogram_boundaries(
    num_buckets: int = constants.DISTOGRAM_BUCKETS,
    min_dist: float = constants.DISTOGRAM_MIN_DIST,
    max_dist: float = constants.DISTOGRAM_MAX_DIST,
) -> jnp.ndarray:
    """linspace(2, 20, B); reference utils.py:41,47."""
    return jnp.linspace(min_dist, max_dist, num_buckets)


def bucketed_distance_matrix(
    coords: jnp.ndarray,
    mask: jnp.ndarray,
    num_buckets: int = constants.DISTOGRAM_BUCKETS,
    ignore_index: int = constants.IGNORE_INDEX,
) -> jnp.ndarray:
    """Distogram CE targets (reference utils.py:45-50).

    coords: (..., N, 3); mask: (..., N) bool. Returns (..., N, N) int32 with
    `ignore_index` outside the pair mask.
    """
    distances = cdist(coords, coords)
    boundaries = distogram_boundaries(num_buckets)[:-1]
    # side='left' == torch.bucketize default (right=False): a value exactly
    # on a boundary stays in the lower bucket
    buckets = jnp.searchsorted(boundaries, distances, side="left")
    pair_mask = mask[..., :, None] & mask[..., None, :]
    return jnp.where(pair_mask, buckets, ignore_index).astype(jnp.int32)


def center_distogram(
    distogram: jnp.ndarray,
    bins: jnp.ndarray | None = None,
    center: str = "mean",
    wide: str = "std",
    eps: float = 1e-7,
):
    """Central distance estimate + confidence weights from a distogram
    (reference utils.py:718-761).

    distogram: (..., N, N, B) non-negative bin weights (probabilities ok).
    Returns (central (..., N, N), weights (..., N, N)).
    """
    if bins is None:
        bins = distogram_boundaries()
    # bin centers: shift down half a step; first bin -> 1.5 A, last bin is
    # the catch-all "far" bin at 1.33 * max (reference utils.py:731-733).
    step = bins[2] - bins[1]
    n_bins = bins - 0.5 * step
    n_bins = n_bins.at[0].set(1.5)
    n_bins = n_bins.at[-1].set(1.33 * bins[-1])

    magnitudes = distogram.sum(axis=-1)

    if center == "median":
        cum = jnp.cumsum(distogram, axis=-1)
        target = 0.5 * cum[..., -1:]
        idx = jnp.sum(cum < target, axis=-1)
        idx = jnp.minimum(idx, n_bins.shape[0] - 1)
        central = n_bins[idx]
    else:  # mean
        central = (distogram * n_bins).sum(axis=-1) / (magnitudes + eps)

    # pairs predicted beyond the last real bin are ignored downstream
    valid = (central <= bins[-2]).astype(distogram.dtype)

    n = distogram.shape[-2]
    eye = jnp.eye(n, dtype=distogram.dtype)
    central = central * (1.0 - eye)  # zero diagonal

    if wide in ("var", "std"):
        disp = (distogram * (n_bins - central[..., None]) ** 2).sum(axis=-1)
        disp = disp / (magnitudes + eps)
        if wide == "std":
            disp = jnp.sqrt(jnp.maximum(disp, 0.0))
    else:
        disp = jnp.zeros_like(central)

    weights = valid / (1.0 + disp)
    weights = jnp.nan_to_num(weights) * (1.0 - eye)
    return central, weights


# ---------------------------------------------------------------------------
# Dihedrals
# ---------------------------------------------------------------------------


def dihedral(c1, c2, c3, c4) -> jnp.ndarray:
    """Dihedral angle (radians) via the atan2 polymer-physics formula
    (reference utils.py:881-897). Inputs (..., 3), output (...,)."""
    u1 = c2 - c1
    u2 = c3 - c2
    u3 = c4 - c3
    c12 = jnp.cross(u1, u2)
    c23 = jnp.cross(u2, u3)
    y = jnp.sum(jnp.linalg.norm(u2, axis=-1, keepdims=True) * u1 * c23, axis=-1)
    x = jnp.sum(c12 * c23, axis=-1)
    return jnp.arctan2(y, x)


def backbone_phis(n_coords, ca_coords, c_coords) -> jnp.ndarray:
    """Phi dihedrals C(-1)-N-CA-C per residue 1..L-1 (reference
    utils.py:917-956, vectorized). Inputs (..., L, 3); output (..., L-1)."""
    return dihedral(
        c_coords[..., :-1, :],
        n_coords[..., 1:, :],
        ca_coords[..., 1:, :],
        c_coords[..., 1:, :],
    )


def fraction_negative_phis(n_coords, ca_coords, c_coords, mask=None):
    """Proportion of negative phi angles, the mirror-selection statistic
    (reference utils.py:948-956). Output (...,)."""
    phis = backbone_phis(n_coords, ca_coords, c_coords)
    neg = (phis < 0).astype(jnp.float32)
    if mask is not None:
        m = (mask[..., :-1] & mask[..., 1:]).astype(jnp.float32)
        return (neg * m).sum(-1) / jnp.maximum(m.sum(-1), 1.0)
    return neg.mean(-1)


# ---------------------------------------------------------------------------
# Kabsch alignment
# ---------------------------------------------------------------------------


def kabsch(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray | None = None,
):
    """Optimal-rotation alignment of x onto y (reference utils.py:999-1029).

    x, y: (..., N, 3); mask: (..., N) optional. Returns (x_aligned, y_centered)
    both centered at the origin, with x rotated onto y. Differentiable; the
    SVD sign fix uses `where` instead of Python branching so it is jittable.
    """
    if mask is None:
        w = jnp.ones(x.shape[:-1], dtype=x.dtype)
    else:
        w = mask.astype(x.dtype)
    wsum = jnp.maximum(w.sum(-1, keepdims=True), 1.0)[..., None]
    x_mu = (x * w[..., None]).sum(-2, keepdims=True) / wsum
    y_mu = (y * w[..., None]).sum(-2, keepdims=True) / wsum
    x_c = (x - x_mu) * w[..., None]
    y_c = (y - y_mu) * w[..., None]

    # covariance (3,3); stop-gradient like the reference's `.detach()` at
    # utils.py:1008 so alignment is treated as a constant rotation in the vjp
    c = jax.lax.stop_gradient(jnp.swapaxes(x_c, -1, -2) @ y_c)
    u, s, vt = jnp.linalg.svd(c, full_matrices=False)
    det = jnp.linalg.det(u) * jnp.linalg.det(vt)
    flip = jnp.where(det < 0, -1.0, 1.0)[..., None]
    u = u.at[..., :, -1].multiply(flip)
    rot = u @ vt
    return x_c @ rot, y_c


# ---------------------------------------------------------------------------
# Metrics (reference utils.py:1098-1247)
# ---------------------------------------------------------------------------


def _masked_mean(x, mask, axis):
    if mask is None:
        return x.mean(axis=axis)
    m = mask.astype(x.dtype)
    return (x * m).sum(axis=axis) / jnp.maximum(m.sum(axis=axis), 1.0)


def rmsd(x, y, mask=None) -> jnp.ndarray:
    """RMSD between point sets (..., N, 3) -> (...,). Matches reference
    rmsd_torch (utils.py:1098-1100): mean over both coord dim and points."""
    sq = (x - y) ** 2
    if mask is not None:
        m = mask[..., None].astype(x.dtype)
        return jnp.sqrt((sq * m).sum((-1, -2)) /
                        jnp.maximum(3.0 * mask.astype(x.dtype).sum(-1), 1.0))
    return jnp.sqrt(sq.mean((-1, -2)))


def gdt(x, y, mask=None, mode: str = "TS", weights=None) -> jnp.ndarray:
    """GDT_TS / GDT_HA (reference utils.py:1106-1141, 1313-1327)."""
    cutoffs = jnp.array([0.5, 1.0, 2.0, 4.0] if mode.upper() == "HA"
                        else [1.0, 2.0, 4.0, 8.0], dtype=x.dtype)
    if weights is None:
        weights = jnp.ones_like(cutoffs)
    else:
        weights = jnp.asarray(weights, dtype=x.dtype)
    dist = jnp.linalg.norm(x - y, axis=-1)  # (..., N)
    under = (dist[..., None, :] <= cutoffs[:, None]).astype(x.dtype)
    frac = _masked_mean(under, None if mask is None else mask[..., None, :], -1)
    return (frac * weights).mean(-1)


def tm_score(x, y, mask=None) -> jnp.ndarray:
    """TM-score (reference utils.py:1143-1150). x, y: (..., N, 3)."""
    n = x.shape[-2] if mask is None else jnp.maximum(
        mask.astype(x.dtype).sum(-1), 1.0)
    l_eff = jnp.maximum(15.0, jnp.asarray(n, dtype=x.dtype))
    d0 = 1.24 * jnp.cbrt(l_eff - 15.0) - 1.8
    dist = jnp.linalg.norm(x - y, axis=-1)
    score = 1.0 / (1.0 + (dist / d0[..., None]) ** 2)
    return _masked_mean(score, mask, -1)


def lddt_ca(
    true_ca: jnp.ndarray,
    pred_ca: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    r0: float = 15.0,
    thresholds=(0.5, 1.0, 2.0, 4.0),
) -> jnp.ndarray:
    """Per-residue CA lDDT in [0, 1] (reference utils.py:1204-1247),
    vectorized & mask-based instead of the reference's boolean indexing.

    true_ca, pred_ca: (..., L, 3) C-alpha coordinates; mask: (..., L).
    Returns (..., L).
    """
    if mask is None:
        mask = jnp.ones(true_ca.shape[:-1], dtype=bool)
    m = mask.astype(true_ca.dtype)
    pair_m = m[..., :, None] * m[..., None, :]
    n = true_ca.shape[-2]
    off_diag = 1.0 - jnp.eye(n, dtype=true_ca.dtype)
    pair_m = pair_m * off_diag

    dt = cdist(true_ca, true_ca)
    dp = cdist(pred_ca, pred_ca)
    incl = (dt < r0).astype(true_ca.dtype) * pair_m
    diff = jnp.abs(dp - dt)
    th = jnp.asarray(thresholds, dtype=true_ca.dtype)
    ok = (diff[..., None] < th).astype(true_ca.dtype).mean(-1)
    denom = jnp.maximum(incl.sum(-1), 1e-9)
    return (ok * incl).sum(-1) / denom * m


def distmat_loss(
    x=None, y=None, x_mat=None, y_mat=None,
    p: float = 2.0, q: float = 2.0, mask=None, clamp=None,
) -> jnp.ndarray:
    """Alignment-free distance-matrix loss (reference utils.py:1057-1096)."""
    if x_mat is None:
        if clamp is not None:
            x = jnp.clip(x, *clamp)
        x_mat = cdist(x, x) if p == 2 else (
            jnp.abs(x[..., :, None, :] - x[..., None, :, :]) ** p
        ).sum(-1) ** (1.0 / p)
    if y_mat is None:
        if clamp is not None:
            y = jnp.clip(y, *clamp)
        y_mat = cdist(y, y) if p == 2 else (
            jnp.abs(y[..., :, None, :] - y[..., None, :, :]) ** p
        ).sum(-1) ** (1.0 / p)
    loss = (x_mat - y_mat) ** 2
    if q != 2:
        loss = loss ** (q / 2.0)
    if mask is None:
        return loss.mean()
    m = mask.astype(loss.dtype)
    return (loss * m).sum() / jnp.maximum(m.sum(), 1.0)


# ---------------------------------------------------------------------------
# Aligned-metric conveniences
# ---------------------------------------------------------------------------


def kabsch_rmsd(x, y, mask=None) -> jnp.ndarray:
    """RMSD after optimal alignment of x onto y."""
    x_a, y_c = kabsch(x, y, mask=mask)
    return rmsd(x_a, y_c, mask=mask)


def kabsch_tm(x, y, mask=None) -> jnp.ndarray:
    x_a, y_c = kabsch(x, y, mask=mask)
    return tm_score(x_a, y_c, mask=mask)


def kabsch_gdt(x, y, mask=None, mode: str = "TS") -> jnp.ndarray:
    x_a, y_c = kabsch(x, y, mask=mask)
    return gdt(x_a, y_c, mask=mask, mode=mode)
