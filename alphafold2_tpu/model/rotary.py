"""Rotary / fixed sinusoidal positional embeddings.

Parity with the reference's rotary module
(/root/reference/alphafold2_pytorch/rotary.py — vestigial there, kept for
README-era API coverage): `rotate_every_two` + `apply_rotary_pos_emb`
(rotary.py:9-20), sinusoidal `FixedPositionalEmbedding` (rotary.py:35-45),
and the 2-D `AxialRotaryEmbedding` for pair-map axial attention
(rotary.py:47-67). Pure functions over explicit lengths — no buffers, no
device state.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def rotate_every_two(x: jnp.ndarray) -> jnp.ndarray:
    """(..., 2k) -> pairwise (x1, x2) -> (-x2, x1) interleave."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out = jnp.stack([-x2, x1], axis=-1)
    return out.reshape(*x.shape)


def apply_rotary_pos_emb(x: jnp.ndarray, sinu_pos: Tuple[jnp.ndarray,
                                                         jnp.ndarray]):
    """Rotate features by position: x*cos + rotate_every_two(x)*sin.
    sinu_pos: (sin, cos) each (..., n, d_rot). When d_rot < x's feature
    dim, only the first d_rot channels rotate and the rest pass through
    (the reference's partial-rotation behavior, rotary.py:15-20)."""
    sin, cos = sinu_pos
    rot_dim = sin.shape[-1]
    if rot_dim < x.shape[-1]:
        x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
        x_rot = x_rot * cos + rotate_every_two(x_rot) * sin
        return jnp.concatenate([x_rot, x_pass], axis=-1)
    return x * cos + rotate_every_two(x) * sin


def fixed_positional_embedding(seq_len: int, dim: int,
                               dtype=jnp.float32):
    """Sinusoidal (sin, cos) tables, each (seq_len, dim) with frequencies
    duplicated pairwise so they align with rotate_every_two."""
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, dim, 2, dtype=dtype) / dim))
    t = jnp.arange(seq_len, dtype=dtype)
    freqs = jnp.einsum("i,j->ij", t, inv_freq)
    freqs = jnp.repeat(freqs, 2, axis=-1)
    return jnp.sin(freqs), jnp.cos(freqs)


def axial_rotary_embedding(height: int, width: int, dim: int,
                           dtype=jnp.float32):
    """2-D rotary tables for an (i, j) pair map: half the channels encode
    the row coordinate, half the column (reference rotary.py:47-67).
    Returns (sin, cos) each (height, width, dim)."""
    assert dim % 4 == 0, \
        "axial rotary needs dim % 4 == 0 (two rotary halves of even width)"
    half = dim // 2
    sin_h, cos_h = fixed_positional_embedding(height, half, dtype)
    sin_w, cos_w = fixed_positional_embedding(width, half, dtype)
    sin = jnp.concatenate([
        jnp.broadcast_to(sin_h[:, None, :], (height, width, half)),
        jnp.broadcast_to(sin_w[None, :, :], (height, width, half)),
    ], axis=-1)
    cos = jnp.concatenate([
        jnp.broadcast_to(cos_h[:, None, :], (height, width, half)),
        jnp.broadcast_to(cos_w[None, :, :], (height, width, half)),
    ], axis=-1)
    return sin, cos
