"""Evoformer trunk: MSA/pair blocks and the scanned, rematerialized stack.

Parity with the reference (/root/reference/alphafold2_pytorch/alphafold2.py:
353-467): `PairwiseAttentionBlock` (outer-mean ingest + triangle mult out/in +
triangle attention out/in), `MsaAttentionBlock` (row attn with pair bias, col
attn), `EvoformerBlock` (msa attn -> msa FF -> pair attn -> pair FF, all
residual), `Evoformer` = depth x block.

TPU-first: instead of the reference's `checkpoint_sequential` (alphafold2.py:
466), the stack runs under `nn.scan` over depth with per-layer remat
(`nn.remat`) — constant compile time at depth 48 and O(1) stored activations
per block, with XLA re-materializing each block's interior in the backward
pass. Pair/MSA activations carry sharding constraints so the stack runs
identically under a pjit mesh (see alphafold2_tpu/parallel).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.model.attention_variants import (
    DEFAULT_CONV_MSA_KERNELS,
    DEFAULT_CONV_SEQ_KERNELS,
    MultiKernelConvBlock,
)
from alphafold2_tpu.model.primitives import (
    AxialAttention,
    FeedForward,
    OuterMean,
    TriangleMultiplicativeModule,
)
from alphafold2_tpu.parallel.mesh import PAIR_I_AXIS, PAIR_J_AXIS
from alphafold2_tpu.parallel.sharding import shard_msa, shard_pair


class PairwiseAttentionBlock(nn.Module):
    """Pair-track block (reference alphafold2.py:353-385).

    `ring_attention=True` runs the two triangle attentions ring-parallel
    over the sharded pair axes when an active mesh shards them
    (AxialAttention.ring_axes; parallel/ring.py) — the long-context mode.
    """

    dim: int
    heads: int
    dim_head: int = 64
    dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, msa_repr=None, msa_mask=None,
                 deterministic: bool = True):
        ring_axes = (PAIR_I_AXIS, PAIR_J_AXIS) if self.ring_attention \
            else None
        if msa_repr is not None:
            x = x + OuterMean(dim=self.dim, dtype=self.dtype,
                              reference_scale=self.outer_mean_reference_scale,
                              name="outer_mean")(msa_repr, mask=msa_mask)
            x = shard_pair(x)

        x = TriangleMultiplicativeModule(
            dim=self.dim, mix="outgoing", dtype=self.dtype,
            name="triangle_multiply_outgoing")(x, mask=mask) + x
        x = TriangleMultiplicativeModule(
            dim=self.dim, mix="ingoing", dtype=self.dtype,
            name="triangle_multiply_ingoing")(x, mask=mask) + x
        x = shard_pair(x)
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=True, col_attn=False, accept_edges=True,
            dropout=self.dropout, ring_axes=ring_axes,
            dtype=self.dtype, name="triangle_attention_outgoing",
        )(x, edges=x, mask=mask, deterministic=deterministic) + x
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=False, col_attn=True, accept_edges=True,
            global_query_attn=self.global_column_attn,
            dropout=self.dropout, ring_axes=ring_axes,
            dtype=self.dtype, name="triangle_attention_ingoing",
        )(x, edges=x, mask=mask, deterministic=deterministic) + x
        return shard_pair(x)


class MsaAttentionBlock(nn.Module):
    """MSA-track block (reference alphafold2.py:387-408).

    `ring_attention=True` runs the row attention (per-alignment attention
    over the residue axis, which `shard_msa` shards over the `i` mesh
    axis) ring-parallel instead of letting GSPMD all-gather the full
    residue axis (round-2 VERDICT next-round #5). Column attention is
    over the alignment axis, which is never mesh-sharded — dense there.

    `row_variant` swaps the residue-axis row attention for one of the
    README-era efficient variants (reference README.md:388-487 — there
    they applied to the pre-Evoformer sequence/MSA self- and cross-
    attention; here the residue axis is where the O(n^2) pressure lives):

    - "full"     — pair-biased axial attention (the default Evoformer row
                   attention; the only variant that consumes pair edges);
    - "sparse"   — `BlockSparseAttention` local+global block pattern (the
                   DeepSpeed sparse-self-attn analog, README.md:388-417;
                   dispatches to the Pallas block-skipping kernel on TPU
                   by default — `ops.use_pallas_attention(True)` opts in
                   the interpreter-mode kernel off-TPU, otherwise CPU
                   keeps the masked-dense fallback);
    - "linear"   — kernelized linear attention (Performer slot,
                   README.md:419-449);
    - "compress" — memory-compressed attention, K/V mean-pooled by
                   `kv_compress_ratio` (README.md:475-487);
    - "kron"     — cross-attention onto the axial-pooled (H+W token) pair
                   map (README.md:451-468's Kronecker operator, re-aimed
                   at the Evoformer's pair context).

    The non-full variants do not take the pair-edge bias — matching the
    README-era modules, which had no pair track to be biased by.
    """

    dim: int
    heads: int
    dim_head: int = 64
    dropout: float = 0.0
    ring_attention: bool = False
    row_variant: str = "full"
    sparse_block: int = 32
    sparse_num_global: int = 1
    sparse_window: int = 1
    kv_compress_ratio: int = 2
    # "linear" row variant backend: "favor" = FAVOR+ Performer (unbiased
    # softmax approximation, the reference's cross_attn_linear), "elu" =
    # the cheap deterministic elu+1 kernel
    linear_attn_kind: str = "favor"
    performer_nb_features: int = 256
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, pairwise_repr=None, pair_mask=None,
                 deterministic: bool = True):
        if self.row_variant == "full":
            x = AxialAttention(
                dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                row_attn=True, col_attn=False, accept_edges=True,
                dropout=self.dropout,
                ring_axes=(None, PAIR_I_AXIS) if self.ring_attention
                else None,
                dtype=self.dtype, name="row_attn",
            )(x, mask=mask, edges=pairwise_repr,
              deterministic=deterministic) + x
        else:
            x = self._row_variant_attn(x, mask, pairwise_repr, pair_mask,
                                       deterministic) + x
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=False, col_attn=True, dropout=self.dropout,
            # the column track attends ALIGNMENT rows — a serving
            # KernelSpec's residue-axis block pattern must never apply
            # here, even when msa_depth happens to equal the bucket
            # length (ISSUE 12)
            sparse_kernel_ok=False,
            dtype=self.dtype, name="col_attn",
        )(x, mask=mask, deterministic=deterministic) + x
        return shard_msa(x)

    def _row_variant_attn(self, x, mask, pairwise_repr, pair_mask,
                          deterministic=True):
        """Residue-axis attention via an efficient variant: alignment rows
        fold into batch (as AxialAttention does), pre-LN applied here (the
        variants are bare attention modules; AxialAttention normalizes
        internally). `dropout` reaches the softmax-matrix variants
        (sparse/compress/kron); the linear variants have no attention
        matrix to drop entries from (performer-pytorch likewise)."""
        from alphafold2_tpu.model.attention_variants import (
            BlockSparseAttention,
            LinearAttention,
            MemoryCompressedAttention,
            kronecker_pool_2d,
        )
        from alphafold2_tpu.model.primitives import Attention, LayerNorm

        b, rows, n, d = x.shape
        h = LayerNorm(dtype=self.dtype, name="row_norm")(x)
        hf = h.reshape(b * rows, n, d)
        mf = None if mask is None else mask.reshape(b * rows, n)
        kw = dict(dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                  dtype=self.dtype, name="row_attn")

        if self.row_variant == "sparse":
            out = BlockSparseAttention(
                block=self.sparse_block, num_global=self.sparse_num_global,
                window=self.sparse_window, dropout=self.dropout, **kw)(
                    hf, mask=mf, deterministic=deterministic)
        elif self.row_variant == "linear":
            if self.linear_attn_kind == "favor":
                from alphafold2_tpu.model.attention_variants import (
                    PerformerAttention)
                out = PerformerAttention(
                    nb_features=self.performer_nb_features, **kw)(
                        hf, mask=mf)
            else:
                out = LinearAttention(**kw)(hf, mask=mf)
        elif self.row_variant == "compress":
            out = MemoryCompressedAttention(
                compress_ratio=self.kv_compress_ratio,
                dropout=self.dropout, **kw)(
                    hf, mask=mf, deterministic=deterministic)
        elif self.row_variant == "kron":
            assert pairwise_repr is not None, \
                "row_variant='kron' needs the pair representation"
            pooled, tmask = kronecker_pool_2d(pairwise_repr, pair_mask)
            # one pooled context per batch item, shared by its alignment
            # rows (repeat matches the row-major fold of x above)
            pooled = jnp.repeat(pooled, rows, axis=0)
            tmask = jnp.repeat(tmask, rows, axis=0)
            if mf is None:
                # Attention only honors context_mask alongside a query
                # mask; synthesize all-ones so padded pooled tokens are
                # still excluded when msa_mask is absent
                mf = jnp.ones((b * rows, n), dtype=bool)
            out = Attention(dropout=self.dropout, **kw)(
                hf, mask=mf, context=pooled, context_mask=tmask,
                deterministic=deterministic)
        else:
            raise ValueError(f"unknown row_variant {self.row_variant!r}")
        return out.reshape(b, rows, n, d)


class EvoformerBlock(nn.Module):
    """One Evoformer layer (reference alphafold2.py:412-446).

    `use_conv=True` appends trRosetta2-style residual conv blocks to both
    tracks (the README-era `use_conv` menu item, README.md:271-340):
    `conv_seq_kernels` over the (n, n) pair map, `conv_msa_kernels` over
    the (rows, n) MSA, with the dilation cycle applied in-block
    (attention_variants.MultiKernelConvBlock documents the TPU-first
    deviations)."""

    dim: int
    heads: int
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    # README-era efficient-attention menu for the MSA row track
    # (MsaAttentionBlock.row_variant documents the options)
    msa_row_variant: str = "full"
    sparse_block: int = 32
    sparse_num_global: int = 1
    sparse_window: int = 1
    kv_compress_ratio: int = 2
    linear_attn_kind: str = "favor"
    performer_nb_features: int = 256
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        # msa attention and transition
        m = MsaAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout, ring_attention=self.ring_attention,
            row_variant=self.msa_row_variant,
            sparse_block=self.sparse_block,
            sparse_num_global=self.sparse_num_global,
            sparse_window=self.sparse_window,
            kv_compress_ratio=self.kv_compress_ratio,
            linear_attn_kind=self.linear_attn_kind,
            performer_nb_features=self.performer_nb_features,
            dtype=self.dtype, name="msa_attn",
        )(m, mask=msa_mask, pairwise_repr=x, pair_mask=mask,
          deterministic=deterministic)
        m = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                        dtype=self.dtype, name="msa_ff")(
                            m, deterministic=deterministic) + m
        if self.use_conv:
            m = MultiKernelConvBlock(
                dim=self.dim, kernels=self.conv_msa_kernels,
                dilations=self.conv_dilations, dtype=self.dtype,
                name="msa_conv")(m, mask=msa_mask) + m

        # pairwise attention (ingesting the updated MSA) and transition
        x = PairwiseAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            dtype=self.dtype, name="attn",
        )(x, mask=mask, msa_repr=m, msa_mask=msa_mask,
          deterministic=deterministic)
        x = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                        dtype=self.dtype, name="ff")(
                            x, deterministic=deterministic) + x
        if self.use_conv:
            x = MultiKernelConvBlock(
                dim=self.dim, kernels=self.conv_seq_kernels,
                dilations=self.conv_dilations, dtype=self.dtype,
                name="pair_conv")(x, mask=mask) + x

        return x, m


class Evoformer(nn.Module):
    """depth x EvoformerBlock under scan + remat (reference alphafold2.py:
    448-467; memory scaling via checkpoint_sequential there, jax.remat here).
    """

    dim: int
    depth: int
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    # README-era efficient-attention menu (reference README.md:388-487),
    # applied to the MSA row track (MsaAttentionBlock.row_variant). Each
    # flag is a bool (all layers) or a per-layer tuple of bools — e.g.
    # `sparse_self_attn=(True, False) * 3` interleaves sparse and full
    # layers (README.md:415). `kv_compress_ratio` is 0 (off) or the pool
    # ratio (README.md:485), scalar or per-layer. At most one variant may
    # be on per layer. Per-layer-heterogeneous menus run the unrolled
    # trunk (nn.scan needs layer-uniform params; the README-era reference
    # was an unrolled torch stack too) and are incompatible with
    # `pipeline_stages`/`reversible`, which regroup scan-stacked params.
    sparse_self_attn: "bool | tuple" = False
    linear_attn: "bool | tuple" = False
    kron_attn: "bool | tuple" = False
    kv_compress_ratio: "int | tuple" = 0
    sparse_block: int = 32
    sparse_num_global: int = 1
    sparse_window: int = 1
    linear_attn_kind: str = "favor"
    performer_nb_features: int = 256
    dtype: jnp.dtype = jnp.float32
    use_scan: bool = True
    # O(1)-activation reversible trunk (model/reversible.py; reference
    # README.md:40 `reversible=True`, reversible.py)
    reversible: bool = False
    # GPipe pipeline parallelism over the mesh's `pipe` axis
    # (parallel/pipeline.py): the depth-stacked scan params are regrouped
    # into S stages of depth/S layers and the trunk runs the static skew
    # schedule, microbatching the batch axis. Params are IDENTICAL to the
    # scanned trunk (the pp path re-reads the scan's stacked params), so
    # checkpoints move freely between pp and non-pp runs.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0   # 0 -> one microbatch per batch row

    def _row_variants(self):
        """Per-layer MSA-row attention variants + compress ratios.

        Returns (variants, ratios): depth-length tuples of variant names
        and kv-pool ratios, validated to at most one variant per layer."""
        def flags(v, label):
            if isinstance(v, (tuple, list)):
                assert len(v) == self.depth, \
                    f"{label} tuple has {len(v)} entries for depth " \
                    f"{self.depth}"
                return tuple(bool(b) for b in v)
            return (bool(v),) * self.depth

        sp = flags(self.sparse_self_attn, "sparse_self_attn")
        li = flags(self.linear_attn, "linear_attn")
        kr = flags(self.kron_attn, "kron_attn")
        cr = self.kv_compress_ratio
        if isinstance(cr, (tuple, list)):
            assert len(cr) == self.depth, \
                f"kv_compress_ratio tuple has {len(cr)} entries for " \
                f"depth {self.depth}"
            cr = tuple(int(c) for c in cr)
        else:
            cr = (int(cr),) * self.depth

        variants = []
        for i in range(self.depth):
            picks = [name for name, on in (
                ("sparse", sp[i]), ("linear", li[i]), ("kron", kr[i]),
                ("compress", cr[i] > 0)) if on]
            assert len(picks) <= 1, \
                f"layer {i}: conflicting attention variants {picks} — " \
                "at most one of sparse_self_attn/linear_attn/kron_attn/" \
                "kv_compress_ratio per layer"
            variants.append(picks[0] if picks else "full")
        return tuple(variants), cr

    def _pipeline_ready(self, deterministic):
        """The active mesh if the pipeline path applies, else None."""
        from alphafold2_tpu.parallel.sharding import active_mesh
        from alphafold2_tpu.parallel.mesh import PIPE_AXIS

        if self.pipeline_stages <= 1:
            return None
        mesh = active_mesh()
        if mesh is None or PIPE_AXIS not in mesh.axis_names:
            return None
        if mesh.shape[PIPE_AXIS] != self.pipeline_stages:
            raise ValueError(
                f"pipeline_stages={self.pipeline_stages} but mesh "
                f"'{PIPE_AXIS}' axis has {mesh.shape[PIPE_AXIS]} devices")
        if self.depth % self.pipeline_stages:
            raise ValueError(
                f"depth {self.depth} not divisible into "
                f"{self.pipeline_stages} pipeline stages")
        return mesh

    def _pipeline_forward(self, mesh, block_kwargs, x, m, mask,
                          msa_mask, deterministic=True):
        """GPipe over the scan-stacked layer params (parallel/pipeline.py).

        Stage s applies layers [s*depth/S, (s+1)*depth/S) — a lax.scan
        over its (depth/S, ...) param slice with per-block remat, the same
        compute as the nn.scan path. Activations (x, m) plus the masks
        ride the pipeline as one microbatched tree; masks pass through
        stages unchanged. The pipeline's shard_map is manual ONLY over
        the `pipe`/`data` axes; the mesh's `i`/`j` axes stay auto, so the
        in-model GSPMD constraints (shard_pair/shard_msa) keep 2-D
        sharding the pair tensor INSIDE each stage — pp composes with
        both dp (microbatch batch dim over `data`) and the pair sharding
        that makes flagship crops fit (VERDICT r4 #4; the constraint
        specs drop the manual axis names via use_mesh's manual_axes).
        """
        import jax

        from alphafold2_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS
        from alphafold2_tpu.parallel.pipeline import (microbatch,
                                                      pipeline_apply,
                                                      unmicrobatch)
        from alphafold2_tpu.parallel.sharding import use_mesh

        s_count = self.pipeline_stages
        depth_per = self.depth // s_count
        # dropout: one base key; each (microbatch, global layer) derives
        # its mask key by fold_in, so the schedule's recomputations (none
        # in GPipe) and the backward replay see identical masks. Keys ride
        # the pipeline as RAW uint32 key data — a plain array leaf that
        # ppermute/where/zeros_like handle like any activation.
        has_dropout = (self.attn_dropout > 0.0 or self.ff_dropout > 0.0) \
            and not deterministic
        base_key = self.make_rng("dropout") if has_dropout else None
        b, n = x.shape[0], x.shape[1]
        if self.pipeline_microbatches:
            m_count = self.pipeline_microbatches
        else:
            # default: the most microbatches whose per-microbatch batch
            # dim still tiles over the data axis — pp x dp stays real
            # (m_count=b would leave batch-1 microbatches that cannot
            # shard, silently replicating across the data devices)
            data_n = mesh.shape.get(DATA_AXIS, 1)
            m_count = b // data_n if (data_n > 1 and b % data_n == 0) \
                else b
        if b % m_count:
            raise ValueError(f"batch {b} not divisible into {m_count} "
                             "microbatches")

        params = self.get_variable("params", "layers")
        stacked = jax.tree.map(
            lambda p: p.reshape(s_count, depth_per, *p.shape[1:]), params)

        # bf16 under the pipeline is TPU-only: on XLA:CPU the partial-auto
        # lowering emits `psum_invariant` all-reduces whose reduction body
        # has a ROOT copy, and the CPU-only AllReducePromotion pass
        # crashes cloning those in bf16 ("Invalid binary instruction
        # opcode copy", r05). CPU also merely emulates bf16 in f32, so
        # widening to f32 there is strictly better; on TPU the promotion
        # pass does not exist and both the configured block dtype and the
        # activation dtype pass through untouched (no casts, numerics
        # identical to the scan path).
        act_dtype = x.dtype
        on_cpu = jax.default_backend() == "cpu"
        stage_kwargs = dict(block_kwargs)
        if on_cpu and stage_kwargs.get("dtype") == jnp.bfloat16:
            stage_kwargs["dtype"] = jnp.float32
        boundary_dtype = jnp.float32 \
            if (on_cpu and act_dtype == jnp.bfloat16) else act_dtype

        block = nn.remat(EvoformerBlock, static_argnums=(5,),
                         prevent_cse=False)(**stage_kwargs, parent=None)

        def stage_fn(stage_params, act):
            xi, mi, pmask, mmask = act[:4]
            bmask, bmsa = pmask > 0.5, mmask > 0.5
            if has_dropout:
                mb_key = jax.random.wrap_key_data(act[4][0])
                s_idx = jax.lax.axis_index(PIPE_AXIS)

            def body(carry, pj):
                p, j = pj
                xi, mi = carry
                # in-stage constraints stay LIVE for the auto (i, j)
                # axes; pipe/data are manual in the enclosing shard_map
                # and get dropped from the specs
                with use_mesh(mesh, manual_axes=frozenset(
                        {PIPE_AXIS, DATA_AXIS})):
                    if has_dropout:
                        lk = jax.random.fold_in(
                            mb_key, s_idx * depth_per + j)
                        xi, mi = block.apply(
                            {"params": p["block"]}, xi, mi, bmask, bmsa,
                            False, rngs={"dropout": lk})
                    else:
                        xi, mi = block.apply({"params": p["block"]}, xi,
                                             mi, bmask, bmsa, True)
                return (xi, mi), None

            (xi, mi), _ = jax.lax.scan(
                body, (xi, mi), (stage_params, jnp.arange(depth_per)))
            return (xi.astype(boundary_dtype), mi.astype(boundary_dtype),
                    pmask, mmask) + act[4:]

        # masks ride as float tensors (one activation tree, one dtype
        # rule per leaf); materialized when absent so the tree is static
        pmask = jnp.ones((b, n, n), jnp.float32) if mask is None else \
            mask.astype(jnp.float32)
        mmask = jnp.ones(m.shape[:3], jnp.float32) if msa_mask is None \
            else msa_mask.astype(jnp.float32)
        xs = jax.tree.map(lambda t: microbatch(t, m_count),
                          (x.astype(boundary_dtype),
                           m.astype(boundary_dtype), pmask, mmask))
        if has_dropout:
            mb_keys = jax.vmap(lambda i: jax.random.key_data(
                jax.random.fold_in(base_key, i)))(jnp.arange(m_count))
            xs = xs + (mb_keys[:, None],)   # (M, 1, key_words)
        out = pipeline_apply(stage_fn, stacked, xs, mesh,
                             data_axis=DATA_AXIS)
        x = unmicrobatch(out[0]).astype(act_dtype)
        m = unmicrobatch(out[1]).astype(act_dtype)
        return x, m

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        variants, ratios = self._row_variants()
        uniform = len(set(variants)) == 1 and len(set(ratios)) == 1
        if not uniform or variants[0] != "full":
            assert self.pipeline_stages <= 1 and not self.reversible, \
                "the efficient-attention menu is not supported with " \
                "pipeline_stages>1 or reversible=True"
            # refuse-rather-than-silently-drop: the variant row attention
            # does not ring-parallelize; ring_attention would silently
            # all-gather the residue axis it was enabled to keep sharded
            assert not self.ring_attention, \
                "the efficient-attention menu is not supported with " \
                "ring_attention=True (the variant row track is not " \
                "ring-parallel)"
        # refuse-rather-than-silently-drop: pp regroups the scan-stacked
        # params, so it needs the scanned trunk (and depth to stage over)
        if self.pipeline_stages > 1:
            assert not self.reversible, \
                "pipeline_stages>1 is not supported with the reversible " \
                "trunk (pp regroups the scan-stacked params)"
            assert self.use_scan and self.depth > 1, \
                "pipeline_stages>1 requires use_scan=True and depth>1"
        if self.reversible:
            # refuse (rather than silently drop) the OuterMean reference-
            # scaling flag: the reversible blocks construct their own
            # PairwiseAttentionBlock without it
            assert not self.outer_mean_reference_scale, \
                "reversible trunk does not support " \
                "outer_mean_reference_scale yet"
            from alphafold2_tpu.model.reversible import ReversibleEvoformer
            return ReversibleEvoformer(
                dim=self.dim, depth=self.depth, heads=self.heads,
                dim_head=self.dim_head,
                global_column_attn=self.global_column_attn,
                ring_attention=self.ring_attention,
                use_conv=self.use_conv,
                conv_seq_kernels=self.conv_seq_kernels,
                conv_msa_kernels=self.conv_msa_kernels,
                conv_dilations=self.conv_dilations,
                attn_dropout=self.attn_dropout,
                ff_dropout=self.ff_dropout,
                dtype=self.dtype, name="rev")(
                    x, m, mask=mask, msa_mask=msa_mask,
                    deterministic=deterministic)

        block_kwargs = dict(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            attn_dropout=self.attn_dropout, ff_dropout=self.ff_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            use_conv=self.use_conv,
            conv_seq_kernels=self.conv_seq_kernels,
            conv_msa_kernels=self.conv_msa_kernels,
            conv_dilations=self.conv_dilations,
            sparse_block=self.sparse_block,
            sparse_num_global=self.sparse_num_global,
            sparse_window=self.sparse_window,
            linear_attn_kind=self.linear_attn_kind,
            performer_nb_features=self.performer_nb_features,
            dtype=self.dtype,
        )
        if uniform:
            block_kwargs["msa_row_variant"] = variants[0]
            if ratios[0] > 0:
                block_kwargs["kv_compress_ratio"] = ratios[0]

        if self.use_scan and self.depth > 1 and uniform:
            # remat each block, stack parameters along a scanned depth axis:
            # constant compile time and one block of live activations.
            block_cls = nn.remat(
                EvoformerBlock,
                static_argnums=(5,),
                prevent_cse=False,
            )

            class ScanBody(nn.Module):
                dtype: jnp.dtype = self.dtype

                @nn.compact
                def __call__(self, carry, _):
                    x, m = carry
                    x, m = block_cls(**block_kwargs, name="block")(
                        x, m, mask, msa_mask, deterministic)
                    return (x, m), None

            pp = self._pipeline_ready(deterministic)
            if pp is not None and not self.is_initializing():
                # params were created by the scan path at init; regroup
                # the (depth, ...) stack into pp stages and run GPipe
                return self._pipeline_forward(
                    pp, block_kwargs, x, m, mask, msa_mask, deterministic)

            scan = nn.scan(
                ScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True,
                            "performer": True},
                length=self.depth,
            )
            (x, m), _ = scan(name="layers")((x, m), None)
        else:
            # unrolled trunk: per-layer configs are free here, so each
            # layer takes its own menu entry
            for i in range(self.depth):
                kw = dict(block_kwargs)
                kw["msa_row_variant"] = variants[i]
                if ratios[i] > 0:
                    kw["kv_compress_ratio"] = ratios[i]
                x, m = EvoformerBlock(**kw, name=f"layers_{i}")(
                    x, m, mask=mask, msa_mask=msa_mask,
                    deterministic=deterministic)

        return x, m
