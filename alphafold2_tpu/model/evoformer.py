"""Evoformer trunk: MSA/pair blocks and the scanned, rematerialized stack.

Parity with the reference (/root/reference/alphafold2_pytorch/alphafold2.py:
353-467): `PairwiseAttentionBlock` (outer-mean ingest + triangle mult out/in +
triangle attention out/in), `MsaAttentionBlock` (row attn with pair bias, col
attn), `EvoformerBlock` (msa attn -> msa FF -> pair attn -> pair FF, all
residual), `Evoformer` = depth x block.

TPU-first: instead of the reference's `checkpoint_sequential` (alphafold2.py:
466), the stack runs under `nn.scan` over depth with per-layer remat
(`nn.remat`) — constant compile time at depth 48 and O(1) stored activations
per block, with XLA re-materializing each block's interior in the backward
pass. Pair/MSA activations carry sharding constraints so the stack runs
identically under a pjit mesh (see alphafold2_tpu/parallel).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.model.attention_variants import (
    DEFAULT_CONV_MSA_KERNELS,
    DEFAULT_CONV_SEQ_KERNELS,
    MultiKernelConvBlock,
)
from alphafold2_tpu.model.primitives import (
    AxialAttention,
    FeedForward,
    OuterMean,
    TriangleMultiplicativeModule,
)
from alphafold2_tpu.parallel.mesh import PAIR_I_AXIS, PAIR_J_AXIS
from alphafold2_tpu.parallel.sharding import shard_msa, shard_pair


class PairwiseAttentionBlock(nn.Module):
    """Pair-track block (reference alphafold2.py:353-385).

    `ring_attention=True` runs the two triangle attentions ring-parallel
    over the sharded pair axes when an active mesh shards them
    (AxialAttention.ring_axes; parallel/ring.py) — the long-context mode.
    """

    dim: int
    heads: int
    dim_head: int = 64
    dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, msa_repr=None, msa_mask=None,
                 deterministic: bool = True):
        ring_axes = (PAIR_I_AXIS, PAIR_J_AXIS) if self.ring_attention \
            else None
        if msa_repr is not None:
            x = x + OuterMean(dim=self.dim, dtype=self.dtype,
                              reference_scale=self.outer_mean_reference_scale,
                              name="outer_mean")(msa_repr, mask=msa_mask)
            x = shard_pair(x)

        x = TriangleMultiplicativeModule(
            dim=self.dim, mix="outgoing", dtype=self.dtype,
            name="triangle_multiply_outgoing")(x, mask=mask) + x
        x = TriangleMultiplicativeModule(
            dim=self.dim, mix="ingoing", dtype=self.dtype,
            name="triangle_multiply_ingoing")(x, mask=mask) + x
        x = shard_pair(x)
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=True, col_attn=False, accept_edges=True,
            ring_axes=ring_axes,
            dtype=self.dtype, name="triangle_attention_outgoing",
        )(x, edges=x, mask=mask, deterministic=deterministic) + x
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=False, col_attn=True, accept_edges=True,
            global_query_attn=self.global_column_attn,
            ring_axes=ring_axes,
            dtype=self.dtype, name="triangle_attention_ingoing",
        )(x, edges=x, mask=mask, deterministic=deterministic) + x
        return shard_pair(x)


class MsaAttentionBlock(nn.Module):
    """MSA-track block (reference alphafold2.py:387-408).

    `ring_attention=True` runs the row attention (per-alignment attention
    over the residue axis, which `shard_msa` shards over the `i` mesh
    axis) ring-parallel instead of letting GSPMD all-gather the full
    residue axis (round-2 VERDICT next-round #5). Column attention is
    over the alignment axis, which is never mesh-sharded — dense there.
    """

    dim: int
    heads: int
    dim_head: int = 64
    dropout: float = 0.0
    ring_attention: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, pairwise_repr=None,
                 deterministic: bool = True):
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=True, col_attn=False, accept_edges=True,
            ring_axes=(None, PAIR_I_AXIS) if self.ring_attention else None,
            dtype=self.dtype, name="row_attn",
        )(x, mask=mask, edges=pairwise_repr, deterministic=deterministic) + x
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=False, col_attn=True,
            dtype=self.dtype, name="col_attn",
        )(x, mask=mask, deterministic=deterministic) + x
        return shard_msa(x)


class EvoformerBlock(nn.Module):
    """One Evoformer layer (reference alphafold2.py:412-446).

    `use_conv=True` appends trRosetta2-style residual conv blocks to both
    tracks (the README-era `use_conv` menu item, README.md:271-340):
    `conv_seq_kernels` over the (n, n) pair map, `conv_msa_kernels` over
    the (rows, n) MSA, with the dilation cycle applied in-block
    (attention_variants.MultiKernelConvBlock documents the TPU-first
    deviations)."""

    dim: int
    heads: int
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        # msa attention and transition
        m = MsaAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout, ring_attention=self.ring_attention,
            dtype=self.dtype, name="msa_attn",
        )(m, mask=msa_mask, pairwise_repr=x, deterministic=deterministic)
        m = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                        dtype=self.dtype, name="msa_ff")(
                            m, deterministic=deterministic) + m
        if self.use_conv:
            m = MultiKernelConvBlock(
                dim=self.dim, kernels=self.conv_msa_kernels,
                dilations=self.conv_dilations, dtype=self.dtype,
                name="msa_conv")(m, mask=msa_mask) + m

        # pairwise attention (ingesting the updated MSA) and transition
        x = PairwiseAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            dtype=self.dtype, name="attn",
        )(x, mask=mask, msa_repr=m, msa_mask=msa_mask,
          deterministic=deterministic)
        x = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                        dtype=self.dtype, name="ff")(
                            x, deterministic=deterministic) + x
        if self.use_conv:
            x = MultiKernelConvBlock(
                dim=self.dim, kernels=self.conv_seq_kernels,
                dilations=self.conv_dilations, dtype=self.dtype,
                name="pair_conv")(x, mask=mask) + x

        return x, m


class Evoformer(nn.Module):
    """depth x EvoformerBlock under scan + remat (reference alphafold2.py:
    448-467; memory scaling via checkpoint_sequential there, jax.remat here).
    """

    dim: int
    depth: int
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    dtype: jnp.dtype = jnp.float32
    use_scan: bool = True
    # O(1)-activation reversible trunk (model/reversible.py; reference
    # README.md:40 `reversible=True`, reversible.py)
    reversible: bool = False
    # GPipe pipeline parallelism over the mesh's `pipe` axis
    # (parallel/pipeline.py): the depth-stacked scan params are regrouped
    # into S stages of depth/S layers and the trunk runs the static skew
    # schedule, microbatching the batch axis. Params are IDENTICAL to the
    # scanned trunk (the pp path re-reads the scan's stacked params), so
    # checkpoints move freely between pp and non-pp runs.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0   # 0 -> one microbatch per batch row

    def _pipeline_ready(self, deterministic):
        """The active mesh if the pipeline path applies, else None."""
        from alphafold2_tpu.parallel.sharding import active_mesh
        from alphafold2_tpu.parallel.mesh import PIPE_AXIS

        if self.pipeline_stages <= 1:
            return None
        mesh = active_mesh()
        if mesh is None or PIPE_AXIS not in mesh.axis_names:
            return None
        if mesh.shape[PIPE_AXIS] != self.pipeline_stages:
            raise ValueError(
                f"pipeline_stages={self.pipeline_stages} but mesh "
                f"'{PIPE_AXIS}' axis has {mesh.shape[PIPE_AXIS]} devices")
        if self.depth % self.pipeline_stages:
            raise ValueError(
                f"depth {self.depth} not divisible into "
                f"{self.pipeline_stages} pipeline stages")
        assert (self.attn_dropout == 0.0 and self.ff_dropout == 0.0) or \
            deterministic, "pipeline trunk does not support dropout"
        return mesh

    def _pipeline_forward(self, mesh, block_kwargs, x, m, mask, msa_mask):
        """GPipe over the scan-stacked layer params (parallel/pipeline.py).

        Stage s applies layers [s*depth/S, (s+1)*depth/S) — a lax.scan
        over its (depth/S, ...) param slice with per-block remat, the same
        compute as the nn.scan path. Activations (x, m) plus the masks
        ride the pipeline as one microbatched tree; masks pass through
        stages unchanged. The in-model GSPMD constraints (shard_pair/
        shard_msa) are disabled inside the shard_map body — within a
        stage the spatial axes are whole; pp composes with dp (the
        microbatch batch dim shards over the data axis), not with the
        2-D pair sharding.
        """
        import jax

        from alphafold2_tpu.parallel.mesh import DATA_AXIS
        from alphafold2_tpu.parallel.pipeline import (microbatch,
                                                      pipeline_apply,
                                                      unmicrobatch)
        from alphafold2_tpu.parallel.sharding import use_mesh

        s_count = self.pipeline_stages
        depth_per = self.depth // s_count
        b, n = x.shape[0], x.shape[1]
        if self.pipeline_microbatches:
            m_count = self.pipeline_microbatches
        else:
            # default: the most microbatches whose per-microbatch batch
            # dim still tiles over the data axis — pp x dp stays real
            # (m_count=b would leave batch-1 microbatches that cannot
            # shard, silently replicating across the data devices)
            data_n = mesh.shape.get(DATA_AXIS, 1)
            m_count = b // data_n if (data_n > 1 and b % data_n == 0) \
                else b
        if b % m_count:
            raise ValueError(f"batch {b} not divisible into {m_count} "
                             "microbatches")

        params = self.get_variable("params", "layers")
        stacked = jax.tree.map(
            lambda p: p.reshape(s_count, depth_per, *p.shape[1:]), params)

        block = nn.remat(EvoformerBlock, static_argnums=(5,),
                         prevent_cse=False)(**block_kwargs, parent=None)

        def stage_fn(stage_params, act):
            xi, mi, pmask, mmask = act
            bmask, bmsa = pmask > 0.5, mmask > 0.5

            def body(carry, p):
                xi, mi = carry
                with use_mesh(None):   # constraints are no-ops in-stage
                    xi, mi = block.apply({"params": p["block"]}, xi, mi,
                                         bmask, bmsa, True)
                return (xi, mi), None

            (xi, mi), _ = jax.lax.scan(body, (xi, mi), stage_params)
            return (xi, mi, pmask, mmask)

        # masks ride as float tensors (one activation tree, one dtype
        # rule per leaf); materialized when absent so the tree is static
        pmask = jnp.ones((b, n, n), jnp.float32) if mask is None else \
            mask.astype(jnp.float32)
        mmask = jnp.ones(m.shape[:3], jnp.float32) if msa_mask is None \
            else msa_mask.astype(jnp.float32)
        xs = jax.tree.map(lambda t: microbatch(t, m_count),
                          (x, m, pmask, mmask))
        out = pipeline_apply(stage_fn, stacked, xs, mesh,
                             data_axis=DATA_AXIS)
        x, m = unmicrobatch(out[0]), unmicrobatch(out[1])
        return x, m

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        # refuse-rather-than-silently-drop: pp regroups the scan-stacked
        # params, so it needs the scanned trunk (and depth to stage over)
        if self.pipeline_stages > 1:
            assert not self.reversible, \
                "pipeline_stages>1 is not supported with the reversible " \
                "trunk (pp regroups the scan-stacked params)"
            assert self.use_scan and self.depth > 1, \
                "pipeline_stages>1 requires use_scan=True and depth>1"
        if self.reversible:
            # the reversible trunk is deterministic by construction (exact
            # inverse reconstruction); refuse configs that expect dropout
            # rather than silently ignoring it
            assert self.attn_dropout == 0.0 and self.ff_dropout == 0.0, \
                "reversible trunk does not support dropout"
            # refuse (rather than silently drop) the OuterMean reference-
            # scaling flag: the reversible blocks construct their own
            # PairwiseAttentionBlock without it
            assert not self.outer_mean_reference_scale, \
                "reversible trunk does not support " \
                "outer_mean_reference_scale yet"
            from alphafold2_tpu.model.reversible import ReversibleEvoformer
            return ReversibleEvoformer(
                dim=self.dim, depth=self.depth, heads=self.heads,
                dim_head=self.dim_head,
                global_column_attn=self.global_column_attn,
                ring_attention=self.ring_attention,
                use_conv=self.use_conv,
                conv_seq_kernels=self.conv_seq_kernels,
                conv_msa_kernels=self.conv_msa_kernels,
                conv_dilations=self.conv_dilations,
                dtype=self.dtype, name="rev")(
                    x, m, mask=mask, msa_mask=msa_mask)

        block_kwargs = dict(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            attn_dropout=self.attn_dropout, ff_dropout=self.ff_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            use_conv=self.use_conv,
            conv_seq_kernels=self.conv_seq_kernels,
            conv_msa_kernels=self.conv_msa_kernels,
            conv_dilations=self.conv_dilations,
            dtype=self.dtype,
        )

        if self.use_scan and self.depth > 1:
            # remat each block, stack parameters along a scanned depth axis:
            # constant compile time and one block of live activations.
            block_cls = nn.remat(
                EvoformerBlock,
                static_argnums=(5,),
                prevent_cse=False,
            )

            class ScanBody(nn.Module):
                dtype: jnp.dtype = self.dtype

                @nn.compact
                def __call__(self, carry, _):
                    x, m = carry
                    x, m = block_cls(**block_kwargs, name="block")(
                        x, m, mask, msa_mask, deterministic)
                    return (x, m), None

            pp = self._pipeline_ready(deterministic)
            if pp is not None and not self.is_initializing():
                # params were created by the scan path at init; regroup
                # the (depth, ...) stack into pp stages and run GPipe
                return self._pipeline_forward(
                    pp, block_kwargs, x, m, mask, msa_mask)

            scan = nn.scan(
                ScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.depth,
            )
            (x, m), _ = scan(name="layers")((x, m), None)
        else:
            for i in range(self.depth):
                x, m = EvoformerBlock(**block_kwargs, name=f"layers_{i}")(
                    x, m, mask=mask, msa_mask=msa_mask,
                    deterministic=deterministic)

        return x, m
