"""Evoformer trunk: MSA/pair blocks and the scanned, rematerialized stack.

Parity with the reference (/root/reference/alphafold2_pytorch/alphafold2.py:
353-467): `PairwiseAttentionBlock` (outer-mean ingest + triangle mult out/in +
triangle attention out/in), `MsaAttentionBlock` (row attn with pair bias, col
attn), `EvoformerBlock` (msa attn -> msa FF -> pair attn -> pair FF, all
residual), `Evoformer` = depth x block.

TPU-first: instead of the reference's `checkpoint_sequential` (alphafold2.py:
466), the stack runs under `nn.scan` over depth with per-layer remat
(`nn.remat`) — constant compile time at depth 48 and O(1) stored activations
per block, with XLA re-materializing each block's interior in the backward
pass. Pair/MSA activations carry sharding constraints so the stack runs
identically under a pjit mesh (see alphafold2_tpu/parallel).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.model.primitives import (
    AxialAttention,
    FeedForward,
    OuterMean,
    TriangleMultiplicativeModule,
)
from alphafold2_tpu.parallel.mesh import PAIR_I_AXIS, PAIR_J_AXIS
from alphafold2_tpu.parallel.sharding import shard_msa, shard_pair


class PairwiseAttentionBlock(nn.Module):
    """Pair-track block (reference alphafold2.py:353-385).

    `ring_attention=True` runs the two triangle attentions ring-parallel
    over the sharded pair axes when an active mesh shards them
    (AxialAttention.ring_axes; parallel/ring.py) — the long-context mode.
    """

    dim: int
    heads: int
    dim_head: int = 64
    dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, msa_repr=None, msa_mask=None,
                 deterministic: bool = True):
        ring_axes = (PAIR_I_AXIS, PAIR_J_AXIS) if self.ring_attention \
            else None
        if msa_repr is not None:
            x = x + OuterMean(dim=self.dim, dtype=self.dtype,
                              reference_scale=self.outer_mean_reference_scale,
                              name="outer_mean")(msa_repr, mask=msa_mask)
            x = shard_pair(x)

        x = TriangleMultiplicativeModule(
            dim=self.dim, mix="outgoing", dtype=self.dtype,
            name="triangle_multiply_outgoing")(x, mask=mask) + x
        x = TriangleMultiplicativeModule(
            dim=self.dim, mix="ingoing", dtype=self.dtype,
            name="triangle_multiply_ingoing")(x, mask=mask) + x
        x = shard_pair(x)
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=True, col_attn=False, accept_edges=True,
            ring_axes=ring_axes,
            dtype=self.dtype, name="triangle_attention_outgoing",
        )(x, edges=x, mask=mask, deterministic=deterministic) + x
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=False, col_attn=True, accept_edges=True,
            global_query_attn=self.global_column_attn,
            ring_axes=ring_axes,
            dtype=self.dtype, name="triangle_attention_ingoing",
        )(x, edges=x, mask=mask, deterministic=deterministic) + x
        return shard_pair(x)


class MsaAttentionBlock(nn.Module):
    """MSA-track block (reference alphafold2.py:387-408).

    `ring_attention=True` runs the row attention (per-alignment attention
    over the residue axis, which `shard_msa` shards over the `i` mesh
    axis) ring-parallel instead of letting GSPMD all-gather the full
    residue axis (round-2 VERDICT next-round #5). Column attention is
    over the alignment axis, which is never mesh-sharded — dense there.
    """

    dim: int
    heads: int
    dim_head: int = 64
    dropout: float = 0.0
    ring_attention: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, pairwise_repr=None,
                 deterministic: bool = True):
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=True, col_attn=False, accept_edges=True,
            ring_axes=(None, PAIR_I_AXIS) if self.ring_attention else None,
            dtype=self.dtype, name="row_attn",
        )(x, mask=mask, edges=pairwise_repr, deterministic=deterministic) + x
        x = AxialAttention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            row_attn=False, col_attn=True,
            dtype=self.dtype, name="col_attn",
        )(x, mask=mask, deterministic=deterministic) + x
        return shard_msa(x)


class EvoformerBlock(nn.Module):
    """One Evoformer layer (reference alphafold2.py:412-446)."""

    dim: int
    heads: int
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        # msa attention and transition
        m = MsaAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout, ring_attention=self.ring_attention,
            dtype=self.dtype, name="msa_attn",
        )(m, mask=msa_mask, pairwise_repr=x, deterministic=deterministic)
        m = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                        dtype=self.dtype, name="msa_ff")(
                            m, deterministic=deterministic) + m

        # pairwise attention (ingesting the updated MSA) and transition
        x = PairwiseAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            dtype=self.dtype, name="attn",
        )(x, mask=mask, msa_repr=m, msa_mask=msa_mask,
          deterministic=deterministic)
        x = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                        dtype=self.dtype, name="ff")(
                            x, deterministic=deterministic) + x

        return x, m


class Evoformer(nn.Module):
    """depth x EvoformerBlock under scan + remat (reference alphafold2.py:
    448-467; memory scaling via checkpoint_sequential there, jax.remat here).
    """

    dim: int
    depth: int
    heads: int = 8
    dim_head: int = 64
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    global_column_attn: bool = False
    ring_attention: bool = False
    outer_mean_reference_scale: bool = False
    dtype: jnp.dtype = jnp.float32
    use_scan: bool = True
    # O(1)-activation reversible trunk (model/reversible.py; reference
    # README.md:40 `reversible=True`, reversible.py)
    reversible: bool = False

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        if self.reversible:
            # the reversible trunk is deterministic by construction (exact
            # inverse reconstruction); refuse configs that expect dropout
            # rather than silently ignoring it
            assert self.attn_dropout == 0.0 and self.ff_dropout == 0.0, \
                "reversible trunk does not support dropout"
            # refuse (rather than silently drop) the OuterMean reference-
            # scaling flag: the reversible blocks construct their own
            # PairwiseAttentionBlock without it
            assert not self.outer_mean_reference_scale, \
                "reversible trunk does not support " \
                "outer_mean_reference_scale yet"
            from alphafold2_tpu.model.reversible import ReversibleEvoformer
            return ReversibleEvoformer(
                dim=self.dim, depth=self.depth, heads=self.heads,
                dim_head=self.dim_head,
                global_column_attn=self.global_column_attn,
                ring_attention=self.ring_attention,
                dtype=self.dtype, name="rev")(
                    x, m, mask=mask, msa_mask=msa_mask)

        block_kwargs = dict(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            attn_dropout=self.attn_dropout, ff_dropout=self.ff_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            dtype=self.dtype,
        )

        if self.use_scan and self.depth > 1:
            # remat each block, stack parameters along a scanned depth axis:
            # constant compile time and one block of live activations.
            block_cls = nn.remat(
                EvoformerBlock,
                static_argnums=(5,),
                prevent_cse=False,
            )

            class ScanBody(nn.Module):
                dtype: jnp.dtype = self.dtype

                @nn.compact
                def __call__(self, carry, _):
                    x, m = carry
                    x, m = block_cls(**block_kwargs, name="block")(
                        x, m, mask, msa_mask, deterministic)
                    return (x, m), None

            scan = nn.scan(
                ScanBody,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.depth,
            )
            (x, m), _ = scan(name="layers")((x, m), None)
        else:
            for i in range(self.depth):
                x, m = EvoformerBlock(**block_kwargs, name=f"layers_{i}")(
                    x, m, mask=mask, msa_mask=msa_mask,
                    deterministic=deterministic)

        return x, m
