"""Top-level Alphafold2 model.

Forward-path parity with the reference
(/root/reference/alphafold2_pytorch/alphafold2.py:469-905): token/relative-
position embeddings, MSA-MLM noising during training, pair-representation
init via outer sum, recycling embedder (norms + bucketized CA-distance
embedding), template pair/angle stacks, extra-MSA Evoformer, the main
Evoformer trunk, distogram + trRosetta-style angle heads, the IPA structure
module, and the lDDT confidence head.

Deviations from the reference (deliberate, documented):
- the extra-MSA path embeds `extra_msa` (the reference embeds `msa` again —
  a bug at alphafold2.py:790);
- angle logits are returned on the `theta`/`phi`/`omega` fields of
  `ReturnValues` (the reference assigns ad-hoc `theta_logits` attributes that
  leave the declared dataclass fields None, alphafold2.py:32-35 vs :816-817);
- randomness (MLM noising, dropout) uses explicit PRNG keys via flax rngs
  {'mlm', 'dropout'} instead of global RNG state;
- the trunk runs in a configurable compute dtype (bf16 on TPU); the
  structure module stays an fp32 island as in the reference
  (alphafold2.py:850-855).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax import struct

from alphafold2_tpu import constants
from alphafold2_tpu.model.attention_variants import (
    DEFAULT_CONV_MSA_KERNELS,
    DEFAULT_CONV_SEQ_KERNELS,
)
from alphafold2_tpu.model.evoformer import Evoformer, PairwiseAttentionBlock
from alphafold2_tpu.model.mlm import MLM
from alphafold2_tpu.model.primitives import Attention, Dense, LayerNorm
from alphafold2_tpu.model.refiners import (AtomEGNNRefiner,
                                            Refiner)
from alphafold2_tpu.model.structure import StructureModule
from alphafold2_tpu.parallel.sharding import shard_msa, shard_pair


@struct.dataclass
class Recyclables:
    """Inter-recycle state (reference alphafold2.py:24-28)."""

    coords: jnp.ndarray
    single_msa_repr_row: jnp.ndarray
    pairwise_repr: jnp.ndarray


@struct.dataclass
class ReturnValues:
    """Multi-output container (reference alphafold2.py:30-37)."""

    distance: Optional[jnp.ndarray] = None
    theta: Optional[jnp.ndarray] = None
    phi: Optional[jnp.ndarray] = None
    omega: Optional[jnp.ndarray] = None
    msa_mlm_loss: Optional[jnp.ndarray] = None
    recyclables: Optional[Recyclables] = None
    # raw lddt-confidence head output (b, n, 1); populated on the coords
    # path so the head can be trained (the reference's lddt_linear ships
    # untrained — alphafold2.py:621)
    confidence: Optional[jnp.ndarray] = None
    # full refined atom cloud (b, n, 14, 3); populated only under
    # structure_module_refinement='egnn-atom' (the notebook's atom-level
    # path — coords stay the CA trace for API stability)
    atoms: Optional[jnp.ndarray] = None


class Alphafold2(nn.Module):
    """See reference Alphafold2.__init__ (alphafold2.py:470-501) for the
    hyperparameter contract; names and defaults match."""

    dim: int
    max_seq_len: int = 2048
    depth: int = 6
    heads: int = 8
    dim_head: int = 64
    max_rel_dist: int = 32
    num_tokens: int = constants.NUM_AMINO_ACIDS
    num_embedds: int = constants.NUM_EMBEDDS_TR
    max_num_msas: int = constants.MAX_NUM_MSA
    max_num_templates: int = constants.MAX_NUM_TEMPLATES
    extra_msa_evoformer_layers: int = 4
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    templates_dim: int = 32
    templates_embed_layers: int = 4
    templates_angles_feats_dim: int = 55
    predict_angles: bool = False
    symmetrize_omega: bool = False
    predict_coords: bool = False
    structure_module_depth: int = 4
    structure_module_heads: int = 1
    structure_module_dim_head: int = 4
    # README-era structure-module selection (reference README.md:106-112,
    # :594-600; the current reference code is IPA-only): 'ipa' runs the
    # IPA module; 'egnn' / 'en' / 'se3' run the equivariant refiners from
    # model/refiners.py instead. refinement_iters > 0 additionally refines
    # the produced coordinates (on top of any module type).
    structure_module_type: str = "ipa"
    structure_module_refinement_iters: int = 0
    # what refinement_iters refines: 'residue' = dense EGNN on the CA
    # trace (the README-era refinement loop); 'egnn-atom' = sparse EGNN
    # over the 14-slot covalent-bond atom graph, the reference notebook's
    # atom-level experiment (egnn_esm_end2end.ipynb cells 25-33,
    # utils.py:497-650) — coords stay (b, n, 3) CA; the full refined
    # atom cloud is returned on ReturnValues.atoms
    structure_module_refinement: str = "residue"
    # reversible main trunk (README.md:40-era flag): O(1) activation memory
    reversible: bool = False
    # scan+remat over trunk depth (Evoformer.use_scan); False unrolls the
    # stack with full activation storage — the linear-memory comparison
    # point for tools/memory_probe.py
    use_scan: bool = True
    # ring-parallel triangle attention over the 2-D-sharded pair tensor
    # (parallel/ring.py): exact long-context mode, active only when the
    # mesh actually shards the pair axes; no-op otherwise
    ring_attention: bool = False
    # GPipe pipeline parallelism for the main trunk over the mesh's
    # `pipe` axis (Evoformer.pipeline_stages; parallel/pipeline.py).
    # The small extra-MSA stack stays scanned — only the deep trunk is
    # worth staging.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0
    # trRosetta2-style conv blocks on both trunk tracks (the reference's
    # README-era `use_conv` menu, README.md:271-340; kernels/dilations
    # mirror its conv_seq_kernels / conv_msa_kernels / dilation cycle)
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    # README-era efficient-attention menu for the main trunk's MSA row
    # track (reference README.md:388-487; Evoformer documents the
    # semantics). Bools (all layers) or per-layer tuples — e.g.
    # `sparse_self_attn=(True, False) * 3` interleaves sparse and full
    # (README.md:415). kv_compress_ratio: 0 = off (README.md:485).
    # Reference-name mapping (MIGRATING.md): sparse_self_attn ->
    # sparse_self_attn, cross_attn_linear -> linear_attn,
    # cross_attn_kron_primary/_msa -> kron_attn,
    # cross_attn_compress_ratio -> kv_compress_ratio.
    sparse_self_attn: Any = False
    linear_attn: Any = False
    kron_attn: Any = False
    kv_compress_ratio: Any = 0
    sparse_block: int = 32
    sparse_num_global: int = 1
    sparse_window: int = 1
    linear_attn_kind: str = "favor"
    performer_nb_features: int = 256
    # reproduce the reference's masked-OuterMean double division
    # (alphafold2.py:347 + the always-synthesized msa_mask at :703);
    # required for exact parity with reference-trained checkpoints
    # (tools/port_weights.py), off by default in favor of the correct
    # masked mean
    outer_mean_reference_scale: bool = False
    disable_token_embed: bool = False
    mlm_mask_prob: float = 0.15
    mlm_random_replace_token_prob: float = 0.1
    mlm_keep_token_same_prob: float = 0.1
    mlm_exclude_token_ids: tuple = (0,)
    recycling_distance_buckets: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        seq,                       # (b, n) int tokens
        msa=None,                  # (b, m, n) int tokens
        mask=None,                 # (b, n) bool
        msa_mask=None,             # (b, m, n) bool
        extra_msa=None,            # (b, e, n) int tokens
        extra_msa_mask=None,       # (b, e, n) bool
        seq_index=None,            # (n,) int residue indices
        seq_embed=None,            # (b, n, dim)
        msa_embed=None,            # (b, m, n, dim)
        templates_feats=None,      # (b, t, n, n, templates_dim)
        templates_mask=None,       # (b, t, n)
        templates_angles=None,     # (b, t, n, templates_angles_feats_dim)
        embedds=None,              # (b, m, n, num_embedds) pretrained embeds
        recyclables: Optional[Recyclables] = None,
        return_trunk: bool = False,
        return_confidence: bool = False,
        return_recyclables: bool = False,
        return_aux_logits: bool = False,
        train: bool = False,
    ):
        assert not (self.disable_token_embed and seq_embed is None), \
            "sequence embedding must be supplied if token embedding disabled"
        assert not (self.disable_token_embed and msa is not None
                    and msa_embed is None), \
            "msa embedding must be supplied if token embedding disabled"

        b, n = seq.shape[:2]
        deterministic = not train

        if mask is None:
            mask = jnp.ones((b, n), dtype=bool)

        # if MSA is not passed in, use the sequence itself
        # (reference alphafold2.py:656-658)
        if msa is None and embedds is None:
            msa = seq[:, None, :]
            msa_mask = mask[:, None, :]

        if msa is not None:
            assert msa.shape[-1] == seq.shape[-1], \
                "sequence length of MSA and primary sequence must match"

        # embedding tables -------------------------------------------------
        token_emb = nn.Embed(self.num_tokens + 1, self.dim,
                             param_dtype=jnp.float32, name="token_emb") \
            if not self.disable_token_embed else None

        def embed_tokens(t):
            if token_emb is None:
                return 0.0
            return token_emb(t).astype(self.dtype)

        # embed main sequence (reference alphafold2.py:676-679). Pretrained
        # LM embeddings at foreign dims (= num_embedds) are projected here —
        # the reference keeps this Linear inside its embed wrappers
        # (embeds.py:14, :41, :84); model-side keeps the wrappers paramless.
        # one projector per input width so a single params tree serves any
        # of the pretrained-LM widths (768/1024/1280 — the reference sizes
        # each wrapper's Linear from its own constant)
        def project_embed(e, prefix):
            e = e.astype(self.dtype)
            if e.shape[-1] != self.dim:
                e = Dense(self.dim, param_dtype=jnp.float32,
                          dtype=self.dtype,
                          name=f"{prefix}_{e.shape[-1]}")(e)
            return e

        x_single = embed_tokens(seq)
        if seq_embed is not None:
            x_single = x_single + project_embed(seq_embed,
                                                "seq_embed_project")

        # MLM noising for MSA during training (reference alphafold2.py:683-688)
        mlm = MLM(
            dim=self.dim,
            num_tokens=self.num_tokens,
            mask_id=self.num_tokens,  # last embedding row is the mask token
            mask_prob=self.mlm_mask_prob,
            random_replace_token_prob=self.mlm_random_replace_token_prob,
            keep_token_same_prob=self.mlm_keep_token_same_prob,
            exclude_token_ids=self.mlm_exclude_token_ids,
            name="mlm",
        )

        original_msa = msa
        replaced_msa_mask = None
        if train and msa is not None:
            if msa_mask is None:
                msa_mask = jnp.ones_like(msa, dtype=bool)
            noised_msa, replaced_msa_mask = mlm.noise(
                self.make_rng("mlm"), msa, msa_mask)
            msa = noised_msa

        # embed MSA (reference alphafold2.py:692-709)
        if msa is not None:
            m = embed_tokens(msa)
            if msa_embed is not None:
                m = m + project_embed(msa_embed, "msa_embed_project")
            m = m + x_single[:, None, :, :]
            if msa_mask is None:
                msa_mask = jnp.ones_like(msa, dtype=bool)
        elif embedds is not None:
            m = Dense(self.dim, param_dtype=jnp.float32, dtype=self.dtype,
                      name="embedd_project")(embedds.astype(self.dtype))
            if msa_mask is None:
                msa_mask = jnp.ones(embedds.shape[:-1], dtype=bool)
        else:
            raise ValueError("either MSA or embedds must be given")
        m = shard_msa(m)

        # pairwise representation by outer sum (reference alphafold2.py:715-717)
        x_pair_proj = Dense(self.dim * 2, param_dtype=jnp.float32,
                            dtype=self.dtype, name="to_pairwise_repr")(
                                   x_single)
        x_left, x_right = jnp.split(x_pair_proj, 2, axis=-1)
        x = x_left[:, :, None, :] + x_right[:, None, :, :]  # (b, i, j, d)
        x_mask = mask[:, :, None] & mask[:, None, :]

        # relative positional embedding, clamped (reference alphafold2.py:721-726)
        if seq_index is None:
            seq_index = jnp.arange(n)
        rel = seq_index[:, None] - seq_index[None, :]
        rel = jnp.clip(rel, -self.max_rel_dist, self.max_rel_dist) + \
            self.max_rel_dist
        pos_emb = nn.Embed(self.max_rel_dist * 2 + 1, self.dim,
                           param_dtype=jnp.float32, name="pos_emb")(rel)
        x = x + pos_emb[None].astype(self.dtype)
        x = shard_pair(x)

        # recycling (reference alphafold2.py:730-739)
        if recyclables is not None:
            first_row = m[:, 0] + LayerNorm(
                dtype=jnp.float32, name="recycling_msa_norm")(
                    recyclables.single_msa_repr_row).astype(self.dtype)
            m = m.at[:, 0].set(first_row)
            x = x + LayerNorm(
                dtype=jnp.float32, name="recycling_pairwise_norm")(
                    recyclables.pairwise_repr).astype(self.dtype)

            coords = recyclables.coords
            dists = jnp.sqrt(jnp.maximum(jnp.sum(
                (coords[:, :, None] - coords[:, None, :]) ** 2, -1), 1e-12))
            boundaries = jnp.linspace(2.0, 20.0,
                                      self.recycling_distance_buckets)[:-1]
            buckets = jnp.searchsorted(boundaries, dists, side="left")
            dist_embed = nn.Embed(
                self.recycling_distance_buckets, self.dim,
                param_dtype=jnp.float32, name="recycling_distance_embed")(
                    buckets)
            x = x + dist_embed.astype(self.dtype)

        # templates (reference alphafold2.py:743-785)
        if templates_feats is not None:
            num_templates = templates_feats.shape[1]
            t = Dense(self.dim, param_dtype=jnp.float32, dtype=self.dtype,
                      name="to_template_embed")(
                             templates_feats.astype(self.dtype))
            t_mask_crossed = templates_mask[:, :, :, None] & \
                templates_mask[:, :, None, :]

            t = t.reshape(b * num_templates, *t.shape[2:])
            t_mask_flat = t_mask_crossed.reshape(
                b * num_templates, *t_mask_crossed.shape[2:])

            # weight-shared pair embedder applied templates_embed_layers
            # times (reference alphafold2.py:751-755)
            template_embedder = PairwiseAttentionBlock(
                dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                dtype=self.dtype, name="template_pairwise_embedder")
            for _ in range(self.templates_embed_layers):
                t = template_embedder(t, mask=t_mask_flat,
                                      deterministic=deterministic)

            t = t.reshape(b, num_templates, *t.shape[1:])

            # pointwise attention across templates per pair cell
            # (reference alphafold2.py:762-778)
            x_point = x.reshape(b * n * n, 1, self.dim)
            t_point = t.transpose(0, 2, 3, 1, 4).reshape(
                b * n * n, num_templates, self.dim)
            x_mask_point = x_mask.reshape(b * n * n, 1)
            t_mask_point = t_mask_crossed.transpose(0, 2, 3, 1).reshape(
                b * n * n, num_templates)

            template_pooled = Attention(
                dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                dropout=self.attn_dropout, dtype=self.dtype,
                name="template_pointwise_attn",
            )(x_point, mask=x_mask_point, context=t_point,
              context_mask=t_mask_point, deterministic=deterministic)

            has_template = (t_mask_point.sum(-1) > 0)[:, None, None]
            template_pooled = template_pooled * has_template
            x = x + template_pooled.reshape(b, n, n, self.dim)

        # template angle features -> extra MSA rows (reference
        # alphafold2.py:782-785)
        if templates_angles is not None:
            t_angs = templates_angles.astype(self.dtype)
            t_angle_feats = Dense(
                self.dim, param_dtype=jnp.float32, dtype=self.dtype,
                name="template_angle_mlp_in")(t_angs)
            t_angle_feats = Dense(
                self.dim, param_dtype=jnp.float32, dtype=self.dtype,
                name="template_angle_mlp_out")(jax.nn.gelu(t_angle_feats))
            m = jnp.concatenate([m, t_angle_feats], axis=1)
            msa_mask = jnp.concatenate([msa_mask, templates_mask], axis=1)

        # extra MSA stack (reference alphafold2.py:789-798; the reference
        # embeds `msa` here by mistake — we embed `extra_msa`)
        if extra_msa is not None:
            extra_m = embed_tokens(extra_msa)
            if extra_msa_mask is None:
                extra_msa_mask = jnp.ones(extra_msa.shape, dtype=bool)
            x, extra_m = Evoformer(
                dim=self.dim, depth=self.extra_msa_evoformer_layers,
                heads=self.heads, dim_head=self.dim_head,
                attn_dropout=self.attn_dropout, ff_dropout=self.ff_dropout,
                global_column_attn=True,
                ring_attention=self.ring_attention,
                outer_mean_reference_scale=self.outer_mean_reference_scale,
                dtype=self.dtype,
                name="extra_msa_evoformer",
            )(x, extra_m, mask=x_mask, msa_mask=extra_msa_mask,
              deterministic=deterministic)

        # main trunk (reference alphafold2.py:802-807)
        x, m = Evoformer(
            dim=self.dim, depth=self.depth, heads=self.heads,
            dim_head=self.dim_head, attn_dropout=self.attn_dropout,
            ff_dropout=self.ff_dropout,
            ring_attention=self.ring_attention,
            outer_mean_reference_scale=self.outer_mean_reference_scale,
            use_conv=self.use_conv,
            conv_seq_kernels=self.conv_seq_kernels,
            conv_msa_kernels=self.conv_msa_kernels,
            conv_dilations=self.conv_dilations,
            sparse_self_attn=self.sparse_self_attn,
            linear_attn=self.linear_attn,
            kron_attn=self.kron_attn,
            kv_compress_ratio=self.kv_compress_ratio,
            sparse_block=self.sparse_block,
            sparse_num_global=self.sparse_num_global,
            sparse_window=self.sparse_window,
            linear_attn_kind=self.linear_attn_kind,
            performer_nb_features=self.performer_nb_features,
            dtype=self.dtype,
            reversible=self.reversible, use_scan=self.use_scan,
            pipeline_stages=self.pipeline_stages,
            pipeline_microbatches=self.pipeline_microbatches, name="net",
        )(x, m, mask=x_mask, msa_mask=msa_mask, deterministic=deterministic)

        # --- init-time coverage of conditional branches -------------------
        # flax creates params lazily on first call; to keep one params tree
        # valid for every forward configuration (recycling on/off, templates
        # on/off, train on/off — the torch reference gets this for free by
        # building all modules in __init__, alphafold2.py:507-628), touch
        # every branch this trace skipped with tiny dummies during init.
        if self.is_initializing():
            zf = lambda *s: jnp.zeros(s, dtype=self.dtype)
            if msa is not None or embedds is None:
                # embedd_project ran only on the (msa-absent, embedds-given)
                # path; create it otherwise
                Dense(self.dim, param_dtype=jnp.float32, dtype=self.dtype,
                      name="embedd_project")(zf(1, 1, 1, self.num_embedds))
            # projector coverage for every known pretrained-LM width plus
            # the configured num_embedds (skip widths this trace created)
            widths = {constants.MSA_EMBED_DIM, constants.PROTTRAN_EMBED_DIM,
                      constants.ESM_EMBED_DIM, self.num_embedds} - {self.dim}
            seq_w = None if seq_embed is None else seq_embed.shape[-1]
            msa_w = None if msa_embed is None else msa_embed.shape[-1]
            for w in sorted(widths):
                if w != seq_w:
                    Dense(self.dim, param_dtype=jnp.float32,
                          dtype=self.dtype,
                          name=f"seq_embed_project_{w}")(zf(1, 1, w))
                if w != msa_w:
                    Dense(self.dim, param_dtype=jnp.float32,
                          dtype=self.dtype,
                          name=f"msa_embed_project_{w}")(zf(1, 1, 1, w))
            if not (train and original_msa is not None):
                mlm(zf(1, 1, 1, self.dim), jnp.zeros((1, 1, 1), jnp.int32),
                    jnp.ones((1, 1, 1), bool))
            if recyclables is None:
                LayerNorm(dtype=jnp.float32, name="recycling_msa_norm")(
                    jnp.zeros((1, 1, self.dim), jnp.float32))
                LayerNorm(dtype=jnp.float32, name="recycling_pairwise_norm")(
                    jnp.zeros((1, 1, 1, self.dim), jnp.float32))
                nn.Embed(self.recycling_distance_buckets, self.dim,
                         param_dtype=jnp.float32,
                         name="recycling_distance_embed")(
                             jnp.zeros((1, 1, 1), jnp.int32))
            if templates_feats is None:
                t_d = Dense(self.dim, param_dtype=jnp.float32,
                            dtype=self.dtype, name="to_template_embed")(
                                   zf(1, 1, 1, self.templates_dim))
                t_d = PairwiseAttentionBlock(
                    dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                    dtype=self.dtype, name="template_pairwise_embedder")(t_d)
                Attention(dim=self.dim, heads=self.heads,
                          dim_head=self.dim_head, dtype=self.dtype,
                          name="template_pointwise_attn")(
                              zf(1, 1, self.dim), context=zf(1, 1, self.dim))
            if templates_angles is None:
                a = Dense(self.dim, param_dtype=jnp.float32,
                          dtype=self.dtype, name="template_angle_mlp_in")(
                                 zf(1, 1, 1, self.templates_angles_feats_dim))
                Dense(self.dim, param_dtype=jnp.float32, dtype=self.dtype,
                      name="template_angle_mlp_out")(jax.nn.gelu(a))
            if extra_msa is None:
                Evoformer(dim=self.dim, depth=self.extra_msa_evoformer_layers,
                          heads=self.heads, dim_head=self.dim_head,
                          attn_dropout=self.attn_dropout,
                          ff_dropout=self.ff_dropout,
                          global_column_attn=True, dtype=self.dtype,
                          name="extra_msa_evoformer")(
                    zf(1, 1, 1, self.dim), zf(1, 1, 1, self.dim))

        ret_kwargs = {}

        # theta / phi heads before symmetrization (reference alphafold2.py:815-817)
        x_f32 = x.astype(jnp.float32)
        if self.predict_angles:
            ret_kwargs["theta"] = Dense(
                constants.THETA_BUCKETS, param_dtype=jnp.float32,
                name="to_prob_theta")(x_f32)
            ret_kwargs["phi"] = Dense(
                constants.PHI_BUCKETS, param_dtype=jnp.float32,
                name="to_prob_phi")(x_f32)

        # symmetrize pair; distogram head (reference alphafold2.py:821-823)
        trunk_embeds = (x_f32 + x_f32.swapaxes(1, 2)) * 0.5
        distance_pred = LayerNorm(
            dtype=jnp.float32, name="distogram_norm")(trunk_embeds)
        distance_pred = Dense(
            constants.DISTOGRAM_BUCKETS, param_dtype=jnp.float32,
            name="to_distogram_logits")(distance_pred)
        ret_kwargs["distance"] = distance_pred

        # MLM loss (reference alphafold2.py:827-830)
        if train and original_msa is not None and replaced_msa_mask is not None:
            num_msa = original_msa.shape[1]
            ret_kwargs["msa_mlm_loss"] = mlm(
                m[:, :num_msa], original_msa, replaced_msa_mask)

        # omega head (reference alphafold2.py:834-836)
        if self.predict_angles:
            omega_input = trunk_embeds if self.symmetrize_omega else x_f32
            ret_kwargs["omega"] = Dense(
                constants.OMEGA_BUCKETS, param_dtype=jnp.float32,
                name="to_prob_omega")(omega_input)

        # during init, fall through even for return_trunk so the structure
        # module's params always exist in the tree
        if (not self.predict_coords) or \
                (return_trunk and not self.is_initializing()):
            return ReturnValues(**ret_kwargs)

        # single / pairwise projections for the structure module
        # (reference alphafold2.py:843-851); fp32 island from here on
        single_msa_repr_row = m[:, 0]
        single_repr = Dense(self.dim, param_dtype=jnp.float32,
                            name="msa_to_single_repr_dim")(
                                   single_msa_repr_row.astype(jnp.float32))
        pairwise_repr = Dense(self.dim, param_dtype=jnp.float32,
                              name="trunk_to_pairwise_repr_dim")(
                                     x.astype(jnp.float32))

        if self.structure_module_type == "ipa":
            coords, single_out = StructureModule(
                dim=self.dim,
                depth=self.structure_module_depth,
                heads=self.structure_module_heads,
                name="structure_module",
            )(single_repr, pairwise_repr, mask=mask)
        else:
            # equivariant-refiner structure module: deterministic chain
            # init (3.8 A CA spacing) breaks translational symmetry, then
            # iterative E(n)/SE(3) updates driven by single + pair reprs
            chain = jnp.arange(n, dtype=jnp.float32)[None, :, None] * \
                jnp.asarray([3.8, 0.0, 0.0])
            init_coords = jnp.broadcast_to(chain, (b, n, 3))
            single_out, coords = Refiner(
                dim=self.dim, kind=self.structure_module_type,
                iters=self.structure_module_depth,
                edge_dim=self.dim, name="structure_module_refiner",
            )(single_repr, init_coords, edges=pairwise_repr, mask=mask)

        if self.structure_module_refinement_iters > 0:
            if self.structure_module_refinement == "egnn-atom":
                # notebook atom-level path: CA trace -> 14-atom scaffold
                # -> sparse EGNN over the covalent graph; coords contract
                # stays the refined CA slot
                _, atoms = AtomEGNNRefiner(
                    dim=self.dim,
                    iters=self.structure_module_refinement_iters,
                    name="atom_refiner",
                )(single_out, coords, seq, mask=mask)
                ret_kwargs["atoms"] = atoms
                # CA slot — except for residues with no atom cloud at all
                # (unknown/'_' tokens: scn cloud mask all-zero), whose
                # refined slot is zeroed; they keep the structure-module
                # coords instead of collapsing to the origin (r05 review)
                from alphafold2_tpu.data.scn import scn_cloud_mask
                has_ca = scn_cloud_mask(seq)[:, :, 1:2] > 0
                coords = jnp.where(has_ca, atoms[:, :, 1], coords)
            elif self.structure_module_refinement == "residue":
                single_out, coords = Refiner(
                    dim=self.dim, kind="egnn",
                    iters=self.structure_module_refinement_iters,
                    edge_dim=self.dim, name="coords_refiner",
                )(single_out, coords, edges=pairwise_repr, mask=mask)
            else:
                raise ValueError(
                    "structure_module_refinement must be 'residue' or "
                    f"'egnn-atom', got "
                    f"{self.structure_module_refinement!r}")

        # confidence head always built (cheap Dense(1)) so one params tree
        # serves every return configuration
        confidence = Dense(1, param_dtype=jnp.float32,
                           name="lddt_linear")(single_out)
        ret_kwargs["confidence"] = confidence

        if return_recyclables:
            ret_kwargs["recyclables"] = Recyclables(
                jax.lax.stop_gradient(coords),
                jax.lax.stop_gradient(single_msa_repr_row.astype(jnp.float32)),
                jax.lax.stop_gradient(pairwise_repr),
            )

        ret = ReturnValues(**ret_kwargs)

        if return_aux_logits:
            return coords, ret

        if return_confidence:
            return coords, confidence

        if return_recyclables:
            return coords, ret

        return coords
