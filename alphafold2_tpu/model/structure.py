"""Invariant Point Attention structure module, from scratch in JAX.

The reference outsources IPA to the external `invariant-point-attention`
package (/root/reference/alphafold2_pytorch/alphafold2.py:19, :608-611,
:873-879) and runs the frame-refinement loop inline in `Alphafold2.forward`
(alphafold2.py:855-891). Here both are first-class:

- `InvariantPointAttention`: the AF2 (Jumper et al. 2021, Alg. 22) attention
  with scalar, point, and pairwise terms. Point terms are computed in global
  coordinates via the per-residue frames, giving SE(3)-invariant logits and
  equivariant point outputs.
- `IPABlock`: IPA -> post-LN -> transition FF -> post-LN (residual), the
  external package's block layout the reference composes with.
- `StructureModule`: the iterative frame refinement with weight sharing
  across iterations, stop-gradient on rotations except the last iteration
  (the DeepMind folding.py trick the reference cites at alphafold2.py:867),
  and the final local-points -> global-coords map.

Whole module is an fp32 island (reference alphafold2.py:850-855): callers
cast trunk outputs to float32 before entry; all params here are fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.core.quaternion import quaternion_multiply as quat_multiply
from alphafold2_tpu.core.rigid import Rigid
from alphafold2_tpu.model.primitives import (MASK_VALUE, Dense, LayerNorm,
                                              zeros_init)


class InvariantPointAttention(nn.Module):
    """AF2 Algorithm 22. All computation fp32."""

    dim: int
    heads: int = 8
    scalar_key_dim: int = 16
    scalar_value_dim: int = 16
    point_key_dim: int = 4
    point_value_dim: int = 8
    pairwise_repr_dim: Optional[int] = None
    eps: float = 1e-8

    @nn.compact
    def __call__(self, single_repr, pairwise_repr, frames: Rigid, mask=None):
        """single_repr: (b, n, d); pairwise_repr: (b, n, n, d_pair);
        frames: Rigid with (b, n, 4)/(b, n, 3); mask: (b, n) bool."""
        b, n, _ = single_repr.shape
        h = self.heads
        x = single_repr

        dense = lambda features, name, use_bias=True: Dense(
            features, use_bias=use_bias, param_dtype=jnp.float32, name=name)

        # --- scalar qkv ---------------------------------------------------
        q_s = dense(h * self.scalar_key_dim, "to_scalar_q", False)(x)
        k_s = dense(h * self.scalar_key_dim, "to_scalar_k", False)(x)
        v_s = dense(h * self.scalar_value_dim, "to_scalar_v", False)(x)
        split = lambda t, dh: t.reshape(b, n, h, dh).transpose(0, 2, 1, 3)
        q_s = split(q_s, self.scalar_key_dim)
        k_s = split(k_s, self.scalar_key_dim)
        v_s = split(v_s, self.scalar_value_dim)

        # --- point qkv (local frame), mapped to globals -------------------
        n_qk, n_v = self.point_key_dim, self.point_value_dim
        q_p = dense(h * n_qk * 3, "to_point_q", False)(x)
        k_p = dense(h * n_qk * 3, "to_point_k", False)(x)
        v_p = dense(h * n_v * 3, "to_point_v", False)(x)
        as_points = lambda t, p: t.reshape(b, n, h, p, 3)
        q_p, k_p = as_points(q_p, n_qk), as_points(k_p, n_qk)
        v_p = as_points(v_p, n_v)

        # frames broadcast over (h, p): local (b, n, h*p, 3) -> global
        to_global = lambda t: frames.apply(
            t.reshape(b, n, -1, 3)).reshape(t.shape)
        q_pg, k_pg, v_pg = map(to_global, (q_p, k_p, v_p))

        # --- attention logits (Alg. 22 line 7) ----------------------------
        w_c = (2.0 / (9.0 * n_qk)) ** 0.5
        w_l = (1.0 / 3.0) ** 0.5

        scalar_logits = jnp.einsum("bhid,bhjd->bhij", q_s, k_s) * \
            (self.scalar_key_dim ** -0.5)

        # per-head learned point weight gamma, softplus-parameterized
        gamma_raw = self.param(
            "point_weights", nn.initializers.constant(0.541324854612918), (h,))
        gamma = jax.nn.softplus(gamma_raw)

        d2 = jnp.sum(
            (q_pg[:, :, None, :, :, :] - k_pg[:, None, :, :, :, :]) ** 2,
            axis=-1)                                   # (b, i, j, h, p)
        point_logits = -0.5 * w_c * gamma[None, None, None, :] * d2.sum(-1)
        point_logits = point_logits.transpose(0, 3, 1, 2)  # (b, h, i, j)

        logits = scalar_logits + point_logits
        if pairwise_repr is not None:
            pair_bias = Dense(h, use_bias=False, param_dtype=jnp.float32,
                              name="pairwise_to_bias")(pairwise_repr)
            logits = logits + pair_bias.transpose(0, 3, 1, 2)
        logits = logits * w_l

        if mask is not None:
            pair_mask = mask[:, None, :, None] & mask[:, None, None, :]
            logits = jnp.where(pair_mask, logits, MASK_VALUE)

        attn = jax.nn.softmax(logits, axis=-1)  # (b, h, i, j)

        # --- aggregate ----------------------------------------------------
        out_scalar = jnp.einsum("bhij,bhjd->bhid", attn, v_s)
        out_scalar = out_scalar.transpose(0, 2, 1, 3).reshape(b, n, -1)

        out_point_g = jnp.einsum("bhij,bjhpc->bihpc", attn, v_pg)
        # back to the local frame of residue i (equivariance)
        out_point = frames.invert_apply(
            out_point_g.reshape(b, n, -1, 3)).reshape(out_point_g.shape)
        out_point_flat = out_point.reshape(b, n, -1)
        out_point_norm = jnp.sqrt(
            jnp.sum(out_point ** 2, axis=-1) + self.eps).reshape(b, n, -1)

        outputs = [out_scalar, out_point_flat, out_point_norm]
        if pairwise_repr is not None:
            out_pair = jnp.einsum("bhij,bijd->bihd", attn, pairwise_repr)
            outputs.append(out_pair.reshape(b, n, -1))

        out = jnp.concatenate(outputs, axis=-1)
        # zero-init final projection (reference zero-inits ipa attn to_out,
        # alphafold2.py:615)
        return Dense(self.dim, param_dtype=jnp.float32,
                     kernel_init=zeros_init(), bias_init=zeros_init(),
                     name="to_out")(out)


class IPABlock(nn.Module):
    """IPA + transition, post-norm layout (matches the external package the
    reference composes with at alphafold2.py:608-611, :873-879)."""

    dim: int
    heads: int = 8
    ff_mult: int = 1
    ff_num_layers: int = 3

    @nn.compact
    def __call__(self, x, pairwise_repr, frames: Rigid, mask=None):
        x = InvariantPointAttention(
            dim=self.dim, heads=self.heads,
            pairwise_repr_dim=pairwise_repr.shape[-1]
            if pairwise_repr is not None else None,
            name="attn",
        )(x, pairwise_repr, frames, mask=mask) + x
        x = LayerNorm(name="attn_norm")(x)

        hidden = self.dim * self.ff_mult
        ff = x
        for i in range(self.ff_num_layers - 1):
            ff = Dense(hidden, param_dtype=jnp.float32,
                       name=f"ff_{i}")(ff)
            ff = jax.nn.relu(ff)
        ff = Dense(self.dim, param_dtype=jnp.float32,
                   name=f"ff_{self.ff_num_layers - 1}")(ff)
        x = x + ff
        return LayerNorm(name="ff_norm")(x)


class StructureModule(nn.Module):
    """Iterative frame refinement (reference alphafold2.py:855-891).

    One weight-shared IPABlock applied `depth` times; quaternion/translation
    updates from a Linear(dim -> 6); rotation stop-gradient except on the
    last iteration; final coords = to_points(single) mapped through frames.
    """

    dim: int
    depth: int = 4
    heads: int = 1

    @nn.compact
    def __call__(self, single_repr, pairwise_repr, mask=None,
                 return_frames: bool = False):
        single_repr = single_repr.astype(jnp.float32)
        pairwise_repr = pairwise_repr.astype(jnp.float32)
        b, n, _ = single_repr.shape

        block = IPABlock(dim=self.dim, heads=self.heads, name="ipa_block")
        to_update = Dense(6, param_dtype=jnp.float32,
                          name="to_quaternion_update")
        init = Rigid.identity((b, n), dtype=jnp.float32)
        quaternions, translations = init.quaternions, init.translations

        x = single_repr
        for i in range(self.depth):
            is_last = i == self.depth - 1

            # stop-gradient on the rotation *matrices* except on the last
            # iteration (reference alphafold2.py:867-871, citing DeepMind
            # folding.py:L383) — the quaternion chain itself stays
            # differentiable across iterations, exactly as in the reference.
            rot_q = quaternions if is_last else \
                jax.lax.stop_gradient(quaternions)
            frames = Rigid(rot_q, translations)

            x = block(x, pairwise_repr, frames, mask=mask)

            update = to_update(x)
            dq, dt = update[..., :3], update[..., 3:]
            dq = jnp.concatenate(
                [jnp.ones((*dq.shape[:-1], 1), dq.dtype), dq], axis=-1)
            # not Rigid.compose_update: the translation update must rotate by
            # the (possibly stop-gradient) rot_q frames while the quaternion
            # chain stays fully differentiable — compose_update would tie both
            # to the same quaternions
            quaternions = quat_multiply(quaternions, dq)
            translations = translations + jnp.einsum(
                "...c,...cd->...d", dt, frames.rotations)

        points_local = Dense(3, param_dtype=jnp.float32,
                             name="to_points")(x)
        frames = Rigid(quaternions, translations)
        coords = frames.apply_single(points_local)

        if return_frames:
            return coords, x, frames
        return coords, x
