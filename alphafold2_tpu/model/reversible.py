"""Reversible Evoformer trunk: O(1) activation memory in depth.

Capability parity with the reference's reversible trunk
(/root/reference/alphafold2_pytorch/reversible.py — RevNet couplings with a
hand-written `backward_pass` and RNG record/replay, README.md:40
`reversible=True`), redesigned for the actual Evoformer and for JAX:

- each track (pair x, MSA m) is duplicated into two coupling streams;
  per layer:
      m1' = m1 + [MsaAttentionBlock(m2; pair=x_in) - m2]
      m2' = m2 + FeedForward(m1')
      x1' = x1 + [PairwiseAttentionBlock(x2; msa=m_out) - x2]
      x2' = x2 + FeedForward(x1')
  with x_in = (x1+x2)/2 (layer-input pair context for the MSA update) and
  m_out = (m1'+m2')/2 (updated-MSA context for the pair update) — the same
  information flow as the standard EvoformerBlock (alphafold2.py:432-446);
- the whole depth-stack runs under one `jax.custom_vjp`: forward stores
  ONLY the final streams; the backward pass reconstructs each layer's
  inputs by algebraically inverting the couplings (reverse `lax.scan`) and
  re-plays `jax.vjp` per layer. Activation memory is O(1) in depth vs
  O(depth) for scan+remat (which must store every layer's carry);
- dropout composes with reversibility via deterministic key replay (the
  JAX form of the reference's RNG record/replay, reversible.py:26-56): one
  base key rides through the custom_vjp; every coupling derives its mask
  key as fold_in(base, layer*4 + coupling), so the forward pass, the
  algebraic inverse (which must subtract the SAME dropout-realized
  deltas), and the per-layer vjp replay all see identical masks.

Numerical note: reconstruction is exact algebra but floating-point
round-trip; run this trunk in fp32 (default) — bf16 streams accumulate
~1e-2 reconstruction drift per 10 layers.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from alphafold2_tpu.model.attention_variants import (
    DEFAULT_CONV_MSA_KERNELS,
    DEFAULT_CONV_SEQ_KERNELS,
    MultiKernelConvBlock,
)
from alphafold2_tpu.model.primitives import FeedForward
# imported late to avoid a cycle: evoformer imports nothing from here


class RevEvoLayer(nn.Module):
    """The four coupling functions of one reversible Evoformer layer."""

    dim: int
    heads: int
    dim_head: int = 64
    global_column_attn: bool = False
    ring_attention: bool = False
    # the reference's reversible 'conv' block type (reversible.py:303-347
    # dispatches 'conv' through the same coupling machinery as 'self'):
    # the conv blocks join the second (FF) coupling of each track, which
    # keeps the layer exactly invertible — x2' = x2 + f(x1') inverts as
    # x2 = x2' - f(x1') no matter what f contains
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    dtype: Any = jnp.float32

    def setup(self):
        from alphafold2_tpu.model.evoformer import (
            MsaAttentionBlock, PairwiseAttentionBlock)
        self.msa_attn = MsaAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout,
            ring_attention=self.ring_attention, dtype=self.dtype)
        self.msa_ff = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                                  dtype=self.dtype)
        self.pair_attn = PairwiseAttentionBlock(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.attn_dropout,
            global_column_attn=self.global_column_attn,
            ring_attention=self.ring_attention, dtype=self.dtype)
        self.pair_ff = FeedForward(dim=self.dim, dropout=self.ff_dropout,
                                   dtype=self.dtype)
        if self.use_conv:
            self.msa_conv = MultiKernelConvBlock(
                dim=self.dim, kernels=self.conv_msa_kernels,
                dilations=self.conv_dilations, dtype=self.dtype)
            self.pair_conv = MultiKernelConvBlock(
                dim=self.dim, kernels=self.conv_seq_kernels,
                dilations=self.conv_dilations, dtype=self.dtype)

    # deltas (no outer residual — the coupling adds it)
    def delta_msa(self, m2, x_ctx, mask, msa_mask, deterministic=True):
        return self.msa_attn(m2, mask=msa_mask, pairwise_repr=x_ctx,
                             deterministic=deterministic) - m2

    def delta_msa_ff(self, m1, msa_mask, deterministic=True):
        out = self.msa_ff(m1, deterministic=deterministic)
        if self.use_conv:
            out = out + self.msa_conv(m1, mask=msa_mask)
        return out

    def delta_pair(self, x2, m_ctx, mask, msa_mask, deterministic=True):
        return self.pair_attn(x2, mask=mask, msa_repr=m_ctx,
                              msa_mask=msa_mask,
                              deterministic=deterministic) - x2

    def delta_pair_ff(self, x1, mask, deterministic=True):
        out = self.pair_ff(x1, deterministic=deterministic)
        if self.use_conv:
            out = out + self.pair_conv(x1, mask=mask)
        return out

    def __call__(self, m2, m1, x2, x1, mask, msa_mask):
        """Used only at init time to create all params."""
        x_ctx = (x1 + x2) * 0.5
        d1 = self.delta_msa(m2, x_ctx, mask, msa_mask)
        d2 = self.delta_msa_ff(m1, msa_mask)
        d3 = self.delta_pair(x2, (m1 + m2) * 0.5, mask, msa_mask)
        d4 = self.delta_pair_ff(x1, mask)
        return d1, d2, d3, d4


def layer_cfg(dim, heads, dim_head=64, global_column_attn=False,
              ring_attention=False, use_conv=False,
              conv_seq_kernels=DEFAULT_CONV_SEQ_KERNELS,
              conv_msa_kernels=DEFAULT_CONV_MSA_KERNELS,
              conv_dilations=(1,), dtype="float32",
              attn_dropout=0.0, ff_dropout=0.0):
    """The static (hashable) layer-config tuple `_run_reversible` carries
    as a nondiff argument — one constructor so tests and the module can't
    drift from `_make_layer`'s unpacking order."""
    return (dim, heads, dim_head, global_column_attn, ring_attention,
            use_conv, tuple(map(tuple, conv_seq_kernels)),
            tuple(map(tuple, conv_msa_kernels)), tuple(conv_dilations),
            jnp.dtype(dtype).name, float(attn_dropout), float(ff_dropout))


def _make_layer(cfg) -> RevEvoLayer:
    (dim, heads, dim_head, gca, ring, use_conv, seq_k, msa_k, dil,
     dtype_name, attn_drop, ff_drop) = cfg
    return RevEvoLayer(dim=dim, heads=heads, dim_head=dim_head,
                       global_column_attn=gca, ring_attention=ring,
                       use_conv=use_conv, conv_seq_kernels=seq_k,
                       conv_msa_kernels=msa_k, conv_dilations=dil,
                       attn_dropout=attn_drop, ff_dropout=ff_drop,
                       dtype=jnp.dtype(dtype_name), parent=None)


def _coupling_apply(cfg, params, key):
    """Coupling applicator: coupling j runs with the mask key
    fold_in(key, j) — the SAME key in the forward pass, the algebraic
    inverse, and the vjp replay, which is what makes dropout compose with
    reversibility (the reference's RNG record/replay, reversible.py:26-56,
    done as deterministic key derivation)."""
    layer = _make_layer(cfg)

    def ap(method, j, *args):
        if key is None:
            return layer.apply({"params": params}, *args, method=method)
        return layer.apply(
            {"params": params}, *args, False, method=method,
            rngs={"dropout": jax.random.fold_in(key, j)})

    return ap


def _layer_fwd(cfg, params, streams, mask, msa_mask, key=None):
    x1, x2, m1, m2 = streams
    bmask = None if mask is None else mask > 0.5
    bmsa = None if msa_mask is None else msa_mask > 0.5
    ap = _coupling_apply(cfg, params, key)

    x_in = (x1 + x2) * 0.5
    m1 = m1 + ap(RevEvoLayer.delta_msa, 0, m2, x_in, bmask, bmsa)
    m2 = m2 + ap(RevEvoLayer.delta_msa_ff, 1, m1, bmsa)
    m_out = (m1 + m2) * 0.5
    x1 = x1 + ap(RevEvoLayer.delta_pair, 2, x2, m_out, bmask, bmsa)
    x2 = x2 + ap(RevEvoLayer.delta_pair_ff, 3, x1, bmask)
    return (x1, x2, m1, m2)


def _layer_inv(cfg, params, streams, mask, msa_mask, key=None):
    """Exact algebraic inverse of `_layer_fwd` (same `key` -> same
    dropout-realized deltas are subtracted)."""
    x1p, x2p, m1p, m2p = streams
    bmask = None if mask is None else mask > 0.5
    bmsa = None if msa_mask is None else msa_mask > 0.5
    ap = _coupling_apply(cfg, params, key)

    x2 = x2p - ap(RevEvoLayer.delta_pair_ff, 3, x1p, bmask)
    m_out = (m1p + m2p) * 0.5
    x1 = x1p - ap(RevEvoLayer.delta_pair, 2, x2, m_out, bmask, bmsa)
    m2 = m2p - ap(RevEvoLayer.delta_msa_ff, 1, m1p, bmsa)
    x_in = (x1 + x2) * 0.5
    m1 = m1p - ap(RevEvoLayer.delta_msa, 0, m2, x_in, bmask, bmsa)
    return (x1, x2, m1, m2)


def _layer_keys(key, stacked_params):
    """(depth,) per-layer dropout keys (None -> None): layer i uses
    fold_in(base, i); couplings fold in further (_coupling_apply)."""
    if key is None:
        return None
    depth = jax.tree.leaves(stacked_params)[0].shape[0]
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(depth))


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _run_reversible(cfg, stacked_params, streams, mask, msa_mask,
                    key=None):
    keys = _layer_keys(key, stacked_params)

    def body(s, pk):
        p, lk = pk
        return _layer_fwd(cfg, p, s, mask, msa_mask, lk), None

    out, _ = jax.lax.scan(body, streams, (stacked_params, keys))
    return out


def _run_fwd(cfg, stacked_params, streams, mask, msa_mask, key=None):
    out = _run_reversible(cfg, stacked_params, streams, mask, msa_mask,
                          key)
    # store ONLY the outputs — this is the whole point
    return out, (stacked_params, out, mask, msa_mask, key)


def _run_bwd(cfg, res, g):
    stacked_params, out, mask, msa_mask, key = res
    keys = _layer_keys(key, stacked_params)

    def body(carry, pk):
        p, lk = pk
        s_out, d_out = carry
        s_in = _layer_inv(cfg, p, s_out, mask, msa_mask, lk)
        _, vjp = jax.vjp(
            lambda pp, ss: _layer_fwd(cfg, pp, ss, mask, msa_mask, lk),
            p, s_in)
        dp, d_in = vjp(d_out)
        return (s_in, d_in), dp

    (s0, d_in), dps = jax.lax.scan(body, (out, g),
                                   (stacked_params, keys), reverse=True)
    zero_mask = None if mask is None else jnp.zeros_like(mask)
    zero_msa = None if msa_mask is None else jnp.zeros_like(msa_mask)
    # the PRNG key is an integer-typed operand: its documented cotangent
    # type is a float0 zero, not None (None happens to pass under current
    # JAX but is not contract — ADVICE r4)
    zero_key = None if key is None else \
        np.zeros(np.shape(key), dtype=jax.dtypes.float0)
    return dps, d_in, zero_mask, zero_msa, zero_key


_run_reversible.defvjp(_run_fwd, _run_bwd)


class ReversibleEvoformer(nn.Module):
    """Drop-in trunk: same (x, m, mask, msa_mask) -> (x, m) contract as
    `Evoformer`, O(1) activation memory."""

    dim: int
    depth: int
    heads: int = 8
    dim_head: int = 64
    global_column_attn: bool = False
    # ring-parallel attention inside the couplings: the inverse pass and
    # the per-layer vjp replay re-trace the same shard_map ring, so the
    # collectives schedule is identical in forward, reconstruction, and
    # gradient recomputation (tests/test_ring.py::TestReversibleRing)
    ring_attention: bool = False
    # the 'conv' coupling (see RevEvoLayer.use_conv)
    use_conv: bool = False
    conv_seq_kernels: tuple = DEFAULT_CONV_SEQ_KERNELS
    conv_msa_kernels: tuple = DEFAULT_CONV_MSA_KERNELS
    conv_dilations: tuple = (1,)
    # dropout composes with reversibility via deterministic key replay
    # (module docstring); active when deterministic=False and a 'dropout'
    # rng is provided at apply
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, m, mask=None, msa_mask=None,
                 deterministic: bool = True):
        has_dropout = self.attn_dropout > 0.0 or self.ff_dropout > 0.0
        dropout_key = None
        if has_dropout and not deterministic:
            dropout_key = self.make_rng("dropout")
        cfg = layer_cfg(self.dim, self.heads, self.dim_head,
                        self.global_column_attn, self.ring_attention,
                        self.use_conv, self.conv_seq_kernels,
                        self.conv_msa_kernels, self.conv_dilations,
                        jnp.dtype(self.dtype).name,
                        self.attn_dropout, self.ff_dropout)
        layer = _make_layer(cfg)

        mask_f = None if mask is None else mask.astype(jnp.float32)
        msa_f = None if msa_mask is None else msa_mask.astype(jnp.float32)

        # static shapes captured for the init-time dummies (no live tracers
        # may leak into the param init closure)
        x_shape, m_shape = x.shape, m.shape
        mask_shape = None if mask is None else mask.shape
        msa_shape = None if msa_mask is None else msa_mask.shape
        dt = jnp.dtype(self.dtype)

        def init_stacked(rng):
            keys = jax.random.split(rng, self.depth)
            dx = jnp.zeros(x_shape, dt)
            dm = jnp.zeros(m_shape, dt)
            dmask = None if mask_shape is None else jnp.ones(mask_shape, bool)
            dmsa = None if msa_shape is None else jnp.ones(msa_shape, bool)

            def one(key):
                return layer.init(key, dm, dm, dx, dx, dmask, dmsa)["params"]

            return jax.vmap(one)(keys)

        stacked = self.param("rev_layers", init_stacked)

        streams = (x, x, m, m)
        x1, x2, m1, m2 = _run_reversible(cfg, stacked, streams,
                                         mask_f, msa_f, dropout_key)
        return (x1 + x2) * 0.5, (m1 + m2) * 0.5
