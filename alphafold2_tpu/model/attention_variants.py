"""Efficient-attention variants from the reference's README-era menu.

Capability parity with the reference's documented (pre-Evoformer) options
(/root/reference/README.md:271-487): DeepSpeed block-sparse self-attention,
Performer linear cross-attention, Kronecker-pooled cross-attention, and
memory-compressed (KV-downsampled) attention. The reference outsourced
these to CUDA packages (DeepSpeed+triton, performer-pytorch); here they
are small JAX modules sharing the package's gating/zero-init conventions
(primitives.attention_output_tail):

- `LinearAttention` — kernelized softmax-free attention, O(N d^2): the
  Performer role (README.md:419-449). Uses the elu+1 feature map
  (positive, monotone) rather than FAVOR+ random features — deterministic
  and TPU-friendly (two matmuls, no gather);
- `MemoryCompressedAttention` — KV mean-pooled by `compress_ratio`
  (README.md:475-487, "2-4 usually acceptable");
- `kronecker_pool_2d` + `KroneckerAttention` — axial-mean pooling of a
  2-D (pair) context into H + W tokens before cross-attention
  (README.md:451-468: attend to row means and column means, the
  Kronecker-structured O(H+W) compression);
- `block_sparse_mask` + `BlockSparseAttention` — fixed local+global
  block pattern as an additive mask (the DeepSpeed sparse-self-attn
  analog, README.md:388-417; a Pallas true-block-sparse kernel can reuse
  the same pattern).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import nn as jnn

from alphafold2_tpu.model.primitives import (
    Dense,
    MASK_VALUE,
    LayerNorm,
    attention_output_tail,
    zeros_init,
)


def _dense_factory(module_dtype):
    return lambda f, name, use_bias=True, **kw: Dense(
        f, use_bias=use_bias, dtype=module_dtype,
        param_dtype=jnp.float32, name=name, **kw)


def _qkv(dense, x, context, heads, dim_head):
    inner = heads * dim_head
    q = dense(inner, "to_q", use_bias=False)(x)
    kv = dense(inner * 2, "to_kv", use_bias=False)(context)
    k, v = jnp.split(kv, 2, axis=-1)
    split = lambda t: t.reshape(*t.shape[:-1], heads, dim_head
                               ).swapaxes(-2, -3)
    return split(q), split(k), split(v)


class LinearAttention(nn.Module):
    """Kernelized linear attention (Performer slot)."""

    dim: int
    heads: int = 8
    dim_head: int = 64
    gating: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context=None, mask=None, context_mask=None):
        dense = _dense_factory(self.dtype)
        ctx = x if context is None else context
        q, k, v = _qkv(dense, x, ctx, self.heads, self.dim_head)

        phi = lambda t: jnn.elu(t) + 1.0
        q, k = phi(q), phi(k)

        kmask = context_mask if context is not None else mask
        if kmask is not None:
            k = k * kmask[:, None, :, None]
            v = v * kmask[:, None, :, None]

        kv = jnp.einsum("bhnd,bhne->bhde", k, v)
        z = jnp.einsum("bhnd,bhd->bhn", q, k.sum(-2))
        out = jnp.einsum("bhnd,bhde->bhne", q, kv) / \
            jnp.maximum(z[..., None], 1e-6)

        inner = self.heads * self.dim_head
        return attention_output_tail(dense, out, x, inner, self.gating,
                                     self.dim)


def orthogonal_random_features(key, nb_features: int, dim: int):
    """FAVOR+ projection matrix (nb_features, dim): rows are orthogonal
    within each dim-sized block (QR of a Gaussian), with row norms
    redrawn chi(dim) — the unbiased orthogonal random features of
    Choromanski et al. 2021 (the reference's performer-pytorch
    gaussian_orthogonal_random_matrix, README.md:419-449)."""
    n_blocks = -(-nb_features // dim)
    keys = jax.random.split(key, n_blocks + 1)
    blocks = []
    for i in range(n_blocks):
        g = jax.random.normal(keys[i], (dim, dim))
        q, _ = jnp.linalg.qr(g)
        blocks.append(q.T)
    w = jnp.concatenate(blocks, axis=0)[:nb_features]
    norms = jnp.sqrt(jax.random.chisquare(keys[-1], dim, (nb_features, 1)))
    return w * norms


def favor_softmax_features(x, proj, is_query: bool, eps: float = 1e-4,
                           mask=None):
    """Positive softmax-kernel features phi(x) (FAVOR+, Choromanski et al.
    2021 eq. 5): phi(x) = exp(Wx - ||x||^2/2 - c) / sqrt(m), giving the
    unbiased estimator E[phi(q)^T phi(k)] = exp(q . k).

    x: (..., n, d) already scaled by d^-1/4 (so q.k carries the 1/sqrt(d)
    softmax temperature). Stabilizer c: per-token max for queries, per
    ATTENTION INSTANCE (last two axes: tokens x features, i.e. one c per
    batch/head slice) for keys — both cancel in the attention ratio. A
    coarser global key max would let one high-magnitude batch entry crush
    every other entry's features toward the eps floor (performer-pytorch
    likewise uses amax over (-1, -2)). `mask` (..., n) excludes padded
    tokens from the key max; masked rows are pinned near c so exp cannot
    overflow before the caller zeroes them."""
    m = proj.shape[0]
    u = x @ proj.T                                     # (..., n, m)
    sq = (x * x).sum(-1, keepdims=True) / 2.0
    h = u - sq
    if mask is not None:
        h = jnp.where(mask[..., None], h, -jnp.inf)
    finite = jnp.where(jnp.isfinite(h), h, -1e30)
    if is_query:
        c = jax.lax.stop_gradient(finite.max(-1, keepdims=True))
    else:
        c = jax.lax.stop_gradient(
            jnp.max(finite, axis=(-1, -2), keepdims=True))
    h = jnp.where(jnp.isfinite(h), h, c - 100.0)  # masked -> exp ~ 0
    return (jnp.exp(h - c) + eps) / jnp.sqrt(m)


class PerformerAttention(nn.Module):
    """FAVOR+ attention (the reference's cross_attn_linear Performer,
    README.md:419-449): unbiased softmax-kernel approximation via
    orthogonal random features — O(n m d) instead of O(n^2 d), with the
    approximation error shrinking as `nb_features` grows
    (tests/test_attention_menu.py::test_favor_error_shrinks_with_features).

    Redraw hook: the projection is drawn from the 'performer' RNG
    collection when provided — `module.apply(params, x,
    rngs={"performer": key})` redraws per call (the JAX form of
    performer-pytorch's redraw_projections interval); without it a fixed
    fallback key keeps features deterministic across steps.
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    nb_features: int = 256
    gating: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context=None, mask=None, context_mask=None):
        dense = _dense_factory(self.dtype)
        ctx = x if context is None else context
        q, k, v = _qkv(dense, x, ctx, self.heads, self.dim_head)
        # FAVOR splits the softmax temperature as d^-1/4 on each of q and
        # k so phi(q)^T phi(k) estimates exp(q.k / sqrt(d)); features run
        # in f32 (exp of differences — bf16 rounding hurts here)
        scale = self.dim_head ** 0.25
        q = (q / scale).astype(jnp.float32)
        k = (k / scale).astype(jnp.float32)

        if self.has_rng("performer"):
            feat_key = self.make_rng("performer")
        else:
            # deterministic fallback, distinct per module path (helps
            # unrolled trunks; a scanned trunk shares one module, so
            # per-layer independence there comes from supplying the
            # 'performer' rng — the train loop and predict.fold both do)
            import zlib
            path = "/".join(self.scope.path) if self.scope else ""
            feat_key = jax.random.PRNGKey(zlib.crc32(path.encode()))
        proj = orthogonal_random_features(feat_key, self.nb_features,
                                          self.dim_head)

        kmask = context_mask if context is not None else mask
        kmask4 = None if kmask is None else kmask[:, None, :]
        phi_q = favor_softmax_features(q, proj, is_query=True)
        phi_k = favor_softmax_features(k, proj, is_query=False,
                                       mask=kmask4)

        if kmask is not None:
            w = kmask[:, None, :, None]
            phi_k = phi_k * w
            v = v * w

        kv = jnp.einsum("bhnm,bhne->bhme", phi_k, v.astype(jnp.float32))
        z = jnp.einsum("bhnm,bhm->bhn", phi_q, phi_k.sum(-2))
        out = jnp.einsum("bhnm,bhme->bhne", phi_q, kv) / \
            jnp.maximum(z[..., None], 1e-6)
        out = out.astype(self.dtype)

        inner = self.heads * self.dim_head
        return attention_output_tail(dense, out, x, inner, self.gating,
                                     self.dim)


class MemoryCompressedAttention(nn.Module):
    """Standard attention with mean-pooled K/V (compression ratio r)."""

    dim: int
    heads: int = 8
    dim_head: int = 64
    compress_ratio: int = 2
    gating: bool = True
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        dense = _dense_factory(self.dtype)
        q, k, v = _qkv(dense, x, x, self.heads, self.dim_head)
        r = self.compress_ratio
        b, h, n, d = k.shape
        pad = (-n) % r
        # always pool with real counts so zero padding never dilutes the
        # last block (mask=None behaves as an all-ones mask)
        m = mask if mask is not None else jnp.ones((b, n), dtype=bool)
        if pad:
            k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
            m = jnp.pad(m, ((0, 0), (0, pad)))
        w = m[:, None, :, None].astype(k.dtype)
        k = (k * w).reshape(b, h, -1, r, d).sum(3)
        v = (v * w).reshape(b, h, -1, r, d).sum(3)
        counts = w.reshape(b, 1, -1, r, 1).sum(3)
        k = k / jnp.maximum(counts, 1.0)
        v = v / jnp.maximum(counts, 1.0)
        kmask = jnp.broadcast_to((counts[..., 0] > 0)[:, :, None, :],
                                 (b, 1, 1, k.shape[2]))

        dots = jnp.einsum("bhid,bhjd->bhij", q * (d ** -0.5), k)
        dots = jnp.where(kmask, dots, MASK_VALUE)
        if mask is not None:
            dots = jnp.where(mask[:, None, :, None], dots, MASK_VALUE)
        attn = jnn.softmax(dots, axis=-1)
        attn = nn.Dropout(self.dropout)(attn, deterministic=deterministic)
        out = jnp.einsum("bhij,bhjd->bhid", attn, v)

        inner = self.heads * self.dim_head
        return attention_output_tail(dense, out, x, inner, self.gating,
                                     self.dim)


def kronecker_pool_2d(
    context: jnp.ndarray,
    context_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(b, H, W, d) pair map -> (b, H + W, d) axial-mean tokens: masked
    mean over columns (one token per row) concatenated with masked mean
    over rows (one token per column) — the Kronecker-structured O(H+W)
    context compression (reference README.md:451-468).

    context_mask: optional (b, H, W) validity. Returns (tokens, token_mask).
    """
    b, height, width, d = context.shape
    if context_mask is None:
        rows = context.mean(2)
        cols = context.mean(1)
        token_mask = jnp.ones((b, height + width), dtype=bool)
    else:
        w = context_mask[..., None].astype(context.dtype)
        rows = (context * w).sum(2) / jnp.maximum(w.sum(2), 1.0)
        cols = (context * w).sum(1) / jnp.maximum(w.sum(1), 1.0)
        token_mask = jnp.concatenate(
            [context_mask.any(2), context_mask.any(1)], axis=1)
    return jnp.concatenate([rows, cols], axis=1), token_mask


class KroneckerAttention(nn.Module):
    """Cross-attention from a 1-D stream onto the axial-pooled (H + W
    token) pair context."""

    dim: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, context_2d, mask=None, context_mask=None,
                 deterministic: bool = True):
        from alphafold2_tpu.model.primitives import Attention
        pooled, token_mask = kronecker_pool_2d(context_2d, context_mask)
        return Attention(dim=self.dim, heads=self.heads,
                         dim_head=self.dim_head, dropout=self.dropout,
                         dtype=self.dtype, name="attn")(
            x, mask=mask, context=pooled, context_mask=token_mask,
            deterministic=deterministic)


# README-era defaults (reference README.md:305-307): 1d+2d kernel mix
# for the (n, n) pair map and the (rows, n) MSA. The single source —
# EvoformerBlock/Evoformer/Alphafold2/RevEvoLayer all default to these.
DEFAULT_CONV_SEQ_KERNELS = ((9, 1), (1, 9), (3, 3))
DEFAULT_CONV_MSA_KERNELS = ((1, 9), (3, 3))


class MultiKernelConvBlock(nn.Module):
    """trRosetta2-style residual conv block (reference README.md:271-340
    `use_conv=True` + `conv_seq_kernels`/`conv_msa_kernels`/dilations —
    "combining 1d and 2d kernels in one resnet-like block"): parallel
    NHWC 2-D convolutions with per-branch kernel shapes x dilations over
    the two spatial axes, averaged, gelu, then a zero-init output
    projection (the package's residual-branch convention — the block is
    an identity at init). The caller adds the residual.

    TPU-first deviations from the README-era design: NHWC layout (XLA's
    native conv layout on TPU — no transposes around the MXU) and the
    dilation cycle applied WITHIN the block (one branch per kernel x
    dilation) instead of varying per layer: the trunk runs under
    `nn.scan`, which requires every layer to share one static config,
    and in-block multi-dilation preserves the mixed receptive fields the
    cycle existed to provide.

    Masking: invalid spatial positions are zeroed BEFORE the convs so
    padding never leaks into valid cells through the kernel window.
    """

    dim: int
    kernels: Tuple[Tuple[int, int], ...] = ((3, 3),)
    dilations: Tuple[int, ...] = (1,)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None):
        h = LayerNorm(dtype=self.dtype)(x)
        if mask is not None:
            h = h * mask[..., None].astype(h.dtype)
        branches = []
        for kh, kw in self.kernels:
            for d in self.dilations:
                branches.append(nn.Conv(
                    features=self.dim, kernel_size=(kh, kw),
                    kernel_dilation=(d, d), padding="SAME",
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name=f"conv_{kh}x{kw}_d{d}")(h))
        h = jnn.gelu(sum(branches) / len(branches))
        out = Dense(self.dim, kernel_init=zeros_init(),
                    bias_init=zeros_init(), dtype=self.dtype,
                    param_dtype=jnp.float32, name="proj_out")(h)
        if mask is not None:
            out = out * mask[..., None].astype(out.dtype)
        return out


def block_sparse_block_pattern(n_blocks: int, num_global: int = 1,
                               window: int = 1):
    """(n_blocks, n_blocks) bool numpy block pattern: attend within
    +-`window` blocks of the diagonal plus the first `num_global` blocks
    (global tokens). Delegates to `ops.block_sparse.
    banded_block_pattern` — the ONE local+global source the dense mask
    below, the Pallas kernel plan, and the serving KernelPolicy's
    static masks all share, so no two of them can diverge."""
    from alphafold2_tpu.ops.block_sparse import banded_block_pattern
    return banded_block_pattern(n_blocks, window=window,
                                num_global=num_global)


def block_sparse_mask(n: int, block: int = 32, num_global: int = 1,
                      window: int = 1) -> jnp.ndarray:
    """(n, n) bool token mask expanded from `block_sparse_block_pattern`
    (handles a trailing partial block when n % block != 0)."""
    nb = -(-n // block)
    bp = jnp.asarray(block_sparse_block_pattern(nb, num_global, window))
    bi = jnp.arange(n) // block
    return bp[bi[:, None], bi[None, :]]


class BlockSparseAttention(nn.Module):
    """Self-attention restricted to a fixed block-sparse pattern (the
    DeepSpeed sparse-attention analog, reference README.md:388-417).

    Two compute backends behind ONE params tree (the projections and
    gated output tail live in the inner `Attention`, shared by both):

    - the true block-skipping Pallas kernel
      (`ops.block_sparse.block_sparse_attention`, FLOPs ∝ nnz blocks):
      the DEFAULT on a TPU backend whenever n divides into `block`s
      (ISSUE 12 — the documented sparse config must actually skip
      FLOPs, not just mask them); off-TPU it is opt-in via
      `ops.use_pallas_attention(True)` (interpreter mode, exactness
      tests only);
    - dense + additive mask: the CPU fallback (and the dropout-active
      training path) — identical attention support, no FLOP skipping.

    Token masks ride into the kernel as per-key validity (replayed
    across the folded head axis); masked-query rows are unspecified on
    both backends. Exactness between the backends:
    tests/test_ops.py::TestBlockSparseKernel.
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    block: int = 32
    num_global: int = 1
    window: int = 1
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    def _kernel_available(self) -> bool:
        """True when the FLOP-skipping Pallas kernel should serve this
        trace: on a TPU backend it is ALWAYS preferred (ISSUE 12 — the
        old gate made the documented sparse_self_attn config silently
        pay dense N^2 compute unless the unrelated fused-attention
        flag was flipped); off-TPU it stays opt-in via
        `ops.use_pallas_attention(True)` (interpreter mode — exactness
        tests), so CPU tier-1 keeps the cheap masked-dense fallback."""
        from alphafold2_tpu.ops.attention import pallas_attention_enabled
        from alphafold2_tpu.ops.block_sparse import (HAS_PALLAS,
                                                     on_tpu_backend)
        if not HAS_PALLAS:
            return False
        return on_tpu_backend() or pallas_attention_enabled()

    @nn.compact
    def __call__(self, x, mask=None, deterministic: bool = True):
        from alphafold2_tpu.model.primitives import Attention
        n = x.shape[-2]
        attn = Attention(dim=self.dim, heads=self.heads,
                         dim_head=self.dim_head, dropout=self.dropout,
                         dtype=self.dtype, name="attn")

        use_kernel = self._kernel_available() and n % self.block == 0
        if use_kernel and \
                not (self.dropout == 0.0 or deterministic):
            # refuse-to-be-silent: the Pallas kernel has no dropout, so a
            # dropout-active training trace pays full dense n^2 attention
            import warnings
            warnings.warn(
                "BlockSparseAttention: dropout>0 under training falls "
                "back to DENSE masked attention (the Pallas block-"
                "skipping kernel has no dropout) — the sparse FLOP "
                "savings do not apply to these steps", RuntimeWarning,
                stacklevel=2)
        if use_kernel and (self.dropout == 0.0 or deterministic):
            from alphafold2_tpu.ops.block_sparse import (
                block_sparse_attention)
            block_pattern = block_sparse_block_pattern(
                n // self.block, self.num_global, self.window)
            q, k, v = attn.project_qkv(x)          # (b, h, n, dh), q scaled
            b, h, _, dh = q.shape
            out = block_sparse_attention(
                q.reshape(b * h, n, dh), k.reshape(b * h, n, dh),
                v.reshape(b * h, n, dh), block_pattern,
                k_mask=mask,                       # unrepeated; index map
                heads=h,                           # replays across heads
                scale=1.0,                         # project_qkv pre-scales
                block=self.block,
                interpret=jax.default_backend() == "cpu")
            return attn.finish(out.reshape(b, h, n, dh), x)

        pattern = block_sparse_mask(n, self.block, self.num_global,
                                    self.window)
        bias = jnp.where(pattern, 0.0, MASK_VALUE)[None, None]
        return attn(x, mask=mask, attn_bias=bias,
                    deterministic=deterministic)
