"""Equivariant coordinate refiners: EGNN, En-Transformer-style, SE3-style.

Capability parity with the reference's secondary structure modules — the
README-era API `structure_module_type = 'se3' | 'egnn' | 'en'` with
`refinement_iters` (/root/reference/README.md:106-112, :594-600,
train_end2end.py:83-87) and the EGNN end-to-end notebook
(notebooks/egnn_esm_end2end.ipynb cells 25-33). The reference outsources
these to external CUDA-backed packages (egnn-pytorch, En-transformer,
se3-transformer-pytorch — setup.py:19-34); here they are small pure-JAX
message-passing layers:

- E(n)-equivariant updates operate on distances and relative vectors only,
  so rotating/translating inputs rotates/translates outputs exactly;
- all-pairs messages are dense (b, n, n) tensors — at protein scale the
  dense form is one MXU matmul, beating sparse gather/scatter on TPU
  (SURVEY.md §2.4's torch-sparse note);
- coordinate updates are tanh-clamped for stability (the notebook's NaN
  debugging, cell 37, is the failure mode this guards).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.model.primitives import Dense, LayerNorm, zeros_init


def _safe_norm2(v, eps=1e-8):
    return jnp.sum(v * v, axis=-1, keepdims=True) + eps


class EGNNLayer(nn.Module):
    """One E(n)-GNN layer (Satorras et al.): invariant messages from
    (h_i, h_j, ||x_i - x_j||^2, e_ij), equivariant coordinate update along
    relative vectors."""

    dim: int
    edge_dim: int = 0
    hidden: Optional[int] = None
    coor_clamp: float = 3.0

    @nn.compact
    def __call__(self, h, x, edges=None, mask=None):
        """h: (b, n, d) node feats; x: (b, n, 3) coords;
        edges: (b, n, n, e) optional; mask: (b, n) optional."""
        hidden = self.hidden or self.dim * 2
        b, n, _ = h.shape

        rel = x[:, :, None, :] - x[:, None, :, :]           # (b, n, n, 3)
        dist2 = _safe_norm2(rel)                            # (b, n, n, 1)

        feats = [jnp.broadcast_to(h[:, :, None, :], (b, n, n, h.shape[-1])),
                 jnp.broadcast_to(h[:, None, :, :], (b, n, n, h.shape[-1])),
                 dist2]
        if edges is not None:
            feats.append(edges)
        msg_in = jnp.concatenate(feats, axis=-1)

        msg = Dense(hidden, param_dtype=jnp.float32, name="edge_mlp_in")(
            msg_in)
        msg = jax.nn.silu(msg)
        msg = Dense(hidden, param_dtype=jnp.float32, name="edge_mlp_out")(
            msg)
        msg = jax.nn.silu(msg)

        if mask is not None:
            pair_mask = (mask[:, :, None] & mask[:, None, :])[..., None]
            msg = msg * pair_mask
        # no self-messages
        eye = jnp.eye(n, dtype=msg.dtype)[None, :, :, None]
        msg = msg * (1.0 - eye)

        # equivariant coordinate update, zero-init scale so the layer starts
        # as identity on coordinates
        coor_w = Dense(1, param_dtype=jnp.float32, use_bias=False,
                       kernel_init=zeros_init(), name="coor_mlp")(msg)
        coor_w = jnp.tanh(coor_w) * self.coor_clamp
        denom = jnp.maximum(
            (mask.astype(x.dtype).sum(-1) - 1.0)[:, None, None]
            if mask is not None else jnp.asarray(float(n - 1)), 1.0)
        x = x + (rel / jnp.sqrt(dist2) * coor_w).sum(axis=2) / denom

        # invariant feature update
        agg = msg.sum(axis=2) / denom
        h_in = jnp.concatenate([h, agg], axis=-1)
        dh = Dense(hidden, param_dtype=jnp.float32, name="node_mlp_in")(
            h_in)
        dh = jax.nn.silu(dh)
        dh = Dense(self.dim, param_dtype=jnp.float32, name="node_mlp_out")(
            dh)
        return h + dh, x


class EnAttentionLayer(nn.Module):
    """En-Transformer-style layer: attention-weighted invariant messages +
    equivariant coordinate update (attention replaces EGNN's sum pooling;
    reference capability via the `En-transformer` dependency,
    setup.py:19-34)."""

    dim: int
    heads: int = 4
    dim_head: int = 32
    edge_dim: int = 0
    coor_clamp: float = 3.0

    @nn.compact
    def __call__(self, h, x, edges=None, mask=None):
        b, n, d = h.shape
        hd, nh = self.dim_head, self.heads
        inner = hd * nh

        hn = LayerNorm(name="norm")(h)
        q = Dense(inner, use_bias=False, param_dtype=jnp.float32,
                  name="to_q")(hn).reshape(b, n, nh, hd)
        k = Dense(inner, use_bias=False, param_dtype=jnp.float32,
                  name="to_k")(hn).reshape(b, n, nh, hd)
        v = Dense(inner, use_bias=False, param_dtype=jnp.float32,
                  name="to_v")(hn).reshape(b, n, nh, hd)

        rel = x[:, :, None, :] - x[:, None, :, :]
        dist2 = _safe_norm2(rel)

        logits = jnp.einsum("bihd,bjhd->bhij", q, k) * (hd ** -0.5)
        # distance-aware bias (+ optional pair-rep edge bias)
        dist_bias = Dense(nh, param_dtype=jnp.float32,
                          name="dist_to_bias")(jnp.log(dist2))
        logits = logits + dist_bias.transpose(0, 3, 1, 2)
        if edges is not None:
            logits = logits + Dense(
                nh, use_bias=False, param_dtype=jnp.float32,
                name="edge_to_bias")(edges).transpose(0, 3, 1, 2)

        if mask is not None:
            pair_mask = mask[:, None, :, None] & mask[:, None, None, :]
            logits = jnp.where(pair_mask, logits, -1e9)

        attn = jax.nn.softmax(logits, axis=-1)              # (b, h, i, j)

        out = jnp.einsum("bhij,bjhd->bihd", attn, v).reshape(b, n, inner)
        h = h + Dense(self.dim, param_dtype=jnp.float32,
                      kernel_init=zeros_init(), bias_init=zeros_init(),
                      name="to_out")(out)

        # equivariant coordinate update weighted by mean attention
        coor_w = Dense(1, use_bias=False, param_dtype=jnp.float32,
                       kernel_init=zeros_init(), name="coor_mlp")(
                              attn.mean(1)[..., None])
        coor_w = jnp.tanh(coor_w) * self.coor_clamp
        x = x + (rel / jnp.sqrt(dist2) * coor_w).sum(axis=2) / max(n - 1, 1)
        return h, x


class Refiner(nn.Module):
    """Iterative equivariant refinement head (README-era
    `structure_module_type` + `refinement_iters`). Weight-shared layer
    applied `iters` times, mirroring the reference's refinement loop."""

    dim: int
    kind: str = "egnn"        # 'egnn' | 'en' | 'se3'
    iters: int = 4
    edge_dim: int = 0
    heads: int = 4

    @nn.compact
    def __call__(self, h, x, edges=None, mask=None):
        if self.kind == "egnn":
            layer = EGNNLayer(dim=self.dim, edge_dim=self.edge_dim,
                              name="layer")
        elif self.kind in ("en", "se3"):
            # 'se3' maps onto the vector-equivariant attention layer: on
            # point clouds with scalar features, SE(3) equivariance is
            # exactly E(3) equivariance of this update
            layer = EnAttentionLayer(dim=self.dim, heads=self.heads,
                                     edge_dim=self.edge_dim, name="layer")
        else:
            raise ValueError(f"unknown refiner kind {self.kind!r}")

        for _ in range(self.iters):
            h, x = layer(h, x, edges=edges, mask=mask)
        return h, x


# ---------------------------------------------------------------------------
# Atom-level refinement over the covalent-bond graph (round-4 VERDICT #8)
# ---------------------------------------------------------------------------


class SparseEGNNLayer(nn.Module):
    """EGNN over a fixed-degree neighbor list instead of all pairs.

    The reference notebook refines at the ATOM level with a *sparse* EGNN
    over the 14-slot covalent graph (egnn_esm_end2end.ipynb cells 25-33,
    utils.py:497-650). The atom cloud is L*14 nodes; all-pairs messages
    would be O((L*14)^2) — 12.8M pairs at 256 res — for a graph whose true
    degree is <= 4. The TPU-native sparse form is a static-shape GATHER:
    each node sees exactly `max_degree` neighbor slots (take_along_axis
    over precomputed indices), so messages are O(N * max_degree), no
    dynamic shapes, no scatter.
    """

    dim: int
    max_degree: int = 4
    hidden: Optional[int] = None
    coor_clamp: float = 3.0

    @nn.compact
    def __call__(self, h, x, neigh_idx, neigh_mask, mask=None):
        """h: (b, N, d); x: (b, N, 3); neigh_idx/(b, N, K) int indices;
        neigh_mask: (b, N, K) 1.0 where the slot holds a real bond;
        mask: (b, N) node validity."""
        hidden = self.hidden or self.dim * 2
        b, n_nodes, d = h.shape
        k = neigh_idx.shape[-1]

        def gather(t, idx):
            # t (b, N, c), idx (b, N, K) -> (b, N, K, c)
            c = t.shape[-1]
            flat = jnp.broadcast_to(idx.reshape(b, n_nodes * k, 1),
                                    (b, n_nodes * k, c))
            return jnp.take_along_axis(t, flat, axis=1).reshape(
                b, n_nodes, k, c)

        h_j = gather(h, neigh_idx)                       # (b, N, K, d)
        x_j = gather(x, neigh_idx)                       # (b, N, K, 3)
        rel = x[:, :, None, :] - x_j                     # (b, N, K, 3)
        dist2 = _safe_norm2(rel)                         # (b, N, K, 1)

        live = neigh_mask[..., None]
        if mask is not None:
            live = live * mask[:, :, None, None]
        msg_in = jnp.concatenate(
            [jnp.broadcast_to(h[:, :, None, :], (b, n_nodes, k, d)),
             h_j, dist2], axis=-1)
        msg = jax.nn.silu(Dense(hidden, param_dtype=jnp.float32,
                                name="edge_mlp_in")(msg_in))
        msg = jax.nn.silu(Dense(hidden, param_dtype=jnp.float32,
                                name="edge_mlp_out")(msg))
        msg = msg * live

        coor_w = Dense(1, param_dtype=jnp.float32, use_bias=False,
                       kernel_init=zeros_init(), name="coor_mlp")(msg)
        coor_w = jnp.tanh(coor_w) * self.coor_clamp * live
        denom = jnp.maximum(live.sum(axis=2), 1.0)       # (b, N, 1)
        x = x + (rel / jnp.sqrt(dist2) * coor_w).sum(axis=2) / denom

        agg = msg.sum(axis=2) / denom
        dh = jax.nn.silu(Dense(hidden, param_dtype=jnp.float32,
                               name="node_mlp_in")(
            jnp.concatenate([h, agg], axis=-1)))
        dh = Dense(self.dim, param_dtype=jnp.float32,
                   name="node_mlp_out")(dh)
        if mask is not None:
            dh = dh * mask[:, :, None]
        return h + dh, x


class AtomEGNNRefiner(nn.Module):
    """Atom-level coordinate refinement: residue repr + CA trace ->
    14-atom scaffold (core/nerf.sidechain_container) -> sparse EGNN over
    the covalent-bond adjacency (data/graph.prot_covalent_bond) ->
    refined atom cloud.

    The `structure_module_refinement='egnn-atom'` mode (reference
    notebook cells 25-33; utils.py:497-650 `mat_input_to_masked` +
    `prot_covalent_bond`). Returns (h_atoms, atoms) with atoms
    (b, L, 14, 3); the CA slot [:, :, 1] is the model's coords contract.
    """

    dim: int
    iters: int = 2
    max_degree: int = 4

    @nn.compact
    def __call__(self, h_res, ca_coords, seq, mask=None):
        """h_res: (b, L, d) single repr; ca_coords: (b, L, 3);
        seq: (b, L) tokens; mask: (b, L) residue validity."""
        from alphafold2_tpu import constants
        from alphafold2_tpu.core.nerf import sidechain_container
        from alphafold2_tpu.data.graph import covalent_neighbor_table
        from alphafold2_tpu.data.scn import scn_atom_embedd, scn_cloud_mask

        b, l, d = h_res.shape
        kk = constants.NUM_COORDS_PER_RES
        n_atoms = l * kk

        atoms = sidechain_container(
            ca_coords.astype(jnp.float32)[:, :, None, :], seq)
        cloud = scn_cloud_mask(seq)                     # (b, L, 14)
        if mask is not None:
            cloud = cloud * mask[..., None].astype(cloud.dtype)

        atom_tok = scn_atom_embedd(seq)                 # (b, L, 14)
        h_atom = Dense(self.dim, param_dtype=jnp.float32,
                       name="res_to_atom")(h_res)[:, :, None, :] + \
            nn.Embed(constants.NUM_ATOM_TOKENS, self.dim,
                     param_dtype=jnp.float32,
                     name="atom_id_embed")(atom_tok)

        # static-degree neighbor list straight from the bond tables —
        # O(N*K); never materializes the (N, N) adjacency
        neigh_idx, neigh_mask = covalent_neighbor_table(seq)

        h = h_atom.reshape(b, n_atoms, self.dim)
        x = atoms.reshape(b, n_atoms, 3)
        node_mask = cloud.reshape(b, n_atoms)
        # a bond to a masked atom slot is not a message path
        neigh_mask = neigh_mask * jnp.take_along_axis(
            node_mask, neigh_idx.reshape(b, -1), axis=1).reshape(
            neigh_idx.shape)

        layer = SparseEGNNLayer(dim=self.dim, max_degree=self.max_degree,
                                name="layer")
        for _ in range(self.iters):
            h, x = layer(h, x, neigh_idx, neigh_mask, mask=node_mask)

        atoms = x.reshape(b, l, kk, 3) * cloud[..., None]
        return h.reshape(b, l, kk, self.dim), atoms
