"""Equivariant coordinate refiners: EGNN, En-Transformer-style, SE3-style.

Capability parity with the reference's secondary structure modules — the
README-era API `structure_module_type = 'se3' | 'egnn' | 'en'` with
`refinement_iters` (/root/reference/README.md:106-112, :594-600,
train_end2end.py:83-87) and the EGNN end-to-end notebook
(notebooks/egnn_esm_end2end.ipynb cells 25-33). The reference outsources
these to external CUDA-backed packages (egnn-pytorch, En-transformer,
se3-transformer-pytorch — setup.py:19-34); here they are small pure-JAX
message-passing layers:

- E(n)-equivariant updates operate on distances and relative vectors only,
  so rotating/translating inputs rotates/translates outputs exactly;
- all-pairs messages are dense (b, n, n) tensors — at protein scale the
  dense form is one MXU matmul, beating sparse gather/scatter on TPU
  (SURVEY.md §2.4's torch-sparse note);
- coordinate updates are tanh-clamped for stability (the notebook's NaN
  debugging, cell 37, is the failure mode this guards).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu.model.primitives import Dense, LayerNorm, zeros_init


def _safe_norm2(v, eps=1e-8):
    return jnp.sum(v * v, axis=-1, keepdims=True) + eps


class EGNNLayer(nn.Module):
    """One E(n)-GNN layer (Satorras et al.): invariant messages from
    (h_i, h_j, ||x_i - x_j||^2, e_ij), equivariant coordinate update along
    relative vectors."""

    dim: int
    edge_dim: int = 0
    hidden: Optional[int] = None
    coor_clamp: float = 3.0

    @nn.compact
    def __call__(self, h, x, edges=None, mask=None):
        """h: (b, n, d) node feats; x: (b, n, 3) coords;
        edges: (b, n, n, e) optional; mask: (b, n) optional."""
        hidden = self.hidden or self.dim * 2
        b, n, _ = h.shape

        rel = x[:, :, None, :] - x[:, None, :, :]           # (b, n, n, 3)
        dist2 = _safe_norm2(rel)                            # (b, n, n, 1)

        feats = [jnp.broadcast_to(h[:, :, None, :], (b, n, n, h.shape[-1])),
                 jnp.broadcast_to(h[:, None, :, :], (b, n, n, h.shape[-1])),
                 dist2]
        if edges is not None:
            feats.append(edges)
        msg_in = jnp.concatenate(feats, axis=-1)

        msg = Dense(hidden, param_dtype=jnp.float32, name="edge_mlp_in")(
            msg_in)
        msg = jax.nn.silu(msg)
        msg = Dense(hidden, param_dtype=jnp.float32, name="edge_mlp_out")(
            msg)
        msg = jax.nn.silu(msg)

        if mask is not None:
            pair_mask = (mask[:, :, None] & mask[:, None, :])[..., None]
            msg = msg * pair_mask
        # no self-messages
        eye = jnp.eye(n, dtype=msg.dtype)[None, :, :, None]
        msg = msg * (1.0 - eye)

        # equivariant coordinate update, zero-init scale so the layer starts
        # as identity on coordinates
        coor_w = Dense(1, param_dtype=jnp.float32, use_bias=False,
                       kernel_init=zeros_init(), name="coor_mlp")(msg)
        coor_w = jnp.tanh(coor_w) * self.coor_clamp
        denom = jnp.maximum(
            (mask.astype(x.dtype).sum(-1) - 1.0)[:, None, None]
            if mask is not None else jnp.asarray(float(n - 1)), 1.0)
        x = x + (rel / jnp.sqrt(dist2) * coor_w).sum(axis=2) / denom

        # invariant feature update
        agg = msg.sum(axis=2) / denom
        h_in = jnp.concatenate([h, agg], axis=-1)
        dh = Dense(hidden, param_dtype=jnp.float32, name="node_mlp_in")(
            h_in)
        dh = jax.nn.silu(dh)
        dh = Dense(self.dim, param_dtype=jnp.float32, name="node_mlp_out")(
            dh)
        return h + dh, x


class EnAttentionLayer(nn.Module):
    """En-Transformer-style layer: attention-weighted invariant messages +
    equivariant coordinate update (attention replaces EGNN's sum pooling;
    reference capability via the `En-transformer` dependency,
    setup.py:19-34)."""

    dim: int
    heads: int = 4
    dim_head: int = 32
    edge_dim: int = 0
    coor_clamp: float = 3.0

    @nn.compact
    def __call__(self, h, x, edges=None, mask=None):
        b, n, d = h.shape
        hd, nh = self.dim_head, self.heads
        inner = hd * nh

        hn = LayerNorm(name="norm")(h)
        q = Dense(inner, use_bias=False, param_dtype=jnp.float32,
                  name="to_q")(hn).reshape(b, n, nh, hd)
        k = Dense(inner, use_bias=False, param_dtype=jnp.float32,
                  name="to_k")(hn).reshape(b, n, nh, hd)
        v = Dense(inner, use_bias=False, param_dtype=jnp.float32,
                  name="to_v")(hn).reshape(b, n, nh, hd)

        rel = x[:, :, None, :] - x[:, None, :, :]
        dist2 = _safe_norm2(rel)

        logits = jnp.einsum("bihd,bjhd->bhij", q, k) * (hd ** -0.5)
        # distance-aware bias (+ optional pair-rep edge bias)
        dist_bias = Dense(nh, param_dtype=jnp.float32,
                          name="dist_to_bias")(jnp.log(dist2))
        logits = logits + dist_bias.transpose(0, 3, 1, 2)
        if edges is not None:
            logits = logits + Dense(
                nh, use_bias=False, param_dtype=jnp.float32,
                name="edge_to_bias")(edges).transpose(0, 3, 1, 2)

        if mask is not None:
            pair_mask = mask[:, None, :, None] & mask[:, None, None, :]
            logits = jnp.where(pair_mask, logits, -1e9)

        attn = jax.nn.softmax(logits, axis=-1)              # (b, h, i, j)

        out = jnp.einsum("bhij,bjhd->bihd", attn, v).reshape(b, n, inner)
        h = h + Dense(self.dim, param_dtype=jnp.float32,
                      kernel_init=zeros_init(), bias_init=zeros_init(),
                      name="to_out")(out)

        # equivariant coordinate update weighted by mean attention
        coor_w = Dense(1, use_bias=False, param_dtype=jnp.float32,
                       kernel_init=zeros_init(), name="coor_mlp")(
                              attn.mean(1)[..., None])
        coor_w = jnp.tanh(coor_w) * self.coor_clamp
        x = x + (rel / jnp.sqrt(dist2) * coor_w).sum(axis=2) / max(n - 1, 1)
        return h, x


class Refiner(nn.Module):
    """Iterative equivariant refinement head (README-era
    `structure_module_type` + `refinement_iters`). Weight-shared layer
    applied `iters` times, mirroring the reference's refinement loop."""

    dim: int
    kind: str = "egnn"        # 'egnn' | 'en' | 'se3'
    iters: int = 4
    edge_dim: int = 0
    heads: int = 4

    @nn.compact
    def __call__(self, h, x, edges=None, mask=None):
        if self.kind == "egnn":
            layer = EGNNLayer(dim=self.dim, edge_dim=self.edge_dim,
                              name="layer")
        elif self.kind in ("en", "se3"):
            # 'se3' maps onto the vector-equivariant attention layer: on
            # point clouds with scalar features, SE(3) equivariance is
            # exactly E(3) equivariance of this update
            layer = EnAttentionLayer(dim=self.dim, heads=self.heads,
                                     edge_dim=self.edge_dim, name="layer")
        else:
            raise ValueError(f"unknown refiner kind {self.kind!r}")

        for _ in range(self.iters):
            h, x = layer(h, x, edges=edges, mask=mask)
        return h, x
