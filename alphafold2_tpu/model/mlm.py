"""MSA masked-language-model self-supervision.

Parity with the reference (/root/reference/alphafold2_pytorch/mlm.py:11-92):
proportional subset masking per MSA row, mask/keep/random-replace split with
excluded token ids, CE loss over replaced positions only.

JAX differences: noising takes an explicit PRNG key (the reference uses
global torch RNG), and the loss uses a masked mean instead of boolean
indexing (`logits[mask]`, mlm.py:88) so shapes stay static for XLA.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import linen as nn

from alphafold2_tpu import constants
from alphafold2_tpu.model.primitives import Dense


def get_mask_subset_with_prob(rng, mask: jnp.ndarray, prob: float) -> jnp.ndarray:
    """Sample ~prob fraction of True positions per row (reference
    mlm.py:11-25). mask: (b, n) bool -> (b, n) bool subset.

    Picks top-`ceil(prob*n)` random valid positions, then trims rows with
    fewer valid tokens so each row gets ~prob * (its valid count).
    """
    batch, seq_len = mask.shape
    max_masked = math.ceil(prob * seq_len)

    num_tokens = mask.sum(axis=-1, keepdims=True)
    mask_excess = jnp.cumsum(mask, axis=-1) > jnp.ceil(num_tokens * prob)
    mask_excess = mask_excess[:, :max_masked]

    rand = jnp.where(mask, jax.random.uniform(rng, mask.shape), -1e9)
    _, sampled = jax.lax.top_k(rand, max_masked)
    sampled = jnp.where(mask_excess, 0, sampled + 1)

    new_mask = jnp.zeros((batch, seq_len + 1), dtype=bool)
    new_mask = new_mask.at[jnp.arange(batch)[:, None], sampled].set(True)
    return new_mask[:, 1:]


class MLM(nn.Module):
    """MSA-MLM head + noising (reference mlm.py:27-92)."""

    dim: int
    num_tokens: int
    mask_id: int
    mask_prob: float = 0.15
    random_replace_token_prob: float = 0.1
    keep_token_same_prob: float = 0.1
    exclude_token_ids: tuple = (0,)

    def noise(self, rng, seq: jnp.ndarray, mask: jnp.ndarray):
        """BERT-style noising. seq: (b, m, n) int tokens; mask: (b, m, n).
        Returns (noised_seq, replaced_mask) both (b, m, n)."""
        b, num_msa, n = seq.shape
        seq_f = seq.reshape(b * num_msa, n)
        mask_f = mask.reshape(b * num_msa, n)

        excluded = mask_f
        for token_id in self.exclude_token_ids:
            excluded = excluded & (seq_f != token_id)

        k_subset, k_rand_subset, k_tokens = jax.random.split(rng, 3)
        mlm_mask = get_mask_subset_with_prob(k_subset, excluded, self.mask_prob)

        noised = jnp.where(mlm_mask, self.mask_id, seq_f)

        random_replace_mask = get_mask_subset_with_prob(
            k_rand_subset, mlm_mask,
            (1.0 - self.keep_token_same_prob) * self.random_replace_token_prob)
        random_tokens = jax.random.randint(
            k_tokens, seq_f.shape, 1, constants.NUM_AMINO_ACIDS)
        for token_id in self.exclude_token_ids:
            random_replace_mask = random_replace_mask & \
                (random_tokens != token_id)

        noised = jnp.where(random_replace_mask, random_tokens, noised)
        return noised.reshape(b, num_msa, n), mlm_mask.reshape(b, num_msa, n)

    @nn.compact
    def __call__(self, seq_embed, original_seq, replaced_mask):
        """CE loss over replaced positions (reference mlm.py:86-92).
        seq_embed: (b, m, n, d); original_seq/replaced_mask: (b, m, n)."""
        logits = Dense(self.num_tokens, param_dtype=jnp.float32,
                       name="to_logits")(seq_embed.astype(jnp.float32))
        logp = jax.nn.log_softmax(logits, axis=-1)
        labels = jax.nn.one_hot(original_seq, self.num_tokens,
                                dtype=logp.dtype)
        ce = -(labels * logp).sum(-1)
        m = replaced_mask.astype(logp.dtype)
        return (ce * m).sum() / jnp.maximum(m.sum(), 1.0)
