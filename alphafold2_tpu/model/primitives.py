"""Evoformer building blocks, flax.linen, TPU-first.

Behavioral parity with the reference blocks
(/root/reference/alphafold2_pytorch/alphafold2.py:69-351):
- `FeedForward`: pre-LN -> Linear(dim -> 2*mult*dim) -> GEGLU -> Linear,
  output zero-initialized (alphafold2.py:74-94);
- `Attention`: QKV attention with sigmoid output gating computed from the
  *input* (gate Linear init weight=0 bias=1 so it starts as pass-through),
  optional additive attention bias, optional `tie_dim` row-tied/global-query
  attention (MSAColumnGlobalAttention), mask fill with -max
  (alphafold2.py:98-190);
- `AxialAttention`: attention over rows/cols of a 2-D feature map by folding
  the off-axis into batch, with optional pair-edge -> per-head bias
  (alphafold2.py:192-255);
- `TriangleMultiplicativeModule`: outgoing/ingoing triangle multiplicative
  update with identity-initialized gates (alphafold2.py:257-317);
- `OuterMean`: MSA -> pair outer-product mean (alphafold2.py:321-351).

TPU notes: weights live in fp32; activations run in `dtype` (bf16 by default
under the train policy) so matmuls hit the MXU at full rate. Folding an axis
into batch is a free reshape under XLA. Attention here is plain einsum +
softmax — XLA fuses bias/mask/softmax; a Pallas fused variant can be swapped
in via `alphafold2_tpu.ops` once it beats the XLA baseline.
"""

from __future__ import annotations

import contextlib
import warnings

from typing import Optional

import jax.numpy as jnp
from flax import linen as nn
from jax import nn as jnn


def Dense(features, **kw):
    """`nn.Dense` whose contraction may route to the AMX host GEMM.

    Identical to `flax.linen.Dense` (same params tree — flax names the
    returned module by its class, `Dense_N`) except the contraction goes
    through `ops.cpu_gemm.amx_dense_dot_general`, which dispatches eligible
    f32 GEMMs to the native AMX kernel on the XLA:CPU fallback path and is
    `lax.dot_general` bit-for-bit everywhere else (TPU path unchanged).
    """
    if "dot_general" not in kw:
        from alphafold2_tpu.ops.cpu_gemm import amx_dense_dot_general
        kw["dot_general"] = amx_dense_dot_general
    return nn.Dense(features, **kw)

# Large-negative fill for masked logits; -finfo.max in the reference
# (alphafold2.py:165). A fixed large constant is safer in bf16.
MASK_VALUE = -1e9


def zeros_init():
    return nn.initializers.zeros_init()


def ones_init():
    return nn.initializers.ones_init()


class LayerNorm(nn.Module):
    """LayerNorm with torch-style epsilon, fp32 statistics."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                            param_dtype=jnp.float32)(x)


class GEGLU(nn.Module):
    """x, gates = split(x); x * gelu(gates) (reference alphafold2.py:69-72)."""

    @nn.compact
    def __call__(self, x):
        x, gates = jnp.split(x, 2, axis=-1)
        return x * jnn.gelu(gates)


class FeedForward(nn.Module):
    """Transition block (reference alphafold2.py:74-94)."""

    dim: int
    mult: int = 4
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = LayerNorm(dtype=self.dtype)(x)
        x = Dense(self.dim * self.mult * 2, dtype=self.dtype,
                  param_dtype=jnp.float32)(x)
        x = GEGLU()(x)
        x = nn.Dropout(self.dropout, deterministic=deterministic)(x)
        # zero-initialized output projection: the block starts as identity
        # w.r.t. the residual stream (reference init_zero_, alphafold2.py:90)
        x = Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                  kernel_init=zeros_init(), bias_init=zeros_init())(x)
        return x


def attention_output_tail(dense, out, x, inner, gating, dim):
    """Shared attention tail (used by Attention and the efficient
    variants): merge heads, sigmoid gate from the input (init
    pass-through, reference alphafold2.py:118-120), zero-init output
    projection (alphafold2.py:123). out: (b, h, n, dh)."""
    out = out.swapaxes(-2, -3).reshape(*x.shape[:-1], inner)
    if gating:
        gates = dense(inner, "gating", kernel_init=zeros_init(),
                      bias_init=ones_init())(x)
        out = out * jnn.sigmoid(gates)
    return dense(dim, "to_out", kernel_init=zeros_init(),
                 bias_init=zeros_init())(out)


class Attention(nn.Module):
    """Gated multi-head attention (reference alphafold2.py:98-190).

    setup-based (not @nn.compact) so `project_qkv` / `finish` are callable
    from a parent module as well as from `__call__` — the ring-attention
    path in AxialAttention reuses exactly these projections, keeping one
    params tree for the dense and ring backends.
    """

    dim: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    gating: bool = True
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        inner = self.heads * self.dim_head
        dense = lambda features, name, use_bias=True, **kw: Dense(
            features, use_bias=use_bias, dtype=self.dtype,
            param_dtype=jnp.float32, name=name, **kw)
        self._to_q = dense(inner, "to_q", use_bias=False)
        self._to_kv = dense(inner * 2, "to_kv", use_bias=False)
        if self.gating:
            self._gating = dense(inner, "gating", kernel_init=zeros_init(),
                                 bias_init=ones_init())
        self._to_out = dense(self.dim, "to_out", kernel_init=zeros_init(),
                             bias_init=zeros_init())
        self._drop = nn.Dropout(self.dropout)

    def project_qkv(self, x, kv_input=None):
        """QKV projections with heads split out and q pre-scaled.

        x: (..., n, d) -> q/k/v (..., h, n, dh). Rank-agnostic: the ring
        path passes the unfolded (b, I, J, d) pair tensor.
        """
        h, dh = self.heads, self.dim_head
        kv_input = x if kv_input is None else kv_input
        q = self._to_q(x)
        k, v = jnp.split(self._to_kv(kv_input), 2, axis=-1)

        def split_heads(t):
            t = t.reshape(*t.shape[:-1], h, dh)
            return jnp.moveaxis(t, -2, 1)  # heads to axis 1

        q, k, v = map(split_heads, (q, k, v))
        return q * (dh ** -0.5), k, v

    def finish(self, out, x):
        """Shared output tail: merge heads, sigmoid gate from the *input*
        (init pass-through, reference alphafold2.py:118-120), zero-init
        output projection. out: heads at axis 1 (project_qkv's layout),
        i.e. (b, h, ..., n, dh); x: the attention input."""
        out = jnp.moveaxis(out, 1, -2).reshape(
            *x.shape[:-1], self.heads * self.dim_head)
        return self._gate_and_project(out, x)

    def _gate_and_project(self, out_merged, x):
        """The tail after head merge — ONE owner for the gating semantics
        so the XLA/Pallas/ring paths (via finish) and the token-major AMX
        path cannot diverge."""
        if self.gating:
            out_merged = out_merged * jnn.sigmoid(self._gating(x))
        return self._to_out(out_merged)

    def __call__(
        self,
        x,                       # (b, n, d)
        mask=None,               # (b, n) bool
        attn_bias=None,          # (b // attn_bias_repeat, heads, n, m)
        context=None,            # (b, m, d)
        context_mask=None,       # (b, m) bool
        tie_dim: Optional[int] = None,
        attn_bias_repeat: int = 1,
        deterministic: bool = True,
    ):
        h, dh = self.heads, self.dim_head
        has_context = context is not None

        q, k, v = self.project_qkv(x, kv_input=context)  # (b, h, n, dh)

        if mask is not None:
            if has_context:
                cmask = context_mask if context_mask is not None else \
                    jnp.ones(k.shape[:1] + k.shape[-2:-1], dtype=bool)
            else:
                cmask = mask
        else:
            cmask = None

        # serving-side kernel selection (ISSUE 12): a trace-time
        # KernelSpec (ops/block_sparse.kernel_context — the executor
        # activates it through predict.fold(kernel=)) reroutes matching
        # SELF-attention (attended-axis length == spec.n, no context,
        # no tie_dim) onto the true block-skipping Pallas kernel, pair
        # bias and key masks riding along unrepeated; its masked-dense
        # backend applies the same pattern as an additive bias instead
        # (identical support, no FLOP skip — the CPU fallback and the
        # numerics reference). Params are untouched either way: the
        # kernel choice lives in which executable gets compiled.
        from alphafold2_tpu.ops.block_sparse import active_kernel_spec
        kspec = active_kernel_spec()
        n_q, n_k = q.shape[-2], k.shape[-2]
        if kspec is not None and (has_context or tie_dim is not None
                                  or n_q != n_k
                                  or not kspec.covers(n_q)):
            kspec = None
        sparse_backend = None
        if kspec is not None:
            sparse_backend = kspec.resolve_backend()
            if sparse_backend == "pallas" and self.dropout > 0.0 \
                    and not deterministic:
                # the block-skipping kernel has no dropout; a training
                # trace keeps the pattern via the masked-dense path
                # (same refuse-don't-drop convention as the fused
                # kernel below)
                sparse_backend = "masked"
        if sparse_backend == "pallas":
            from alphafold2_tpu.ops.block_sparse import \
                block_sparse_attention
            b_all = q.shape[0]
            bias_arg = None
            if attn_bias is not None:
                bias_arg = jnp.broadcast_to(
                    attn_bias.astype(jnp.float32),
                    (b_all // attn_bias_repeat, h, n_q, n_k)
                ).reshape(-1, n_q, n_k)
            out = block_sparse_attention(
                q.reshape(b_all * h, n_q, dh),
                k.reshape(b_all * h, n_k, dh),
                v.reshape(b_all * h, n_k, dh),
                kspec.pattern_array(),
                bias=bias_arg, bias_repeat=attn_bias_repeat,
                k_mask=cmask, heads=h,
                scale=1.0,                # project_qkv pre-scales q
                block=kspec.block,
                interpret=kspec.interpret())
            return self.finish(out.reshape(b_all, h, n_q, dh), x)
        if sparse_backend == "masked":
            # the pattern as a broadcastable additive bias: both the
            # fused-Pallas and XLA dense paths below honor attn_bias,
            # so the masked backend needs no further branching
            fill = jnp.where(jnp.asarray(kspec.token_mask()), 0.0,
                             MASK_VALUE).astype(jnp.float32)[None, None]
            attn_bias = fill if attn_bias is None else \
                attn_bias + fill.astype(attn_bias.dtype)

        # optional Pallas fused path (bias+mask+softmax+AV in one
        # VMEM-resident kernel; alphafold2_tpu/ops/attention.py). Bias
        # stays *unrepeated* (replayed over the folded axial axis by the
        # kernel's index map) and masks stay (b, n) vectors — no O(N^2)
        # HBM bias/mask tensor is ever built on this path. Tie-dim
        # (global-query) and dropout-active traces fall back to the XLA
        # path. Both backends share the gating/projection tail below.
        from alphafold2_tpu.ops.attention import (
            fused_attention, pallas_attention_enabled)
        use_pallas = pallas_attention_enabled() and tie_dim is None
        if use_pallas and self.dropout > 0.0 and not deterministic:
            # refuse-don't-drop convention (evoformer.py menu): the fused
            # kernel has no dropout; say so instead of silently slowing
            warnings.warn(
                "Pallas fused attention is enabled but attention dropout "
                f"({self.dropout}) is active in a training trace; this "
                "layer falls back to the XLA attention path. Set "
                "attn_dropout=0.0 or run deterministic to keep the "
                "kernel.", stacklevel=2)
            use_pallas = False
        if use_pallas:
            b_all = q.shape[0]
            n_q, n_k = q.shape[-2], k.shape[-2]
            if attn_bias is not None:
                # callers may pass broadcast-shaped bias, e.g. (1,1,n,n)
                # from BlockSparseAttention; the kernel's index map needs
                # the full (b, heads) leading shape
                attn_bias = jnp.broadcast_to(
                    attn_bias.astype(jnp.float32),
                    (b_all // attn_bias_repeat, h, n_q, n_k))
            out = fused_attention(
                q.reshape(b_all * h, n_q, dh),
                k.reshape(b_all * h, n_k, dh),
                v.reshape(b_all * h, n_k, dh),
                bias=None if attn_bias is None else
                attn_bias.reshape(-1, n_q, n_k),
                q_mask=mask,
                k_mask=cmask,
                heads=h,
                bias_repeat=attn_bias_repeat)
            return self.finish(out.reshape(b_all, h, n_q, dh), x)

        pair_mask = None if mask is None else \
            mask[:, None, :, None] & cmask[:, None, None, :]

        if attn_bias is not None and attn_bias_repeat != 1:
            # replay the (b, h, n, m) bias across the folded axial axis
            # (reference alphafold2.py:246-248); only the XLA path needs
            # the materialized repeat
            attn_bias = jnp.repeat(attn_bias, attn_bias_repeat, axis=0)

        # the attention contractions route to the AMX host GEMM on the CPU
        # fallback path (ops/cpu_gemm.py; exact XLA einsums otherwise).
        # When eligible, the NATURAL-layout ops consume q/k/v with heads
        # minor to tokens ([b, n, h, dh], as the projections produce them
        # modulo one cancelled moveaxis round-trip) and emit the output
        # token-major — no [b,n,h,d]<->[b,h,n,d] transposes materialize
        # around the custom calls (XLA folds the two inverse moveaxes
        # away; an FFI boundary, unlike XLA's own dot, cannot absorb a
        # layout change).
        from alphafold2_tpu.ops.cpu_gemm import (amx_attention_dots,
                                                 amx_attention_natural_ok,
                                                 amx_attention_out,
                                                 amx_attn_av, amx_attn_qk)

        if tie_dim is not None:
            # global-query attention: average queries across the tied rows
            # (the paper's MSAColumnGlobalAttention; reference
            # alphafold2.py:142-151)
            b = q.shape[0] // tie_dim
            q = q.reshape(b, tie_dim, *q.shape[1:]).mean(axis=1)
            k = k.reshape(b, tie_dim, *k.shape[1:])
            dots = jnp.einsum("bhid,brhjd->brhij", q, k)
            dots = dots.reshape(-1, *dots.shape[2:])
            natural = False
        else:
            q_n, k_n, v_n = (jnp.moveaxis(t, 1, -2) for t in (q, k, v))
            natural = amx_attention_natural_ok(q_n, k_n)
            dots = amx_attn_qk(q_n, k_n) if natural \
                else amx_attention_dots(q, k)

        if attn_bias is not None:
            dots = dots + attn_bias.astype(dots.dtype)

        if pair_mask is not None:
            dots = jnp.where(pair_mask, dots, MASK_VALUE)

        attn = jnn.softmax(dots, axis=-1)
        attn = self._drop(attn, deterministic=deterministic)

        if natural:
            out = amx_attn_av(attn, v_n)          # (b, n, h, dh)
            return self._gate_and_project(
                out.reshape(*x.shape[:-1], h * dh), x)
        out = amx_attention_out(attn, v)
        return self.finish(out, x)


class AxialAttention(nn.Module):
    """Row/column attention over a 2-D map (reference alphafold2.py:192-255).

    Input x: (b, H, W, d). `row_attn` attends along W for each of the H rows;
    `col_attn` attends along H for each of the W columns. Exactly one of the
    two must be set. `accept_edges` projects a pair representation
    (b, I, J, d) into per-head attention bias.

    Long-context mode: when `ring_axes=(axis_H, axis_W)` names the mesh
    axes sharding x's two spatial dims and the attended axis is actually
    sharded (>1 devices) under the active mesh, the attention dispatches
    to `parallel.ring.pair_row_attention_sharded` — exact blockwise
    softmax with K/V shards rotating around the mesh ring over ICI —
    instead of letting GSPMD all-gather the full attended axis
    (SURVEY.md §5.7 hard-part #1). Same params either way (the ring path
    reuses the inner Attention's projections), so the flag is purely an
    execution-strategy switch. Falls back to the dense path only for
    global-query (tie_dim) attention; training-time attention dropout
    runs inside the ring (per-device/key-shard fold_in masks, see
    parallel/ring.py) rather than disabling it.
    """

    dim: int
    heads: int
    dim_head: int = 64
    row_attn: bool = True
    col_attn: bool = False
    accept_edges: bool = False
    global_query_attn: bool = False
    dropout: float = 0.0
    ring_axes: Optional[tuple] = None   # (mesh axis of H, mesh axis of W)
    # serving kernel selection (ISSUE 12): False suppresses any active
    # ops.block_sparse KernelSpec for this attention — set on tracks
    # whose attended axis is NOT the residue axis (the MSA column
    # attention attends alignment rows; a residue-length pattern
    # matching its length by coincidence would restrict the wrong
    # axis). Params are unaffected (non-init field).
    sparse_kernel_ok: bool = True
    dtype: jnp.dtype = jnp.float32

    def _ring_mesh(self, height, width):
        """The active mesh if the ring path applies, else None.

        A ring_axes entry may be None, meaning that spatial dim is not
        mesh-sharded (the MSA track: alignment rows are local, only the
        attended residue axis rides the mesh)."""
        from alphafold2_tpu.parallel.sharding import active_mesh

        if self.ring_axes is None or self.global_query_attn:
            return None
        mesh = active_mesh()
        if mesh is None:
            return None
        ax_h, ax_w = self.ring_axes
        ax_att = ax_w if self.row_attn else ax_h
        if ax_att is None or ax_att not in mesh.axis_names:
            return None
        if mesh.shape[ax_att] <= 1:
            return None
        # each sharded spatial dim must tile over its mesh axis
        for dim, ax in ((height, ax_h), (width, ax_w)):
            if ax is not None and ax in mesh.axis_names and \
                    dim % mesh.shape[ax]:
                return None
        return mesh

    def _ring_forward(self, x, edges, mask, mesh, dropout_key=None):
        """Ring-parallel axial attention over the sharded attended axis.

        Reuses the inner Attention's projections/tail so the params tree
        is identical to the dense path; outputs match the dense path at
        all valid (unmasked-query) positions — masked-query cells carry
        unspecified values on both paths (dense: uniform average; ring:
        average over valid keys).

        Mask contract: EXACT. The full (b, H, W) mask rides into the ring
        as per-row key validity — within row i, key j is valid iff
        mask[b, i, j] — matching the dense path's key-side masking for
        arbitrary (including non-separable) masks. (Round-2 VERDICT weak
        #5: an earlier version relaxed the mask to per-axis `any()`
        vectors; no longer.)
        """
        from alphafold2_tpu.parallel.ring import pair_row_attention_sharded

        attn = Attention(
            dim=self.dim, heads=self.heads, dim_head=self.dim_head,
            dropout=self.dropout, dtype=self.dtype, name="attn")
        q, k, v = attn.project_qkv(x)  # (b, h, H, W, dh), q pre-scaled

        bias = None
        if self.accept_edges and edges is not None:
            bias = Dense(self.heads, use_bias=False, dtype=self.dtype,
                         param_dtype=jnp.float32,
                         name="edges_to_attn_bias")(edges)
            bias = bias.transpose(0, 3, 1, 2)  # (b, heads, i, j)

        drop = dict(dropout_rate=self.dropout if dropout_key is not None
                    else 0.0, dropout_key=dropout_key)
        ax_h, ax_w = self.ring_axes
        if self.row_attn:
            out = pair_row_attention_sharded(
                q, k, v, bias, mesh, i_axis=ax_h, j_axis=ax_w,
                mask=mask, **drop)

        else:
            swap = lambda t: t.swapaxes(2, 3)  # (b, h, W, H, dh)
            out = pair_row_attention_sharded(
                swap(q), swap(k), swap(v), bias, mesh,
                i_axis=ax_w, j_axis=ax_h,
                mask=None if mask is None else mask.swapaxes(1, 2), **drop)
            out = out.swapaxes(2, 3)

        return attn.finish(out, x)

    @nn.compact
    def __call__(self, x, edges=None, mask=None, deterministic: bool = True):
        assert self.row_attn ^ self.col_attn, \
            "has to be either row or column attention, not both"

        b, height, width, d = x.shape
        x = LayerNorm(dtype=self.dtype)(x)

        # the ring path stays active under training-time dropout (round-4
        # VERDICT #5 — it used to silently de-ring): the mask is drawn
        # inside the ring from per-(device, key-shard) fold_in keys
        ring_mesh = self._ring_mesh(height, width)
        if ring_mesh is not None:
            drop_key = None
            if self.dropout > 0.0 and not deterministic:
                drop_key = self.make_rng("dropout")
            return self._ring_forward(x, edges, mask, ring_mesh, drop_key)

        if self.col_attn:
            axial_dim = width
            x_fold = x.swapaxes(1, 2).reshape(b * width, height, d)
            mask_fold = None if mask is None else \
                mask.swapaxes(1, 2).reshape(b * width, height)
        else:
            axial_dim = height
            x_fold = x.reshape(b * height, width, d)
            mask_fold = None if mask is None else mask.reshape(b * height, width)

        attn_bias = None
        if self.accept_edges and edges is not None:
            # (b, i, j, d) -> per-head bias (b, heads, i, j), tiled over the
            # folded axis (reference alphafold2.py:214-217, :246-248)
            bias = Dense(self.heads, use_bias=False, dtype=self.dtype,
                         param_dtype=jnp.float32,
                         name="edges_to_attn_bias")(edges)
            attn_bias = bias.transpose(0, 3, 1, 2)  # (b, heads, i, j)

        tie_dim = axial_dim if self.global_query_attn else None

        from alphafold2_tpu.ops.block_sparse import (active_kernel_spec,
                                                     kernel_context)
        ctx = kernel_context(None) if (not self.sparse_kernel_ok
                                       and active_kernel_spec()
                                       is not None) \
            else contextlib.nullcontext()
        with ctx:
            out = Attention(
                dim=self.dim, heads=self.heads, dim_head=self.dim_head,
                dropout=self.dropout, dtype=self.dtype, name="attn",
            )(x_fold, mask=mask_fold, attn_bias=attn_bias,
              tie_dim=tie_dim,
              attn_bias_repeat=axial_dim if attn_bias is not None else 1,
              deterministic=deterministic)

        if self.col_attn:
            out = out.reshape(b, width, height, d).swapaxes(1, 2)
        else:
            out = out.reshape(b, height, width, d)
        return out


class TriangleMultiplicativeModule(nn.Module):
    """Triangle multiplicative update (reference alphafold2.py:257-317).

    mix='outgoing': out[i,j] = sum_k left[i,k] * right[j,k]
    mix='ingoing' : out[i,j] = sum_k left[k,j] * right[k,i]
    The O(L^3 d) contraction is a batched matmul -> lands on the MXU.
    """

    dim: int
    hidden_dim: Optional[int] = None
    mix: str = "ingoing"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None):
        assert self.mix in ("ingoing", "outgoing")
        assert x.shape[1] == x.shape[2], "feature map must be square"
        hidden = self.hidden_dim or self.dim

        dense = lambda features, name, **kw: Dense(
            features, dtype=self.dtype, param_dtype=jnp.float32,
            name=name, **kw)

        if mask is not None:
            mask = mask[..., None].astype(x.dtype)

        x = LayerNorm(dtype=self.dtype)(x)

        left = dense(hidden, "left_proj")(x)
        right = dense(hidden, "right_proj")(x)

        if mask is not None:
            left = left * mask
            right = right * mask

        # gates initialized to identity (reference alphafold2.py:280-282)
        gate = lambda name: jnn.sigmoid(
            dense(hidden, name, kernel_init=zeros_init(),
                  bias_init=ones_init())(x))
        left = left * gate("left_gate")
        right = right * gate("right_gate")
        out_gate = gate("out_gate")

        if self.mix == "outgoing":
            out = jnp.einsum("bikd,bjkd->bijd", left, right)
        else:
            out = jnp.einsum("bkjd,bkid->bijd", left, right)

        out = LayerNorm(dtype=self.dtype)(out)
        out = out * out_gate
        return dense(self.dim, "to_out")(out)


class OuterMean(nn.Module):
    """MSA -> pair communication via outer-product mean
    (reference alphafold2.py:321-351).

    Note: the reference's masked branch divides by the row count twice
    (`.mean(dim=1) / (mask.sum(dim=1)+eps)`, alphafold2.py:347); we use the
    standard masked mean (sum / count) — the trailing projection absorbs the
    scale and this behaves correctly for ragged MSAs. Set
    `reference_scale=True` to reproduce the reference's double-division
    exactly — required when running checkpoints trained with the reference
    (the reference synthesizes an all-ones msa_mask at alphafold2.py:703,
    so its masked branch is effectively always active).
    """

    dim: int
    hidden_dim: Optional[int] = None
    eps: float = 1e-5
    reference_scale: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None):
        hidden = self.hidden_dim or self.dim
        x = LayerNorm(dtype=self.dtype)(x)
        left = Dense(hidden, dtype=self.dtype, param_dtype=jnp.float32,
                     name="left_proj")(x)
        right = Dense(hidden, dtype=self.dtype, param_dtype=jnp.float32,
                      name="right_proj")(x)

        if mask is not None:
            m = mask.astype(x.dtype)  # (b, m, n)
            left = left * m[..., None]
            right = right * m[..., None]
            # einsum over the MSA-row axis: (b,m,i,d),(b,m,j,d)->(b,i,j,d)
            outer = jnp.einsum("bmid,bmjd->bijd", left, right)
            counts = jnp.einsum("bmi,bmj->bij", m, m)[..., None]
            if self.reference_scale:
                # reference alphafold2.py:347: .mean(dim=1) then /(count+eps)
                outer = outer / x.shape[1] / (counts + self.eps)
            else:
                outer = outer / (counts + self.eps)
        else:
            outer = jnp.einsum("bmid,bmjd->bijd", left, right)
            outer = outer / x.shape[1]

        return Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="proj_out")(outer)
