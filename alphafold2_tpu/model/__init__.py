from alphafold2_tpu.model.alphafold2 import (  # noqa: F401
    Alphafold2,
    Recyclables,
    ReturnValues,
)
from alphafold2_tpu.model.evoformer import (  # noqa: F401
    Evoformer,
    EvoformerBlock,
    MsaAttentionBlock,
    PairwiseAttentionBlock,
)
from alphafold2_tpu.model.attention_variants import (  # noqa: F401
    BlockSparseAttention,
    KroneckerAttention,
    LinearAttention,
    MemoryCompressedAttention,
    MultiKernelConvBlock,
)
from alphafold2_tpu.model.mlm import MLM  # noqa: F401
from alphafold2_tpu.model.refiners import EGNNLayer, EnAttentionLayer, Refiner  # noqa: F401
from alphafold2_tpu.model.reversible import ReversibleEvoformer  # noqa: F401
from alphafold2_tpu.model.primitives import (  # noqa: F401
    Attention,
    AxialAttention,
    FeedForward,
    OuterMean,
    TriangleMultiplicativeModule,
)
from alphafold2_tpu.model.structure import (  # noqa: F401
    InvariantPointAttention,
    IPABlock,
    StructureModule,
)
