"""One typed configuration tree for model, data, mesh, and training.

The reference spreads configuration over three uncoordinated mechanisms
(SURVEY.md §5.6: constructor kwargs, script-level module constants, argparse
in one DataModule). Here a single dataclass tree drives everything;
`Experiment.build()` materializes the model, optimizer, mesh, and train
step from it.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax.numpy as jnp


@dataclass
class ModelConfig:
    dim: int = 256
    depth: int = 6
    heads: int = 8
    dim_head: int = 64
    max_rel_dist: int = 32
    predict_angles: bool = False
    symmetrize_omega: bool = False
    predict_coords: bool = False
    structure_module_depth: int = 4
    structure_module_heads: int = 1
    structure_module_type: str = "ipa"
    structure_module_refinement_iters: int = 0
    structure_module_refinement: str = "residue"   # 'residue' | 'egnn-atom'
    reversible: bool = False
    ring_attention: bool = False
    pipeline_stages: int = 1          # GPipe trunk stages (mesh pipe axis)
    pipeline_microbatches: int = 0
    use_conv: bool = False            # trRosetta2-style trunk conv blocks
    # README-era efficient-attention menu for the MSA row track: bools
    # (all layers) or per-layer lists, e.g. sparse_self_attn =
    # [true, false, true, false] interleaves sparse and full layers
    # (reference README.md:388-487; Evoformer documents semantics).
    # kv_compress_ratio: 0 = off.
    sparse_self_attn: Any = False
    linear_attn: Any = False
    kron_attn: Any = False
    kv_compress_ratio: Any = 0
    linear_attn_kind: str = "favor"   # "favor" (Performer) | "elu"
    performer_nb_features: int = 256
    sparse_block: int = 32
    sparse_num_global: int = 1
    sparse_window: int = 1
    extra_msa_evoformer_layers: int = 4
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    bfloat16: bool = True

    def build(self):
        from alphafold2_tpu import Alphafold2
        kwargs = dataclasses.asdict(self)
        use_bf16 = kwargs.pop("bfloat16")
        return Alphafold2(
            **kwargs, dtype=jnp.bfloat16 if use_bf16 else jnp.float32)

    def sparse_kwargs(self) -> Dict[str, int]:
        """The one set of block-sparsity knobs, shared by the model-level
        `sparse_self_attn` menu and the SERVING kernel policy
        (`serve.KernelPolicy.from_model_config`, ISSUE 12): one source
        so the pattern a model trains/evaluates under and the pattern
        the serving executor routes long folds onto cannot drift."""
        return {"block": self.sparse_block,
                "num_global": self.sparse_num_global,
                "window": self.sparse_window}


def draft_preset(base: ModelConfig) -> ModelConfig:
    """The draft-tier config derived from a flagship config (ISSUE 19).

    HelixFold-style tiered efficiency: half the width, a third of the
    depth (floored at 1) — the quadratic-in-dim trunk cost drops
    roughly an order of magnitude while the architecture, attention
    menu, and structure module stay the flagship's, so every serving
    path (bucketing, kernel policy, mesh planning) works on the draft
    unchanged. Deriving instead of hardcoding keeps the pair coupled:
    a flagship config change cannot strand a stale draft preset.

    The returned config is a DIFFERENT model with different params —
    the cascade keys its cache entries apart by model_tag, never by
    config digest, so the tag discipline (serve.cascade) still applies.
    """
    return dataclasses.replace(
        base,
        dim=max(base.dim // 2, 1),
        depth=max(base.depth // 3, 1),
        structure_module_depth=max(base.structure_module_depth // 2, 1),
    )


@dataclass
class DataConfig:
    crop_len: int = 128
    msa_depth: int = 5
    batch_size: int = 1
    root: Optional[str] = None        # trrosetta-style data dir; None=synthetic


@dataclass
class MeshConfig:
    pipe: int = 1
    data: int = 1
    i: int = 1
    j: int = 1

    def build(self):
        from alphafold2_tpu.parallel import make_mesh
        if self.pipe * self.data * self.i * self.j == 1:
            return None
        return make_mesh(self.data, self.i, self.j, pipe=self.pipe)


@dataclass
class TrainConfig:
    learning_rate: float = 3e-4
    grad_accum_every: int = 16        # reference train_pre.py:16
    max_grad_norm: Optional[float] = None
    # warmup+cosine schedule (0 / None = the reference's constant LR)
    warmup_steps: int = 0
    decay_steps: Optional[int] = None
    num_steps: int = 1000
    log_every: int = 10
    checkpoint_dir: Optional[str] = None
    seed: int = 0


@dataclass
class Experiment:
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    train: TrainConfig = field(default_factory=TrainConfig)

    # --- serialization -----------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Experiment":
        return cls(
            model=ModelConfig(**d.get("model", {})),
            data=DataConfig(**d.get("data", {})),
            mesh=MeshConfig(**d.get("mesh", {})),
            train=TrainConfig(**d.get("train", {})),
        )

    @classmethod
    def from_json(cls, text: str) -> "Experiment":
        return cls.from_dict(json.loads(text))

    # --- materialization ---------------------------------------------------

    def build(self):
        """Returns (model, tx, mesh)."""
        from alphafold2_tpu.train import adam
        model = self.model.build()
        tx = adam(self.train.learning_rate, self.train.grad_accum_every,
                  self.train.max_grad_norm,
                  warmup_steps=self.train.warmup_steps,
                  decay_steps=self.train.decay_steps)
        mesh = self.mesh.build()
        return model, tx, mesh
