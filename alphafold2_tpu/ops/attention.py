"""Fused attention Pallas kernel for the Evoformer's axial attention.

The hot loop of the trunk is gated axial attention over rows/columns of
length <= crop (128-384) with an additive pair bias
(SURVEY.md §3.1; reference Attention at alphafold2.py:98-190). XLA already
fuses bias+softmax well, but it materializes the (B*L, H, N, N) logits in
HBM between the two matmuls; this kernel keeps the whole row block
resident in VMEM (crop-sized N fits comfortably: 384*64*4B per head-block)
and writes only the (N, D) output — one HBM round-trip instead of three.

Bias and masks are OPTIONAL and never materialized at full batch size in
HBM (round-1 ADVICE/VERDICT finding: the old contract forced callers to
allocate a dense fp32 (B, Nq, Nk) bias of zeros even with no bias/mask,
re-introducing exactly the O(N^2) HBM traffic the kernel exists to avoid):
- `bias` may be passed *unrepeated* — shape (Bb, Nq, Nk) with
  B == Bb//heads * bias_repeat * heads — and the BlockSpec index map
  replays it across the folded axial axis, so the axial row/col edge bias
  (b, h, N, N) is read as-is instead of being `jnp.repeat`-ed to
  (b*L, h, N, N);
- `q_mask`/`k_mask` are (B//heads, N) vectors; the (Nq, Nk) fill is
  computed inside the kernel in VMEM.

Shapes are the post-folding axial layout: q/k/v (B, N, D) with heads
folded innermost into B (B = batch*heads, head fastest). Softmax runs in
fp32 regardless of input dtype.

Selection: `use_pallas_attention(True)` flips the backend globally (the
flax modules read the flag at trace time); it requires a TPU backend —
under CPU tests the kernel runs in interpreter mode only inside its own
unit tests.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

try:  # pallas import is TPU/CPU-safe; guard for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

# Large-negative fill for masked logits (matches model/primitives.py).
MASK_VALUE = -1e9

_BACKEND = {"pallas": False}


def use_pallas_attention(enabled: bool = True):
    """Globally select the Pallas fused-attention path."""
    _BACKEND["pallas"] = enabled and HAS_PALLAS


def pallas_attention_enabled() -> bool:
    return _BACKEND["pallas"]


@contextlib.contextmanager
def pallas_attention(enabled: bool = True):
    prev = _BACKEND["pallas"]
    use_pallas_attention(enabled)
    try:
        yield
    finally:
        _BACKEND["pallas"] = prev


def _attn_kernel(*refs, scale, has_bias, has_qm, has_km):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    bias_ref = refs[idx] if has_bias else None
    idx += int(has_bias)
    qm_ref = refs[idx] if has_qm else None
    idx += int(has_qm)
    km_ref = refs[idx] if has_km else None
    idx += int(has_km)
    o_ref = refs[idx]

    q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    k = k_ref[0].astype(jnp.float32)                  # (n, d)
    v = v_ref[0].astype(jnp.float32)                  # (n, d)

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (bq, n)
    if has_bias:
        logits = logits + bias_ref[0].astype(jnp.float32)
    if has_qm or has_km:
        # masks arrive as (1, len) f32 rows; the (bq, n) fill pattern is
        # their outer AND, built here in VMEM rather than in HBM upstream.
        # Reshape the f32 rows BEFORE comparing: Mosaic (v5e) cannot
        # reshape i1 vectors across the minor dim ("Insertion of minor dim
        # that is not a no-op only supported for 32-bit types").
        valid = jnp.ones(logits.shape, dtype=bool)
        if has_qm:
            valid &= qm_ref[0].reshape(-1, 1) > 0     # (bq, 1)
        if has_km:
            valid &= km_ref[0].reshape(1, -1) > 0     # (1, n)
        logits = jnp.where(valid, logits, MASK_VALUE)

    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / denom
    o_ref[0] = out.astype(o_ref.dtype)


def _fused_attention_pallas(
    q: jnp.ndarray,              # (B, Nq, D)
    k: jnp.ndarray,              # (B, Nk, D)
    v: jnp.ndarray,              # (B, Nk, D)
    bias=None,                   # (Bb, Nq, Nk) additive, optional
    q_mask=None,                 # (B // heads, Nq) bool/0-1, optional
    k_mask=None,                 # (B // heads, Nk) bool/0-1, optional
    *,
    heads: int = 1,
    bias_repeat: int = 1,
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """The raw pallas_call (forward only — no AD rule; use
    `fused_attention`)."""
    b, n, d = q.shape
    nk = k.shape[1]
    # largest power-of-two block <= block_q that divides n, so any sequence
    # length works (crops are normally multiples of 8 anyway)
    bq = min(block_q, n)
    while bq > 1 and n % bq != 0:
        bq //= 2
    block_q = bq if n % bq == 0 else 1
    scale = 1.0  # caller pre-scales q (matches model convention)

    grid = (b, n // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, nk, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, nk, d), lambda i, j: (i, 0, 0)),
    ]
    args = [q, k, v]

    if bias is not None:
        assert bias.shape[0] * bias_repeat == b, (bias.shape, bias_repeat, b)
        rh = bias_repeat * heads
        in_specs.append(pl.BlockSpec(
            (1, block_q, nk),
            lambda i, j: ((i // rh) * heads + i % heads, j, 0)))
        args.append(bias)
    if q_mask is not None:
        assert q_mask.shape == (b // heads, n), (q_mask.shape, b, heads, n)
        in_specs.append(pl.BlockSpec(
            (1, 1, block_q), lambda i, j: (i // heads, 0, j)))
        args.append(q_mask.astype(jnp.float32).reshape(b // heads, 1, n))
    if k_mask is not None:
        assert k_mask.shape == (b // heads, nk), (k_mask.shape, b, heads, nk)
        in_specs.append(pl.BlockSpec(
            (1, 1, nk), lambda i, j: (i // heads, 0, 0)))
        args.append(k_mask.astype(jnp.float32).reshape(b // heads, 1, nk))

    kernel = functools.partial(
        _attn_kernel, scale=scale, has_bias=bias is not None,
        has_qm=q_mask is not None, has_km=k_mask is not None)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, n, d), q.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(*args)


@functools.lru_cache(maxsize=None)
def _fused_attention_vjp(heads, bias_repeat, block_q, interpret):
    """custom_vjp wrapper: Pallas forward, XLA-recompute backward.

    The kernel stores only the (N, D) output, so the backward recomputes
    attention through `attention_reference` under jax.vjp — the same
    recompute-in-backward trade `jax.checkpoint` makes, with XLA free to
    fuse the recomputation. Grads flow to q/k/v and the (unrepeated)
    bias; masks get symbolic-zero cotangents."""

    def run(q, k, v, bias, q_mask, k_mask):
        return _fused_attention_pallas(
            q, k, v, bias, q_mask, k_mask, heads=heads,
            bias_repeat=bias_repeat, block_q=block_q, interpret=interpret)

    f = jax.custom_vjp(run)

    def fwd(q, k, v, bias, q_mask, k_mask):
        return run(q, k, v, bias, q_mask, k_mask), \
            (q, k, v, bias, q_mask, k_mask)

    def bwd(res, g):
        import numpy as np
        q, k, v, bias, q_mask, k_mask = res
        if bias is None:
            ref = lambda q, k, v: attention_reference(
                q, k, v, q_mask=q_mask, k_mask=k_mask, heads=heads,
                bias_repeat=bias_repeat)
            _, vjp = jax.vjp(ref, q, k, v)
            dq, dk, dv = vjp(g)
            dbias = None
        else:
            ref = lambda q, k, v, bias: attention_reference(
                q, k, v, bias=bias, q_mask=q_mask, k_mask=k_mask,
                heads=heads, bias_repeat=bias_repeat)
            _, vjp = jax.vjp(ref, q, k, v, bias)
            dq, dk, dv, dbias = vjp(g)

        def zero_cot(x):
            if x is None:
                return None
            if jnp.issubdtype(x.dtype, jnp.inexact):
                return jnp.zeros_like(x)
            return np.zeros(np.shape(x), dtype=jax.dtypes.float0)

        return dq, dk, dv, dbias, zero_cot(q_mask), zero_cot(k_mask)

    f.defvjp(fwd, bwd)
    return f


def fused_attention(
    q: jnp.ndarray,              # (B, Nq, D)
    k: jnp.ndarray,              # (B, Nk, D)
    v: jnp.ndarray,              # (B, Nk, D)
    bias=None,                   # (Bb, Nq, Nk) additive, optional
    q_mask=None,                 # (B // heads, Nq) bool/0-1, optional
    k_mask=None,                 # (B // heads, Nk) bool/0-1, optional
    *,
    heads: int = 1,
    bias_repeat: int = 1,
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused bias+mask+softmax+matmul attention (differentiable).

    Batch layout: B = batch * bias_repeat * heads with head fastest, i.e.
    flat index i = (batch * bias_repeat + fold) * heads + head. `bias`
    covers (batch, heads) and is replayed over the folded middle axis via
    the index map; masks cover (batch * bias_repeat) and are shared
    across heads. N and D should be multiples of the TPU lane/sublane
    tiling (128 / 8); callers pad crops accordingly.

    Degenerate tiles (Nq or Nk < 8 — e.g. the 1x1 pair maps the model's
    init-time branch coverage traces) fall back to the XLA reference:
    Mosaic lowers their dots to vector multi_reductions with loop-carried
    accumulators and refuses ("only constant accumulators supported",
    observed on-chip r05), and such shapes gain nothing from the kernel.
    """
    n, nk = q.shape[1], k.shape[1]
    if n < 8 or nk < 8:
        return attention_reference(q, k, v, bias=bias, q_mask=q_mask,
                                   k_mask=k_mask, heads=heads,
                                   bias_repeat=bias_repeat)
    return _fused_attention_vjp(heads, bias_repeat, block_q, interpret)(
        q, k, v, bias, q_mask, k_mask)


def attention_reference(q, k, v, bias=None, q_mask=None, k_mask=None,
                        *, heads=1, bias_repeat=1):
    """XLA reference of the same contract (used for tests and fallback)."""
    logits = jnp.einsum("bnd,bmd->bnm", q, k).astype(jnp.float32)
    if bias is not None:
        logits = logits + jnp.repeat(
            bias.astype(jnp.float32).reshape(
                -1, heads, *bias.shape[1:]),
            bias_repeat, axis=0).reshape(logits.shape)
    valid = None
    if q_mask is not None:
        valid = (q_mask > 0)[:, :, None]
    if k_mask is not None:
        km = (k_mask > 0)[:, None, :]
        valid = km if valid is None else valid & km
    if valid is not None:
        valid = jnp.broadcast_to(
            valid, (valid.shape[0],) + logits.shape[1:])
        valid = jnp.repeat(valid, heads, axis=0)
        logits = jnp.where(valid, logits, MASK_VALUE)
    attn = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnm,bmd->bnd", attn.astype(q.dtype), v)
