"""Fused attention Pallas kernel for the Evoformer's axial attention.

The hot loop of the trunk is gated axial attention over rows/columns of
length <= crop (128-384) with an additive pair bias
(SURVEY.md §3.1; reference Attention at alphafold2.py:98-190). XLA already
fuses bias+softmax well, but it materializes the (B*L, H, N, N) logits in
HBM between the two matmuls; this kernel keeps the whole row block
resident in VMEM (crop-sized N fits comfortably: 384*64*4B per head-block)
and writes only the (N, D) output — one HBM round-trip instead of three.

Shapes are the post-folding axial layout: q/k/v (B, N, D) with heads folded
into B, bias (B, N, N) already containing mask fill. Softmax runs in fp32
regardless of input dtype.

Selection: `use_pallas_attention(True)` flips the backend globally (the
flax modules read the flag at trace time); it requires a TPU backend —
under CPU tests the kernel runs in interpreter mode only inside its own
unit tests.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

try:  # pallas import is TPU/CPU-safe; guard for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

_BACKEND = {"pallas": False}


def use_pallas_attention(enabled: bool = True):
    """Globally select the Pallas fused-attention path."""
    _BACKEND["pallas"] = enabled and HAS_PALLAS


def pallas_attention_enabled() -> bool:
    return _BACKEND["pallas"]


@contextlib.contextmanager
def pallas_attention(enabled: bool = True):
    prev = _BACKEND["pallas"]
    use_pallas_attention(enabled)
    try:
        yield
    finally:
        _BACKEND["pallas"] = prev


def _attn_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale):
    q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    k = k_ref[0].astype(jnp.float32)                  # (n, d)
    v = v_ref[0].astype(jnp.float32)                  # (n, d)
    bias = bias_ref[0].astype(jnp.float32)            # (bq, n)

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) + bias    # (bq, n)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    out = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / denom
    o_ref[0] = out.astype(o_ref.dtype)


def fused_attention(
    q: jnp.ndarray,        # (B, N, D)
    k: jnp.ndarray,        # (B, N, D)
    v: jnp.ndarray,        # (B, N, D)
    bias: jnp.ndarray,     # (B, N, N) additive (mask already folded in)
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused bias+softmax+matmul attention. N and D should be multiples of
    the TPU lane/sublane tiling (128 / 8); callers pad crops accordingly."""
    b, n, d = q.shape
    nk = k.shape[1]
    # largest power-of-two block <= block_q that divides n, so any sequence
    # length works (crops are normally multiples of 8 anyway)
    bq = min(block_q, n)
    while bq > 1 and n % bq != 0:
        bq //= 2
    block_q = bq if n % bq == 0 else 1
    scale = 1.0  # caller pre-scales q (matches model convention)

    grid = (b, n // block_q)
    return pl.pallas_call(
        functools.partial(_attn_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, n, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, nk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, nk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, nk), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(q, k, v, bias)


def attention_reference(q, k, v, bias):
    """XLA reference of the same contract (used for tests and fallback)."""
    logits = jnp.einsum("bnd,bmd->bnm", q, k).astype(jnp.float32) + \
        bias.astype(jnp.float32)
    attn = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnm,bmd->bnd", attn.astype(q.dtype), v)
