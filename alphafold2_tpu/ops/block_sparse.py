"""True block-sparse attention Pallas kernel (splash-style block skipping).

Round-1 VERDICT (§2.4 "DeepSpeed sparse attn"): the model-level
`BlockSparseAttention` is dense compute + additive mask — correct
semantics, zero FLOP savings. This kernel does the real thing, the TPU
way: the sparsity pattern is compressed host-side into a per-q-block
column list, the grid's innermost dimension runs only to the max live
block count T (<< n_blocks for banded/global patterns), and a scalar-
prefetched index map steers each step's k/v DMA straight to the t-th
live block. FLOPs and HBM traffic both scale with nnz blocks, not N².

Softmax is the online (flash) recurrence over visited blocks — running
row max / denominator in VMEM scratch, output written on the last step.
Equivalent to dense attention with the pattern applied as a -1e9
additive bias (tests/test_ops.py::TestBlockSparseKernel asserts this
against `attention_reference`).

No torch/CUDA counterpart is being translated here: DeepSpeed's sparse
attention is a Triton kernel stack; this is an independent Pallas
design following the public splash-attention pattern (scalar prefetch +
compressed column index).
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

NEG_INF = float("-inf")
MASK_VALUE = -1e9  # matches ops/attention.py and the dense model path


def plan_block_pattern(pattern: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Compress a (nqb, nkb) boolean block pattern into a padded column
    plan: cols[i, t] = index of the t-th live k-block of q-block i,
    valid[i, t] = 1 where the slot is real. Every q-block must keep at
    least one live k-block (softmax over an empty row is undefined)."""
    pattern = np.asarray(pattern, dtype=bool)
    counts = pattern.sum(axis=1)
    if counts.min() < 1:
        raise ValueError("every q block needs >= 1 live k block")
    t_max = int(counts.max())
    nqb = pattern.shape[0]
    cols = np.zeros((nqb, t_max), np.int32)
    valid = np.zeros((nqb, t_max), np.int32)
    for i in range(nqb):
        live = np.nonzero(pattern[i])[0]
        cols[i, :live.size] = live
        valid[i, :live.size] = 1
    return cols, valid


def _kernel(cols_ref, valid_ref, *refs, t_total, scale, has_kmask):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    km_ref = refs[idx] if has_kmask else None
    idx += int(has_kmask)
    o_ref = refs[idx]
    acc_ref, m_ref, l_ref = refs[idx + 1:]

    qb = pl.program_id(1)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(valid_ref[qb, t] == 1)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k = k_ref[0].astype(jnp.float32)          # (bk, d)
        v = v_ref[0].astype(jnp.float32)          # (bk, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (bq, bk)
        if has_kmask:
            # (1, bk) f32 row — stays >=2-D in VMEM, broadcasting over
            # the query dim (same mask recipe as ops/attention.py)
            logits = jnp.where(km_ref[0] > 0, logits, MASK_VALUE)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
        # exp(-inf - m_new) == 0 covers the first live step cleanly
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
        m_ref[...] = m_new

    @pl.when(t == t_total - 1)
    def _finish():
        # l >= 1 always: every q-block has >= 1 live k-block
        # (plan_block_pattern), and even a fully-masked block contributes
        # p = exp(-1e9 - (-1e9)) = 1 per key — fully-masked rows yield a
        # mean of visited values (unspecified on every backend), never a
        # zero division
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def block_sparse_attention(
    q: jnp.ndarray,                # (B, N, D)
    k: jnp.ndarray,                # (B, N, D)
    v: jnp.ndarray,                # (B, N, D)
    pattern: np.ndarray,           # (nqb, nkb) bool, STATIC
    *,
    k_mask: jnp.ndarray | None = None,   # (B // heads, N) key validity
    heads: int = 1,
    scale: float | None = None,
    block: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Attention restricted to `pattern` with true block skipping.

    `scale` multiplies q inside the kernel; default 1/sqrt(D) (the
    standard softmax temperature). Pass scale=1.0 for pre-scaled q —
    e.g. when fed from Attention.project_qkv, which scales at projection
    time. `k_mask` masks individual keys INSIDE live blocks (the padded
    tail of a crop, per-sequence gaps) with the dense path's -1e9 fill;
    it stays UNrepeated — shape (B // heads, N) with head folded
    innermost into B — and the BlockSpec index map replays it across
    heads at zero HBM cost (same contract as ops/attention.py's
    fused_attention). Query-side masking is not applied — masked-query
    rows are unspecified on every backend, matching the dense path's
    contract.

    The Mosaic compile path (PrefetchScalarGridSpec + scalar-prefetch
    index maps) is exactness-tested in interpreter mode
    (tests/test_ops.py); on-chip timing vs the XLA dense path is
    `python tools/bench_blocksparse.py` (see STATUS.md for the current
    keep-or-kill state).
    """
    if not HAS_PALLAS:
        raise RuntimeError("block_sparse_attention needs jax.experimental"
                           ".pallas, which failed to import in this build")
    b, n, d = q.shape
    assert n % block == 0, (n, block)
    nqb = n // block
    assert pattern.shape == (nqb, nqb), (pattern.shape, nqb)
    cols, valid = plan_block_pattern(pattern)
    t_total = cols.shape[1]
    if scale is None:
        scale = float(d) ** -0.5
    has_kmask = k_mask is not None

    qkv_spec = [
        pl.BlockSpec((1, block, d),
                     lambda bi, qb, t, cols, valid: (bi, qb, 0)),
        pl.BlockSpec((1, block, d),
                     lambda bi, qb, t, cols, valid:
                     (bi, cols[qb, t], 0)),
        pl.BlockSpec((1, block, d),
                     lambda bi, qb, t, cols, valid:
                     (bi, cols[qb, t], 0)),
    ]
    args = [jnp.asarray(cols), jnp.asarray(valid), q, k, v]
    if has_kmask:
        assert b % heads == 0, (b, heads)
        assert k_mask.shape == (b // heads, n), \
            (k_mask.shape, (b // heads, n))
        # 3-D (B//heads, 1, N) f32, sliced (1, 1, block) per live block
        # and replayed across the folded head axis by the index map —
        # mirrors fused_attention's mask recipe (stays >=2-D in VMEM;
        # Mosaic v5e cannot reshape 1-bit/1-D vectors on the minor dim)
        args.append(k_mask.astype(jnp.float32)
                    .reshape(b // heads, 1, n))
        qkv_spec.append(pl.BlockSpec(
            (1, 1, block),
            lambda bi, qb, t, cols, valid:
            (bi // heads, 0, cols[qb, t])))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nqb, t_total),
        in_specs=qkv_spec,
        out_specs=pl.BlockSpec((1, block, d),
                               lambda bi, qb, t, cols, valid: (bi, qb, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),   # acc
            pltpu.VMEM((block, 1), jnp.float32),   # running max
            pltpu.VMEM((block, 1), jnp.float32),   # denominator
        ],
    )
    kernel = functools.partial(_kernel, t_total=t_total, scale=scale,
                               has_kmask=has_kmask)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, d), q.dtype),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(*args)
