"""True block-sparse attention Pallas kernel (splash-style block skipping).

Round-1 VERDICT (§2.4 "DeepSpeed sparse attn"): the model-level
`BlockSparseAttention` is dense compute + additive mask — correct
semantics, zero FLOP savings. This kernel does the real thing, the TPU
way: the sparsity pattern is compressed host-side into a per-q-block
column list, the grid's innermost dimension runs only to the max live
block count T (<< n_blocks for banded/global patterns), and a scalar-
prefetched index map steers each step's k/v DMA straight to the t-th
live block. FLOPs and HBM traffic both scale with nnz blocks, not N².

Softmax is the online (flash) recurrence over visited blocks — running
row max / denominator in VMEM scratch, output written on the last step.
Equivalent to dense attention with the pattern applied as a -1e9
additive bias (tests/test_ops.py::TestBlockSparseKernel asserts this
against `attention_reference`).

No torch/CUDA counterpart is being translated here: DeepSpeed's sparse
attention is a Triton kernel stack; this is an independent Pallas
design following the public splash-attention pattern (scalar prefetch +
compressed column index).
"""

from __future__ import annotations

import contextlib
import functools
import hashlib
import threading
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
    # jax 0.4.x ships the TPU compiler params as TPUCompilerParams;
    # newer releases renamed it CompilerParams. One shim keeps the
    # kernel lowering on both (same spirit as parallel/sharding.py's
    # shard_map_compat toolchain shims).
    _COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
except Exception:  # pragma: no cover
    HAS_PALLAS = False
    _COMPILER_PARAMS = None

NEG_INF = float("-inf")
MASK_VALUE = -1e9  # matches ops/attention.py and the dense model path


def on_tpu_backend() -> bool:
    """The platform-string-is-TPU predicate for trace-time kernel
    dispatch (mirrors __graft_entry__.is_tpu_platform, which package
    code cannot import: the tunneled chip reports 'axon', a directly
    attached one 'tpu' — checking == 'tpu' alone would silently route
    real-chip serving onto the masked-dense fallback)."""
    plat = jax.default_backend() or ""
    return plat == "axon" or "tpu" in plat


def banded_block_pattern(n_blocks: int, window: int = 1,
                         num_global: int = 1) -> np.ndarray:
    """(n_blocks, n_blocks) bool block pattern: attend within +-window
    blocks of the diagonal plus the first num_global global blocks.
    THE single source of the local+global semantics — KernelSpec.banded,
    contact_block_pattern's floor, and the model-level
    attention_variants.block_sparse_block_pattern all delegate here, so
    the serving mask and the model mask cannot drift."""
    bi = np.arange(n_blocks)
    local = np.abs(bi[:, None] - bi[None, :]) <= window
    glob = (bi < num_global)[:, None] | (bi < num_global)[None, :]
    return local | glob


def plan_block_pattern(pattern: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Compress a (nqb, nkb) boolean block pattern into a padded column
    plan: cols[i, t] = index of the t-th live k-block of q-block i,
    valid[i, t] = 1 where the slot is real. Every q-block must keep at
    least one live k-block (softmax over an empty row is undefined)."""
    pattern = np.asarray(pattern, dtype=bool)
    counts = pattern.sum(axis=1)
    if counts.min() < 1:
        raise ValueError("every q block needs >= 1 live k block")
    t_max = int(counts.max())
    nqb = pattern.shape[0]
    cols = np.zeros((nqb, t_max), np.int32)
    valid = np.zeros((nqb, t_max), np.int32)
    for i in range(nqb):
        live = np.nonzero(pattern[i])[0]
        cols[i, :live.size] = live
        valid[i, :live.size] = 1
    return cols, valid


def _kernel(cols_ref, valid_ref, *refs, t_total, scale, has_bias,
            has_kmask):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    bias_ref = refs[idx] if has_bias else None
    idx += int(has_bias)
    km_ref = refs[idx] if has_kmask else None
    idx += int(has_kmask)
    o_ref = refs[idx]
    acc_ref, m_ref, l_ref = refs[idx + 1:]

    qb = pl.program_id(1)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(valid_ref[qb, t] == 1)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
        k = k_ref[0].astype(jnp.float32)          # (bk, d)
        v = v_ref[0].astype(jnp.float32)          # (bk, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (bq, bk)
        if has_bias:
            # (bq, bk) additive bias of THIS live block (the unrepeated
            # per-head pair bias, steered by the same compressed column
            # plan as k/v — dead blocks' bias is never even fetched)
            logits = logits + bias_ref[0].astype(jnp.float32)
        if has_kmask:
            # (1, bk) f32 row — stays >=2-D in VMEM, broadcasting over
            # the query dim (same mask recipe as ops/attention.py)
            logits = jnp.where(km_ref[0] > 0, logits, MASK_VALUE)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
        # exp(-inf - m_new) == 0 covers the first live step cleanly
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
        m_ref[...] = m_new

    @pl.when(t == t_total - 1)
    def _finish():
        # l >= 1 always: every q-block has >= 1 live k-block
        # (plan_block_pattern), and even a fully-masked block contributes
        # p = exp(-1e9 - (-1e9)) = 1 per key — fully-masked rows yield a
        # mean of visited values (unspecified on every backend), never a
        # zero division
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def block_sparse_attention(
    q: jnp.ndarray,                # (B, N, D)
    k: jnp.ndarray,                # (B, N, D)
    v: jnp.ndarray,                # (B, N, D)
    pattern: np.ndarray,           # (nqb, nkb) bool, STATIC
    *,
    bias: jnp.ndarray | None = None,     # (Bb, N, N) additive, unrepeated
    bias_repeat: int = 1,
    k_mask: jnp.ndarray | None = None,   # (B // heads, N) key validity
    heads: int = 1,
    scale: float | None = None,
    block: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Attention restricted to `pattern` with true block skipping.

    `scale` multiplies q inside the kernel; default 1/sqrt(D) (the
    standard softmax temperature). Pass scale=1.0 for pre-scaled q —
    e.g. when fed from Attention.project_qkv, which scales at projection
    time. `bias` is an optional additive logit bias (the Evoformer's
    pair-edge bias) with the SAME unrepeated-replay contract as
    ops/attention.py's fused_attention: shape (Bb, N, N) with
    B == Bb // heads * bias_repeat * heads (head fastest), replayed
    across the folded axial axis by the index map — and only LIVE
    blocks of it are ever DMA'd, so the bias read scales with nnz
    blocks like everything else. `k_mask` masks individual keys INSIDE
    live blocks (the padded tail of a crop, per-sequence gaps) with the
    dense path's -1e9 fill; it stays UNrepeated — shape (B // heads, N)
    with head folded innermost into B — and the BlockSpec index map
    replays it across heads at zero HBM cost. Query-side masking is not
    applied — masked-query rows are unspecified on every backend,
    matching the dense path's contract.

    The Mosaic compile path (PrefetchScalarGridSpec + scalar-prefetch
    index maps) is exactness-tested in interpreter mode
    (tests/test_ops.py); on-chip timing vs the XLA dense path is
    `python tools/bench_blocksparse.py` (see STATUS.md for the current
    keep-or-kill state).
    """
    if not HAS_PALLAS:
        raise RuntimeError("block_sparse_attention needs jax.experimental"
                           ".pallas, which failed to import in this build")
    b, n, d = q.shape
    assert n % block == 0, (n, block)
    nqb = n // block
    assert pattern.shape == (nqb, nqb), (pattern.shape, nqb)
    cols, valid = plan_block_pattern(pattern)
    t_total = cols.shape[1]
    if scale is None:
        scale = float(d) ** -0.5
    has_bias = bias is not None
    has_kmask = k_mask is not None

    qkv_spec = [
        pl.BlockSpec((1, block, d),
                     lambda bi, qb, t, cols, valid: (bi, qb, 0)),
        pl.BlockSpec((1, block, d),
                     lambda bi, qb, t, cols, valid:
                     (bi, cols[qb, t], 0)),
        pl.BlockSpec((1, block, d),
                     lambda bi, qb, t, cols, valid:
                     (bi, cols[qb, t], 0)),
    ]
    args = [jnp.asarray(cols), jnp.asarray(valid), q, k, v]
    if has_bias:
        assert bias.shape[0] * bias_repeat == b, \
            (bias.shape, bias_repeat, b)
        assert bias.shape[1:] == (n, n), (bias.shape, n)
        rh = bias_repeat * heads
        # fused_attention's replay contract: flat batch index
        # i = (batch * bias_repeat + fold) * heads + head, bias covers
        # (batch, heads) — only the live block (qb, cols[qb, t]) of the
        # (N, N) map is fetched per step
        qkv_spec.append(pl.BlockSpec(
            (1, block, block),
            lambda bi, qb, t, cols, valid:
            ((bi // rh) * heads + bi % heads, qb, cols[qb, t])))
        args.append(bias.astype(jnp.float32))
    if has_kmask:
        assert b % heads == 0, (b, heads)
        assert k_mask.shape == (b // heads, n), \
            (k_mask.shape, (b // heads, n))
        # 3-D (B//heads, 1, N) f32, sliced (1, 1, block) per live block
        # and replayed across the folded head axis by the index map —
        # mirrors fused_attention's mask recipe (stays >=2-D in VMEM;
        # Mosaic v5e cannot reshape 1-bit/1-D vectors on the minor dim)
        args.append(k_mask.astype(jnp.float32)
                    .reshape(b // heads, 1, n))
        qkv_spec.append(pl.BlockSpec(
            (1, 1, block),
            lambda bi, qb, t, cols, valid:
            (bi // heads, 0, cols[qb, t])))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nqb, t_total),
        in_specs=qkv_spec,
        out_specs=pl.BlockSpec((1, block, d),
                               lambda bi, qb, t, cols, valid: (bi, qb, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),   # acc
            pltpu.VMEM((block, 1), jnp.float32),   # running max
            pltpu.VMEM((block, 1), jnp.float32),   # denominator
        ],
    )
    kernel = functools.partial(_kernel, t_total=t_total, scale=scale,
                               has_bias=has_bias, has_kmask=has_kmask)
    kw = {}
    if _COMPILER_PARAMS is not None:
        kw["compiler_params"] = _COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, d), q.dtype),
        interpret=interpret,
        **kw,
    )(*args)


# ---------------------------------------------------------------------------
# Serving-side kernel selection (ISSUE 12)
# ---------------------------------------------------------------------------
#
# A KernelSpec is the STATIC description of which attention kernel one
# compiled executable runs: the block pattern (banded+global, or a
# per-target contact-prior mask planned from recycle-1 pair
# activations), the block size, and the backend. It is hashable and
# cheap to label, so the serving executor can bake it into an ExecKey —
# flipping the policy (or re-planning the mask) re-lowers instead of
# serving a stale program.
#
# The spec reaches the model through a TRACE-TIME context
# (`kernel_context`), the same pattern as ops.attention's global
# use_pallas_attention flag but scoped and thread-local: the executor's
# jitted entry points wrap `predict.fold*` in the context, and
# `model.primitives.Attention` reads `active_kernel_spec()` while being
# traced, dispatching matching self-attention (attended-axis length ==
# spec.n) onto `block_sparse_attention` — one params tree, no module
# changes, the kernel choice lives entirely in which executable you
# compile.


@dataclass(frozen=True)
class KernelSpec:
    """One attention-kernel choice, static per compiled executable.

    pattern: (nqb, nkb) block pattern as a tuple of row tuples of bool
        (hashable; `pattern_array()` gives the numpy view the kernel
        plans from). Every row must keep >= 1 live block
        (plan_block_pattern's softmax guard).
    block: token block size. The spec covers attention whose attended
        axis has length n == block * nqb exactly.
    backend: "auto" (Pallas kernel on TPU, masked-dense fallback on
        CPU — tier-1 stays green without interpret-mode compile blowup),
        "pallas" (force the kernel; interpret mode off-TPU — tests),
        "masked" (dense compute + the pattern as a -1e9 additive mask:
        identical support, no FLOP skipping — the numerics reference).
    source: "static" (banded+global first-pass mask) or "contact"
        (planned from recycle-1 pair activations); observability only.
    """

    block: int
    pattern: Tuple[Tuple[bool, ...], ...]
    backend: str = "auto"
    source: str = "static"
    _label: str = field(default="", compare=False)

    def __post_init__(self):
        if self.backend not in ("auto", "pallas", "masked"):
            raise ValueError(f"unknown backend {self.backend!r}")
        nqb = len(self.pattern)
        if nqb == 0 or any(len(r) != nqb for r in self.pattern):
            raise ValueError("pattern must be square and non-empty")
        if any(not any(r) for r in self.pattern):
            raise ValueError("every q block needs >= 1 live k block")

    @classmethod
    def from_pattern(cls, pattern, block: int, backend: str = "auto",
                     source: str = "static") -> "KernelSpec":
        arr = np.asarray(pattern, dtype=bool)
        return cls(block=int(block),
                   pattern=tuple(tuple(bool(x) for x in row)
                                 for row in arr),
                   backend=backend, source=source)

    @classmethod
    def banded(cls, n: int, block: int, window: int = 1,
               num_global: int = 1, backend: str = "auto"
               ) -> "KernelSpec":
        """The static first-pass mask (banded_block_pattern — the one
        local+global source shared with the model-level menu)."""
        if n % block:
            raise ValueError(f"n={n} not divisible by block={block}")
        return cls.from_pattern(
            banded_block_pattern(n // block, window, num_global),
            block, backend=backend)

    @property
    def n(self) -> int:
        return self.block * len(self.pattern)

    @property
    def live_fraction(self) -> float:
        flat = [x for row in self.pattern for x in row]
        return sum(flat) / float(len(flat))

    @property
    def label(self) -> str:
        """Short stable identifier — the ExecKey element and the span/
        metric tag. Covers pattern content, block size, and backend, so
        two specs that would compile different programs never share a
        label."""
        lbl = object.__getattribute__(self, "_label")
        if not lbl:
            h = hashlib.blake2b(digest_size=4)
            h.update(np.packbits(self.pattern_array()).tobytes())
            h.update(f"|{self.block}|{self.backend}".encode())
            lbl = (f"bs{self.block}x{len(self.pattern)}-"
                   f"{self.source[0]}{h.hexdigest()}")
            object.__setattr__(self, "_label", lbl)
        return lbl

    def pattern_array(self) -> np.ndarray:
        return np.asarray(self.pattern, dtype=bool)

    def token_mask(self) -> np.ndarray:
        """(n, n) bool token-level view of the block pattern (the
        masked-dense backend's additive-mask support)."""
        p = self.pattern_array()
        return np.repeat(np.repeat(p, self.block, 0), self.block, 1)

    def covers(self, n: int) -> bool:
        return int(n) == self.n

    def resolve_backend(self) -> str:
        """The backend this trace actually runs: "auto" is the Pallas
        kernel when lowering for a TPU, the masked-dense fallback
        otherwise (CPU tier-1 must not pay interpret-mode tracing for
        every serving fold — interpret is opt-in via backend="pallas")."""
        if self.backend != "auto":
            return self.backend
        return "pallas" if (HAS_PALLAS and on_tpu_backend()) \
            else "masked"

    def interpret(self) -> bool:
        return not on_tpu_backend()


_ACTIVE = threading.local()


def active_kernel_spec() -> Optional[KernelSpec]:
    """The KernelSpec governing the current trace, if any (thread-local
    — concurrent executor compiles on dispatch-pool threads each see
    their own)."""
    return getattr(_ACTIVE, "spec", None)


@contextlib.contextmanager
def kernel_context(spec: Optional[KernelSpec]):
    """Activate `spec` for the enclosed trace (None suppresses an outer
    context — e.g. the MSA column track, whose attended axis is
    alignment rows, must never inherit a residue-axis pattern)."""
    prev = getattr(_ACTIVE, "spec", None)
    _ACTIVE.spec = spec
    try:
        yield
    finally:
        _ACTIVE.spec = prev


# -- contact-prior mask planning (host-side, numpy) -------------------------


def contact_probs_from_distogram(distogram: np.ndarray,
                                 cutoff: float = 8.0,
                                 lengths=None) -> np.ndarray:
    """(n, n) contact probability from distogram logits: P(d < cutoff)
    via softmax over the distance buckets, max-reduced over the batch
    axis when given (b, n, n, buckets) — a batch shares one compiled
    pattern, so the mask must keep any block ANY element needs.

    `lengths` (optional, one per batch element) zeroes each element's
    contribution beyond its real residue count BEFORE the batch
    reduce: a padded row's distogram is garbage, and under continuous
    batching an admitted shorter fold's padding region (ISSUE 13) must
    plan as DEAD blocks — the sparse kernel must never DMA pair-bias
    garbage the mask would otherwise mark live. A length of 0 removes
    the element entirely (an unoccupied batch row).

    Bucket edges follow the distogram head's convention
    (constants.DISTOGRAM_MIN_DIST..MAX_DIST, linspace over
    DISTOGRAM_BUCKETS)."""
    from alphafold2_tpu import constants

    logits = np.asarray(distogram, np.float32)
    if logits.ndim == 3:
        logits = logits[None]
    b, n, n2, nb = logits.shape
    if lengths is not None and len(lengths) != b:
        raise ValueError(
            f"lengths has {len(lengths)} entries for batch of {b}")
    edges = np.linspace(constants.DISTOGRAM_MIN_DIST,
                        constants.DISTOGRAM_MAX_DIST, nb)
    # stable softmax over the bucket axis, ONE full-size temporary
    # (in-place exp; the normalized (..., nb) array is never
    # materialized): this runs host-side inside the serving step loop,
    # where a long bucket's (b, n, n, 37) map is GB-scale
    z = logits - logits.max(-1, keepdims=True)
    np.exp(z, out=z)
    close = edges <= cutoff
    probs = z[..., close].sum(-1)
    probs /= z.sum(-1)                       # (b, n, n)
    if lengths is not None:
        for i, ln in enumerate(lengths):
            ln = max(int(ln), 0)
            probs[i, ln:, :] = 0.0
            probs[i, :, ln:] = 0.0
    return probs.max(0)


def contact_block_pattern(contacts: np.ndarray, block: int, *,
                          threshold: float = 0.5,
                          live_frac: Optional[float] = None,
                          window: int = 1,
                          num_global: int = 1) -> np.ndarray:
    """Plan a (nqb, nkb) block pattern from an (n, n) contact-probability
    map: a block is live when its max cell probability clears
    `threshold` — or, with `live_frac` set, when it ranks inside the
    top live_frac of blocks (a data-independent FLOP budget). The
    banded window + global blocks are ALWAYS kept (the first-pass
    static mask is a floor, so the contact prior can only add support,
    never starve the diagonal) and the result is symmetrized —
    attention support should be, and it guarantees plan_block_pattern's
    min-1-live-block invariant via the diagonal."""
    c = np.asarray(contacts, np.float32)
    n = c.shape[0]
    if c.shape != (n, n):
        raise ValueError(f"contacts must be square, got {c.shape}")
    if n % block:
        raise ValueError(f"n={n} not divisible by block={block}")
    nb = n // block
    scores = c.reshape(nb, block, nb, block).max(axis=(1, 3))
    if live_frac is not None:
        live_frac = min(max(float(live_frac), 0.0), 1.0)
        k = max(1, int(round(live_frac * nb * nb)))
        cut = np.sort(scores.ravel())[::-1][k - 1]
        live = scores >= cut
    else:
        live = scores >= threshold
    live = live | banded_block_pattern(nb, window, num_global)
    return live | live.T
