from alphafold2_tpu.ops.attention import (  # noqa: F401
    attention_reference,
    fused_attention,
    pallas_attention,
    pallas_attention_enabled,
    use_pallas_attention,
)
