from alphafold2_tpu.ops.attention import (  # noqa: F401
    attention_reference,
    fused_attention,
    pallas_attention,
    pallas_attention_enabled,
    use_pallas_attention,
)
from alphafold2_tpu.ops.block_sparse import (  # noqa: F401
    KernelSpec,
    active_kernel_spec,
    block_sparse_attention,
    contact_block_pattern,
    contact_probs_from_distogram,
    kernel_context,
    plan_block_pattern,
)
