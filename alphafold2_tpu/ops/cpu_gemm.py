"""AMX-accelerated Dense contractions for the XLA:CPU fallback path.

The production compute path is XLA:TPU (bf16 on the MXU). When a step runs
on the host instead — the driver's CPU fallback, CI, tests — XLA:CPU's dot
emitter reaches ~100 GFLOP/s on one core while the same core's AMX tiles
sustain >600 GFLOP/s in bf16. `native/amx_gemm.cc` provides a
single-threaded AMX GEMM as the XLA FFI custom call ``af2_amx_gemm``
(f32 in/out, bf16 tile compute, f32 accumulate — mirroring the TPU MXU's
bf16-multiply/f32-accumulate precision story); this module routes the
model's Dense-layer contractions to it.

Opt-in and CPU-only: enable with ``AF2_CPU_AMX=1`` (read at trace time) or
`use_amx_dense(True)`. `amx_dense_dot_general` is shaped like
`lax.dot_general` so it can be handed to `flax.linen.Dense(dot_general=…)`;
ineligible calls (batched dims, misaligned K/N, non-f32 dtypes, non-CPU
backend, tiny M, a per-call precision request above DEFAULT) fall through
to XLA unchanged. With the flag OFF the wrapper is `lax.dot_general`
bit-for-bit; with it ON, routed GEMMs carry bf16 operand rounding
(~2e-2 rel vs the f32 dot) — opting in chooses that precision story.

Gradients route through AMX too (`jax.custom_vjp`: dA = G @ Bᵀ and
dB = Aᵀ @ G are themselves eligible GEMMs; the transposes stay in XLA,
which emits blocked transposes).

No reference counterpart: lucidrains/alphafold2's CPU matmuls ride
torch/ATen's oneDNN. This is the from-scratch JAX-runtime equivalent.
"""

from __future__ import annotations

import ctypes
import math
import os
import subprocess

import jax
import jax.numpy as jnp
from jax import lax

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           os.pardir, os.pardir, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libaf2amx.so")

_lib = None
_lib_failed = False
_registered = False
_enabled: bool | None = None  # tri-state: None -> consult AF2_CPU_AMX env


def _load() -> "ctypes.CDLL | None":
    """Load (building on demand) libaf2amx.so; None when unavailable."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    try:
        if not os.path.exists(_LIB_PATH):
            # cross-process build lock: concurrent first users (pytest
            # workers, a bench child) must not race `make` — the loser
            # could dlopen a half-written .so and latch _lib_failed
            import fcntl
            with open(os.path.join(_NATIVE_DIR, ".amx_build.lock"),
                      "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                if not os.path.exists(_LIB_PATH):
                    subprocess.run(
                        ["make", "-C", _NATIVE_DIR, "-s", "libaf2amx.so",
                         f"FFI_INCLUDE={jax.ffi.include_dir()}"],
                        check=True, capture_output=True, text=True,
                        timeout=120)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.af2_amx_available.restype = ctypes.c_int
        if not lib.af2_amx_available():
            raise RuntimeError("host CPU has no AMX tile support")
        _lib = lib
        return _lib
    except Exception as e:  # noqa: BLE001 — degrade to XLA, but say why
        import warnings
        detail = ""
        if isinstance(e, subprocess.CalledProcessError):
            detail = f"; make stderr: {(e.stderr or '')[-500:]}"
        warnings.warn(
            f"AF2 AMX GEMM unavailable, Dense contractions stay on XLA "
            f"({type(e).__name__}: {e}{detail})", RuntimeWarning,
            stacklevel=3)
        _lib_failed = True
        return None


def _ensure_registered() -> bool:
    global _registered
    if _registered:
        return True
    lib = _load()
    if lib is None:
        return False
    jax.ffi.register_ffi_target(
        "af2_amx_gemm", jax.ffi.pycapsule(lib.Af2AmxGemm), platform="cpu")
    jax.ffi.register_ffi_target(
        "af2_amx_gemm_tb", jax.ffi.pycapsule(lib.Af2AmxGemmTb),
        platform="cpu")
    jax.ffi.register_ffi_target(
        "af2_amx_attn_qk", jax.ffi.pycapsule(lib.Af2AmxAttnQk),
        platform="cpu")
    jax.ffi.register_ffi_target(
        "af2_amx_attn_av", jax.ffi.pycapsule(lib.Af2AmxAttnAv),
        platform="cpu")
    _registered = True
    return True


def use_amx_dense(on: bool) -> None:
    """Force the AMX Dense path on/off (overrides the AF2_CPU_AMX env)."""
    global _enabled
    _enabled = bool(on)


def amx_dense_enabled() -> bool:
    """True when eligible Dense contractions will route to the AMX GEMM."""
    if _enabled is False:
        return False
    if _enabled is None and os.environ.get("AF2_CPU_AMX") != "1":
        return False
    return jax.default_backend() == "cpu" and _ensure_registered()


def _ffi_gemm(a, b):
    """af2_amx_gemm on 2-D or 3-D (leading batch-of-GEMMs) operands."""
    out_shape = a.shape[:-1] + b.shape[-1:]
    return jax.ffi.ffi_call(
        "af2_amx_gemm",
        jax.ShapeDtypeStruct(out_shape, jnp.float32),
        vmap_method="sequential",
    )(a, b)


def _eligible(a_shape, b_shape, a_dtype, b_dtype) -> bool:
    m = math.prod(a_shape[:-1])
    k, n = b_shape[-2], b_shape[-1]
    return (a_dtype == jnp.float32 and b_dtype == jnp.float32
            and k % 32 == 0 and n % 16 == 0 and m >= 32 and k >= 32)


@jax.custom_vjp
def amx_matmul(a, b):
    """a[M,K] @ b[K,N] (or [G,·,·] batched) on the AMX tiles, f32."""
    return _ffi_gemm(a, b)


def _amx_matmul_fwd(a, b):
    return _ffi_gemm(a, b), (a, b)


def _amx_matmul_bwd(res, g):
    a, b = res
    # da = g @ b^T: the tb kernel reads b [..,K,N] as the transposed
    # operand directly (no XLA transpose)
    if (b.dtype == jnp.float32 and g.dtype == jnp.float32
            and b.shape[-1] % 32 == 0 and b.shape[-2] % 16 == 0):
        da = _ffi_gemm_tb(g, b)
    else:
        da = jnp.matmul(g, jnp.swapaxes(b, -1, -2))
    at = jnp.swapaxes(a, -1, -2)
    db = (_ffi_gemm(at, g) if _eligible(at.shape, g.shape, at.dtype, g.dtype)
          else jnp.matmul(at, g))
    return da, db


amx_matmul.defvjp(_amx_matmul_fwd, _amx_matmul_bwd)


def _ffi_gemm_tb(a, bt):
    """C = a @ bt^T with bt stored [.., N, K] — af2_amx_gemm_tb packs the
    transposed operand straight into VNNI tiles (no XLA transpose)."""
    out_shape = a.shape[:-1] + bt.shape[-2:-1]
    return jax.ffi.ffi_call(
        "af2_amx_gemm_tb",
        jax.ShapeDtypeStruct(out_shape, jnp.float32),
        vmap_method="sequential",
    )(a, bt)


# batched form is the same op — the kernel takes [G,M,K]x[G,K,N] natively
amx_bmm = amx_matmul


@jax.custom_vjp
def amx_bmm_tb(a, bt):
    """Batched a[G,M,K] @ bt[G,N,K]^T — the q @ k^T shape of attention
    logits, with k consumed in its natural [tokens, head_dim] layout."""
    return _ffi_gemm_tb(a, bt)


def _amx_bmm_tb_fwd(a, bt):
    return _ffi_gemm_tb(a, bt), (a, bt)


def _amx_bmm_tb_bwd(res, g):
    a, bt = res
    # da = g @ bt (natural); dbt = g^T @ a (one XLA transpose of g)
    da = (_ffi_gemm(g, bt) if _eligible(g.shape, bt.shape, g.dtype,
                                        bt.dtype) else jnp.matmul(g, bt))
    gt = jnp.swapaxes(g, -1, -2)
    dbt = (_ffi_gemm(gt, a) if _eligible(gt.shape, a.shape, gt.dtype,
                                         a.dtype) else jnp.matmul(gt, a))
    return da, dbt


amx_bmm_tb.defvjp(_amx_bmm_tb_fwd, _amx_bmm_tb_bwd)


def _ffi_attn_qk(q, k):
    """q[B,N,H,D] x k[B,M,H,D] -> [B,H,N,M], heads minor to tokens on
    both inputs — no transposes materialize around the custom call."""
    b, n, h, _ = q.shape
    m = k.shape[1]
    return jax.ffi.ffi_call(
        "af2_amx_attn_qk",
        jax.ShapeDtypeStruct((b, h, n, m), jnp.float32),
        vmap_method="sequential",
    )(q, k)


def _ffi_attn_av(p, v):
    """probs[B,H,N,M] x v[B,M,H,D] -> [B,N,H,D] (token-major out)."""
    b, h, n, _ = p.shape
    d = v.shape[-1]
    return jax.ffi.ffi_call(
        "af2_amx_attn_av",
        jax.ShapeDtypeStruct((b, n, h, d), jnp.float32),
        vmap_method="sequential",
    )(p, v)


@jax.custom_vjp
def amx_attn_qk(q, k):
    """Natural-layout attention logits on the AMX tiles. The two
    attention ops are each other's duals, so every gradient is again one
    of the two kernels; only the probs-sized cotangent transposes."""
    return _ffi_attn_qk(q, k)


def _amx_attn_qk_fwd(q, k):
    return _ffi_attn_qk(q, k), (q, k)


def _amx_attn_qk_bwd(res, g):
    q, k = res
    dq = _ffi_attn_av(g, k)
    dk = _ffi_attn_av(jnp.swapaxes(g, -1, -2), q)
    return dq, dk


amx_attn_qk.defvjp(_amx_attn_qk_fwd, _amx_attn_qk_bwd)


@jax.custom_vjp
def amx_attn_av(p, v):
    """Natural-layout probs @ v on the AMX tiles (see amx_attn_qk)."""
    return _ffi_attn_av(p, v)


def _amx_attn_av_fwd(p, v):
    return _ffi_attn_av(p, v), (p, v)


def _amx_attn_av_bwd(res, g):
    p, v = res
    dp = _ffi_attn_qk(g, v)
    dv = _ffi_attn_av(jnp.swapaxes(p, -1, -2), g)
    return dp, dv


amx_attn_av.defvjp(_amx_attn_av_fwd, _amx_attn_av_bwd)


def amx_attention_natural_ok(q_nhd, k_nhd) -> bool:
    """True when the whole natural-layout attention path (qk, av, and
    both backward duals) is AMX-eligible for these [B,tokens,H,D]
    operands: D and both token counts 32-aligned, f32, flag on."""
    n, d = q_nhd.shape[1], q_nhd.shape[3]
    m = k_nhd.shape[1]
    return (amx_dense_enabled()
            and q_nhd.dtype == jnp.float32 and k_nhd.dtype == jnp.float32
            and d % 32 == 0 and n % 32 == 0 and m % 32 == 0)


def amx_attention_dots(q, k):
    """einsum('bhid,bhjd->bhij') via the AMX tb kernel when enabled and
    aligned (d % 32 == 0, j % 16 == 0, f32); exact XLA einsum otherwise.

    The backward routes through AMX too (custom_vjp above)."""
    b, h, i, d = q.shape
    j = k.shape[-2]
    if (amx_dense_enabled() and q.dtype == jnp.float32
            and k.dtype == jnp.float32 and d % 32 == 0 and j % 16 == 0
            and b * h * i >= 32):
        out = amx_bmm_tb(q.reshape(b * h, i, d), k.reshape(b * h, j, d))
        return out.reshape(b, h, i, j)
    return jnp.einsum("bhid,bhjd->bhij", q, k)


def amx_attention_out(attn, v):
    """einsum('bhij,bhjd->bhid') via the AMX kernel when enabled and
    aligned (j % 32 == 0, d % 16 == 0, f32); exact XLA einsum otherwise."""
    b, h, i, j = attn.shape
    d = v.shape[-1]
    if (amx_dense_enabled() and attn.dtype == jnp.float32
            and v.dtype == jnp.float32 and j % 32 == 0 and d % 16 == 0
            and b * h * i >= 32):
        out = amx_bmm(attn.reshape(b * h, i, j), v.reshape(b * h, j, d))
        return out.reshape(b, h, i, d)
    return jnp.einsum("bhij,bhjd->bhid", attn, v)


def amx_dense_dot_general(lhs, rhs, dimension_numbers, precision=None,
                          preferred_element_type=None):
    """`lax.dot_general` drop-in for `flax.linen.Dense(dot_general=…)`.

    Routes the Dense pattern — contract lhs's last dim with rhs's first,
    no batch dims — to the AMX GEMM when enabled and aligned; everything
    else falls through to `lax.dot_general` bit-for-bit.

    Precision contract: a per-call ``precision`` request above DEFAULT
    (e.g. ``Dense(precision=lax.Precision.HIGHEST)``) always falls through
    to XLA — the tiles multiply in bf16 and cannot honor it. With
    ``precision=None`` the opt-in flag itself IS the precision choice
    (bf16 multiply / f32 accumulate, the TPU-MXU story), superseding the
    ambient ``jax_default_matmul_precision`` for the routed Dense layers;
    results differ from the f32 dot at bf16 rounding level (~2e-2 rel).
    """
    (lc, rc), (lb, rb) = dimension_numbers
    if (amx_dense_enabled()
            and precision in (None, lax.Precision.DEFAULT,
                              (lax.Precision.DEFAULT, lax.Precision.DEFAULT))
            and not lb and not rb
            and tuple(lc) == (lhs.ndim - 1,) and tuple(rc) == (0,)
            and rhs.ndim == 2
            and preferred_element_type in (None, jnp.float32)
            and _eligible(lhs.shape, rhs.shape, lhs.dtype, rhs.dtype)):
        lead = lhs.shape[:-1]
        out = amx_matmul(lhs.reshape(-1, lhs.shape[-1]), rhs)
        return out.reshape(*lead, rhs.shape[-1])
    return lax.dot_general(lhs, rhs, dimension_numbers, precision=precision,
                           preferred_element_type=preferred_element_type)
