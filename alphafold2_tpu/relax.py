"""Structure relaxation.

Parity-plus with the reference's post-processing layer
(/root/reference/scripts/refinement.py:22-74): `pdb2rosetta` /
`rosetta2pdb` conversions and `run_fast_relax` are gated on pyrosetta
exactly like the reference — but where the reference's relax raises
NotImplementedError (refinement.py:74), this module also ships a working
native alternative: `gradient_relax`, a differentiable restraint
minimizer in JAX (idealized covalent-bond lengths from the per-AA bond
tables + steric repulsion), jitted and TPU-ready.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from alphafold2_tpu.data.graph import prot_covalent_bond

# idealized bond length by element pair (see core/nerf.py)
_DEFAULT_BOND_LENGTH = 1.52
_CLASH_DISTANCE = 2.0


class RelaxResult(NamedTuple):
    coords: jnp.ndarray        # (b, L*14, 3)
    energy_history: jnp.ndarray  # (steps,)


def restraint_energy(coords_flat, bonds, atom_mask, bond_length=None):
    """Bond-length violations + soft steric clash energy.

    coords_flat: (b, L*14, 3); bonds: (b, N, N) covalent adjacency;
    atom_mask: (b, N) occupancy.
    """
    d2 = jnp.sum(
        (coords_flat[:, :, None] - coords_flat[:, None, :]) ** 2, -1)
    dist = jnp.sqrt(d2 + 1e-8)
    pair_mask = atom_mask[:, :, None] * atom_mask[:, None, :]

    target = _DEFAULT_BOND_LENGTH if bond_length is None else bond_length
    bond_term = (bonds * pair_mask * (dist - target) ** 2).sum((-1, -2))

    nonbond = pair_mask * (1.0 - bonds) * \
        (1.0 - jnp.eye(dist.shape[-1])[None])
    clash = nonbond * jnp.maximum(_CLASH_DISTANCE - dist, 0.0) ** 2
    return (bond_term + 0.25 * clash.sum((-1, -2))).sum()


def gradient_relax(
    coords14: jnp.ndarray,     # (b, L, 14, 3)
    seq: jnp.ndarray,          # (b, L)
    cloud_mask: Optional[jnp.ndarray] = None,   # (b, L, 14)
    steps: int = 50,
    lr: float = 0.02,
) -> RelaxResult:
    """Differentiable fast-relax substitute: gradient descent on covalent
    bond-length + clash restraints. Runs entirely under jit."""
    b, l, k, _ = coords14.shape
    flat = coords14.reshape(b, l * k, 3)
    bonds = prot_covalent_bond(seq)
    if cloud_mask is None:
        mask = (jnp.abs(coords14).sum(-1) != 0).astype(flat.dtype)
    else:
        mask = cloud_mask.astype(flat.dtype)
    mask_flat = mask.reshape(b, l * k)

    energy_grad = jax.grad(restraint_energy)

    def body(carry, _):
        x = carry
        g = energy_grad(x, bonds, mask_flat)
        x = x - lr * g * mask_flat[..., None]
        return x, restraint_energy(x, bonds, mask_flat)

    out, history = jax.lax.scan(body, flat, None, length=steps)
    return RelaxResult(out, history)


# ---------------------------------------------------------------------------
# pyrosetta-gated paths (reference scripts/refinement.py)
# ---------------------------------------------------------------------------


def _require_pyrosetta():
    try:
        import pyrosetta  # noqa: F401
        return pyrosetta
    except ImportError as exc:  # pragma: no cover - env dependent
        raise RuntimeError(
            "pyrosetta is not installed; use gradient_relax() for the "
            "native TPU relaxation path") from exc


def pdb2rosetta(route: str):
    """PDB file -> pyrosetta pose (reference refinement.py:22-32)."""
    pyrosetta = _require_pyrosetta()
    pyrosetta.init(silent=True)
    return pyrosetta.pose_from_pdb(route)


def rosetta2pdb(pose, route: str) -> str:
    """pyrosetta pose -> PDB file (reference refinement.py:34-44)."""
    _require_pyrosetta()
    pose.dump_pdb(route)
    return route


def run_fast_relax(route_in: str, route_out: str) -> str:
    """FastRelax via pyrosetta (the reference stops at NotImplementedError,
    refinement.py:74; this actually runs when pyrosetta exists)."""
    pyrosetta = _require_pyrosetta()
    pose = pdb2rosetta(route_in)
    scorefxn = pyrosetta.get_fa_scorefxn()
    relax = pyrosetta.rosetta.protocols.relax.FastRelax()
    relax.set_scorefxn(scorefxn)
    relax.apply(pose)
    return rosetta2pdb(pose, route_out)
