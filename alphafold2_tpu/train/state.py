"""Train state: params + optimizer + PRNG, one pytree.

Net-new relative to the reference (its training scripts keep model/optimizer
as Python objects and never checkpoint — SURVEY.md §5.4); designed so the
whole state shards under pjit (optimizer state inherits param shardings,
giving ZeRO-style optimizer sharding for free when params are sharded).
"""

from __future__ import annotations

from typing import Optional

import jax
import optax
from flax.training import train_state


class TrainState(train_state.TrainState):
    """flax TrainState + a carried PRNG key (for MLM noising / dropout)."""

    rng: jax.Array


def adam(
    learning_rate: float = 3e-4,
    grad_accum_every: int = 1,
    max_grad_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    """The reference's optimizer (Adam 3e-4, grad-accum 16 —
    train_pre.py:16,58; train_end2end.py:27) as one optax chain;
    accumulation via MultiSteps instead of a Python loop."""
    parts = []
    if max_grad_norm is not None:
        parts.append(optax.clip_by_global_norm(max_grad_norm))
    parts.append(optax.adam(learning_rate))
    tx = optax.chain(*parts)
    if grad_accum_every > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=grad_accum_every)
    return tx
