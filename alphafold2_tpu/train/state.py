"""Train state: params + optimizer + PRNG, one pytree.

Net-new relative to the reference (its training scripts keep model/optimizer
as Python objects and never checkpoint — SURVEY.md §5.4); the whole state
shards under pjit: `parallel.shard_pytree_zero` places params AND the adam
moments over the data axis (ZeRO-style), exercised end-to-end by
tests/test_sharding.py::TestZeroSharding (per-device optimizer bytes
measured ~1/n_data of replicated, numerics equal to the replicated step)
and by __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from typing import Optional

import jax
import optax
from flax.training import train_state


class TrainState(train_state.TrainState):
    """flax TrainState + a carried PRNG key (for MLM noising / dropout)."""

    rng: jax.Array


def adam(
    learning_rate: float = 3e-4,
    grad_accum_every: int = 1,
    max_grad_norm: Optional[float] = None,
    warmup_steps: int = 0,
    decay_steps: Optional[int] = None,
    end_lr_ratio: float = 0.1,
) -> optax.GradientTransformation:
    """The reference's optimizer (Adam 3e-4, grad-accum 16 —
    train_pre.py:16,58; train_end2end.py:27) as one optax chain;
    accumulation via MultiSteps instead of a Python loop.

    Beyond the reference's bare Adam: optional linear warmup over
    `warmup_steps` and cosine decay to `end_lr_ratio * learning_rate`
    over `decay_steps` (the AF2-style schedule). Both default off, so
    the reference configuration is the default behavior.
    """
    if decay_steps is not None:
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps > 0 else learning_rate,
            peak_value=learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=max(decay_steps, warmup_steps + 1),
            end_value=end_lr_ratio * learning_rate)
    elif warmup_steps > 0:
        # warmup alone: ramp to peak, then HOLD peak (no decay). The
        # obvious warmup_cosine_decay_schedule(decay_steps=warmup_steps+1)
        # spelling silently decays to end_lr one step after warmup.
        lr = optax.join_schedules(
            [optax.linear_schedule(0.0, learning_rate, warmup_steps),
             optax.constant_schedule(learning_rate)],
            boundaries=[warmup_steps])
    else:
        lr = learning_rate
    parts = []
    if max_grad_norm is not None:
        parts.append(optax.clip_by_global_norm(max_grad_norm))
    parts.append(optax.adam(lr))
    tx = optax.chain(*parts)
    if grad_accum_every > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=grad_accum_every)
    return tx
