from alphafold2_tpu.train import losses  # noqa: F401
from alphafold2_tpu.train.checkpoint import CheckpointManager  # noqa: F401
from alphafold2_tpu.train.loop import (  # noqa: F401
    compute_loss,
    fit,
    make_eval_step,
    make_recycled_train_step,
    make_train_step,
    shard_batch,
)
from alphafold2_tpu.train.prefetch import device_prefetch  # noqa: F401
from alphafold2_tpu.train.state import TrainState, adam  # noqa: F401
