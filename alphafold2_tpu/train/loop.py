"""Training step + loop.

The reference's loops (/root/reference/train_pre.py:64-96,
train_end2end.py:99-166) are Python for-loops with manual grad accumulation
and .backward(); here the step is one jitted, pjit-shardable function:

- loss = distogram CE [+ coords Kabsch-RMSD + dispersion term + MLM + angle
  CE + confidence regression], selected by what the batch provides and the
  model config;
- gradient accumulation lives in the optimizer (optax.MultiSteps), so the
  jitted step stays a single program;
- under a mesh, batch inputs are sharded over the `data` axis and the
  in-model sharding constraints distribute the pair representation over
  (i, j) — XLA inserts the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from alphafold2_tpu.parallel.mesh import DATA_AXIS
from alphafold2_tpu.parallel.sharding import active_mesh
from alphafold2_tpu.train import losses
from alphafold2_tpu.train.state import TrainState


def compute_loss(model, params, batch, rng, train: bool = True,
                 recyclables=None):
    """Forward + composite loss. Returns (loss, metrics).

    `recyclables` feeds the recycling embedder (prior-iteration state from
    a no-grad prologue pass; see make_recycled_train_step)."""
    metrics = {}
    wants_coords = model.predict_coords and "coords" in batch

    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones(batch["seq"].shape, dtype=bool)

    kwargs = dict(
        msa=batch.get("msa"),
        mask=mask,
        msa_mask=batch.get("msa_mask"),
        train=train,
        recyclables=recyclables,
    )
    # 'performer' redraws FAVOR+ random features every step (the per-step
    # form of performer-pytorch's feature_redraw_interval; unbiased). Eval
    # still needs a performer key: with rngs=None the scanned trunk would
    # hand every layer the same path-derived fallback key, so all layers
    # would share ONE FAVOR+ projection and their estimator errors add
    # coherently — a fixed key here lets nn.scan's split_rngs give each
    # layer an independent projection (predict.fold does the same).
    rngs = {"mlm": rng, "dropout": jax.random.fold_in(rng, 1),
            "performer": jax.random.fold_in(rng, 2)} if train \
        else {"performer": jax.random.PRNGKey(0)}

    if wants_coords:
        coords, ret = model.apply(params, batch["seq"], **kwargs,
                                  return_aux_logits=True,
                                  rngs=rngs)
        loss = losses.coords_loss(coords, batch["coords"], mask,
                                  distogram_logits=ret.distance)
        metrics["coords_loss"] = loss
        if ret.confidence is not None:
            c_loss = losses.lddt_confidence_loss(
                ret.confidence, coords, batch["coords"], mask)
            metrics["confidence_loss"] = c_loss
            loss = loss + c_loss
    elif model.predict_coords:
        # coords model but the batch has no coords target: still request
        # aux logits so `ret` is a ReturnValues, not a bare coords array
        # (only the MLM/angle terms below can contribute here — the
        # distogram term requires a coords target)
        _, ret = model.apply(params, batch["seq"], **kwargs,
                             return_aux_logits=True, rngs=rngs)
        loss = jnp.zeros((), jnp.float32)
    else:
        ret = model.apply(params, batch["seq"], **kwargs, rngs=rngs)
        loss = jnp.zeros((), jnp.float32)

    if "coords" in batch and not wants_coords:
        d_loss = losses.distogram_loss(ret.distance, batch["coords"], mask)
        metrics["distogram_loss"] = d_loss
        loss = loss + d_loss

    if model.predict_angles and "theta" in batch:
        a_loss = losses.angle_loss(
            ret.theta, ret.phi, ret.omega,
            batch["theta"], batch["phi"], batch["omega"])
        metrics["angle_loss"] = a_loss
        loss = loss + a_loss

    if ret.msa_mlm_loss is not None:
        metrics["mlm_loss"] = ret.msa_mlm_loss
        loss = loss + ret.msa_mlm_loss

    metrics["loss"] = loss
    return loss, metrics


def make_train_step(model):
    """Build the jitted train step: state, batch -> state, metrics."""

    def train_step(state: TrainState, batch):
        rng, new_rng = jax.random.split(state.rng)

        def loss_fn(params):
            return compute_loss(model, params, batch, rng, train=True)

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads).replace(rng=new_rng)
        return new_state, metrics

    return train_step


def make_recycled_train_step(model, max_recycles: int = 3):
    """Train step with SAMPLED recycling (the AF2 training protocol the
    reference only gestures at — its tests run the recycle loop by hand
    at inference, test_attention.py:344-385, but nothing trains the
    recycling embedder).

    Each step draws r ~ Uniform{0..max_recycles}, runs r no-grad passes
    threading `Recyclables` (the model already stop-gradients them), and
    takes the gradient only through the final pass — so the same weights
    serve every inference recycle count (predict.fold). One compiled
    program: the prologue is a fori_loop with a traced bound, the
    r==0 / r>0 split is a lax.cond."""
    assert model.predict_coords, "recycled training needs predict_coords"
    assert max_recycles >= 1

    def train_step(state: TrainState, batch):
        rng, new_rng = jax.random.split(state.rng)
        r = jax.random.randint(jax.random.fold_in(rng, 77), (), 0,
                               max_recycles + 1)

        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(batch["seq"].shape, dtype=bool)
        fwd_kwargs = dict(msa=batch.get("msa"), mask=mask,
                          msa_mask=batch.get("msa_mask"), train=False,
                          return_aux_logits=True, return_recyclables=True,
                          rngs={"performer": jax.random.PRNGKey(0)})

        def one_pass(rec):
            _, ret = model.apply(state.params, batch["seq"],
                                 recyclables=rec, **fwd_kwargs)
            return ret.recyclables

        # prologue: pass 1 from scratch, then r-1 recycled passes — all
        # outside the grad trace (recycling trains with stopped gradients,
        # matching the model's own stop_gradient on Recyclables). The
        # whole prologue sits under the r>0 cond so r==0 steps (1 in
        # max_recycles+1) skip it entirely; the false branch's zero
        # Recyclables are never consumed (the loss cond discards them).
        rec_shapes = jax.eval_shape(lambda: one_pass(None))

        def prologue(_):
            return jax.lax.fori_loop(
                0, jnp.maximum(r - 1, 0), lambda _, c: one_pass(c),
                one_pass(None))

        rec = jax.lax.cond(
            r > 0, prologue,
            lambda _: jax.tree.map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype), rec_shapes),
            None)

        def loss_fn(params):
            return jax.lax.cond(
                r > 0,
                lambda _: compute_loss(model, params, batch, rng,
                                       train=True, recyclables=rec),
                lambda _: compute_loss(model, params, batch, rng,
                                       train=True),
                None)

        grads, metrics = jax.grad(loss_fn, has_aux=True)(state.params)
        metrics["recycles"] = r.astype(jnp.float32)
        new_state = state.apply_gradients(grads=grads).replace(rng=new_rng)
        return new_state, metrics

    return train_step


def make_eval_step(model):
    def eval_step(state: TrainState, batch):
        _, metrics = compute_loss(model, state.params, batch,
                                  jax.random.PRNGKey(0), train=False)
        return metrics

    return eval_step


def shard_batch(batch, mesh=None):
    """Place a host batch on the mesh, sharded over the data axis."""
    mesh = mesh or active_mesh()
    if mesh is None:
        return batch

    def place(x):
        spec = [None] * x.ndim
        if x.ndim >= 1 and x.shape[0] % mesh.shape[DATA_AXIS] == 0:
            spec[0] = DATA_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(place, batch)


def fit(
    model,
    state: TrainState,
    batches,
    num_steps: int,
    log_every: int = 10,
    logger=None,
    step_timer=None,
    prefetch: int = 2,
    registry=None,
):
    """Minimal host loop (reference train_pre.py:64-96 analog): consumes an
    iterator of batches, runs the jitted step, logs scalar metrics.
    `prefetch` stages that many batches onto device from a background
    thread (train/prefetch.py) so host featurization/transfer overlaps
    the step; 0 disables.

    Training reports into the same process-wide metrics registry the
    serving stack uses (`registry=None` = obs.get_registry()):
    `train_steps_total`, a `train_step_seconds` histogram (when a
    `step_timer` measures steps), and last-logged loss terms as
    `train_metric{name=...}` gauges — one Prometheus scrape sees train
    and serve side by side."""
    from alphafold2_tpu.obs.registry import get_registry

    reg = registry or get_registry()
    m_steps = reg.counter("train_steps_total", "optimizer steps run")
    m_step_s = reg.histogram("train_step_seconds",
                             "wall time per training step")
    m_metric = reg.gauge("train_metric",
                         "last logged training metric value", ("name",))

    pre_placed = prefetch > 0
    if pre_placed:
        from alphafold2_tpu.train.prefetch import device_prefetch
        batches = device_prefetch(batches, size=prefetch)
    train_step = jax.jit(make_train_step(model), donate_argnums=(0,))
    history = []
    for i in range(num_steps):
        batch = next(batches)
        if step_timer is not None:
            step_timer.start()
        # the prefetch worker already owns placement; re-sharding every
        # step would redo a tree of device_puts on the hot path
        state, metrics = train_step(
            state, batch if pre_placed else shard_batch(batch))
        if step_timer is not None:
            jax.block_until_ready(metrics["loss"])
            step_timer.stop()
            # a StepTimer already wired to a registry histogram
            # (StepTimer(histogram=...)) records itself; observing here
            # too would double-count every step
            if getattr(step_timer, "histogram", None) is None:
                m_step_s.observe(step_timer.durations[-1])
        m_steps.inc()
        if i % log_every == 0:
            scalars = {k: float(v) for k, v in metrics.items()}
            history.append(scalars)
            for k, v in scalars.items():
                m_metric.set(v, name=k)
            if logger is not None:
                logger.log(step=i, **scalars)
    return state, history
