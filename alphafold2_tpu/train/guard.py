"""Failure detection and recovery for training.

Net-new vs the reference, which trains in bare infinite loops with no
try/except, no NaN handling, no checkpoint-on-failure (SURVEY.md §5.3):

- `guarded_train_step`: wraps a train step so a non-finite loss or
  gradient skips the update (params unchanged, a `skipped` flag and the
  bad-metric snapshot returned) instead of poisoning the state — all
  inside jit via `lax.cond`-style `where` selects;
- `AutoCheckpointer`: periodic + on-failure checkpointing around the host
  loop, resuming from the latest checkpoint after a crash.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from alphafold2_tpu.train.checkpoint import CheckpointManager
from alphafold2_tpu.train.state import TrainState


def all_finite(tree) -> jnp.ndarray:
    leaves = [jnp.isfinite(x).all() for x in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(leaves).all()


def guarded_train_step(train_step: Callable) -> Callable:
    """state, batch -> state, metrics with metrics['skipped'] = 1.0 when a
    non-finite loss/grad update was rejected (state passes through)."""

    def step(state: TrainState, batch):
        new_state, metrics = train_step(state, batch)
        # opt_state finiteness matters independently of params/loss: with
        # optax.MultiSteps accumulation a non-finite micro-step gradient
        # can poison the accumulator while params and loss stay finite,
        # and later rejected updates would roll back *onto* the poisoned
        # accumulator, wedging training permanently
        ok = (all_finite(metrics["loss"]) & all_finite(new_state.params)
              & all_finite(new_state.opt_state))

        # keep the PRNG/step advance so a skipped batch is not replayed
        # with the same randomness forever. One lax.cond over the whole
        # state instead of a per-leaf jnp.where: the per-leaf selects
        # blow XLA:CPU compile time up >10x on a full-model step (the
        # "Very slow compile" alarm; measured 15+ min vs ~90 s)
        passthrough = state.replace(step=new_state.step, rng=new_state.rng)
        safe_state = jax.lax.cond(ok, lambda: new_state,
                                  lambda: passthrough)
        metrics = dict(metrics)
        metrics["skipped"] = 1.0 - ok.astype(jnp.float32)
        return safe_state, metrics

    return step


class AutoCheckpointer:
    """Host-loop companion: save every `every` steps and on failure."""

    def __init__(self, directory: str, every: int = 100, max_to_keep: int = 3):
        self.manager = CheckpointManager(directory, max_to_keep=max_to_keep)
        self.every = every

    def maybe_save(self, state: TrainState, step: Optional[int] = None):
        step = int(state.step) if step is None else step
        if step > 0 and step % self.every == 0:
            self.manager.save(state, step)

    def resume_or(self, state: TrainState) -> TrainState:
        """Restore the latest checkpoint if one exists, else return state."""
        if self.manager.latest_step() is None:
            return state
        return self.manager.restore(state)

    def on_failure(self, state: TrainState):
        try:
            self.manager.save(state, int(state.step))
        except Exception:  # pragma: no cover - best effort
            pass
