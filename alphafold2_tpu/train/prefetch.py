"""Async host→device input staging.

The reference leans on torch DataLoader worker processes for input
overlap (training_scripts/datasets/trrosetta.py:451-476); the TPU-native
equivalent is simpler: featurization is already host-side numpy
(data/featurize.py), so one background thread that runs the iterator and
issues `device_put` (with the mesh placement of `train.shard_batch`) is
enough to hide host time behind the accelerator step — XLA transfers are
async and thread-safe.

`fit(..., prefetch=N)` uses this by default (N=2: one batch on device,
one staging). Exceptions in the source iterator surface in the consumer,
not silently in a dead thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

_END = object()


def device_prefetch(batches: Iterator[dict], size: int = 2,
                    mesh=None) -> Iterator[dict]:
    """Wrap a host batch iterator: a daemon thread stages up to `size`
    batches onto device while the caller's step runs — mesh data-axis
    placement via `train.shard_batch` under a mesh, plain `device_put`
    otherwise (so single-device training still gets the H2D overlap).
    Yields the same batches in order; the batches it yields are already
    placed (consumers must not re-shard).

    The worker stops when the consumer does: closing the generator (or
    letting it be GC'd after a partial read, as `fit` does after
    num_steps) signals the thread to exit rather than draining the
    source forever. At most one extra source batch — the one in flight —
    is consumed past the last one yielded; that lookahead is what
    prefetching is.
    """
    import jax

    from alphafold2_tpu.parallel.sharding import active_mesh
    from alphafold2_tpu.train.loop import shard_batch

    # resolve the mesh HERE: active_mesh() is thread-local, so the worker
    # thread would otherwise silently see none and skip placement
    mesh = mesh or active_mesh()
    if mesh is not None:
        place = lambda b: shard_batch(b, mesh)  # noqa: E731
    else:
        place = lambda b: jax.tree.map(jax.device_put, b)  # noqa: E731

    if size <= 0:
        yield from (place(b) for b in batches)
        return

    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    def worker():
        try:
            it = iter(batches)
            while not stop.is_set():
                try:
                    b = next(it)
                except StopIteration:
                    q.put((None, _END))
                    return
                item = ("ok", place(b))
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 - propagate to consumer
            q.put(("err", e))

    threading.Thread(target=worker, daemon=True,
                     name="device-prefetch").start()

    try:
        while True:
            tag, item = q.get()
            if item is _END:
                return
            if tag == "err":
                raise item
            yield item
    finally:
        stop.set()
