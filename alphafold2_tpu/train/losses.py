"""Training losses.

Parity with the reference's two training objectives:
- distogram cross-entropy with ignore_index=-100 over bucketized CA
  distances (/root/reference/train_pre.py:76-89, utils.py:45-50);
- end-to-end coordinate loss: Kabsch-align prediction onto ground truth,
  then RMSD, plus a distogram-dispersion weighting term
  (/root/reference/train_end2end.py:157-159);
- trRosetta-style angle cross-entropies for the theta/phi/omega heads
  (/root/reference/training_scripts/datasets/trrosetta.py targets);
- MSA-MLM loss comes out of the model itself (mlm.py:86-92 there).

All losses are masked means with static shapes; `ignore_index` semantics are
implemented with `where` masks rather than boolean indexing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from alphafold2_tpu import constants
from alphafold2_tpu.core import geometry as geo


def softmax_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_index: int = constants.IGNORE_INDEX,
) -> jnp.ndarray:
    """Mean CE over positions whose label != ignore_index.

    logits: (..., C) float; labels: (...,) int.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    m = valid.astype(jnp.float32)
    return (ce * m).sum() / jnp.maximum(m.sum(), 1.0)


def distogram_loss(
    distogram_logits: jnp.ndarray,
    coords_ca: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """Distogram pretraining loss (reference train_pre.py:76-89):
    bucketize true CA distances, CE against predicted logits."""
    targets = geo.bucketed_distance_matrix(coords_ca, mask)
    return softmax_cross_entropy(distogram_logits, targets)


def angle_loss(
    theta_logits, phi_logits, omega_logits,
    theta_target, phi_target, omega_target,
) -> jnp.ndarray:
    """Sum of trRosetta anglegram CEs (targets carry ignore_index fill)."""
    loss = softmax_cross_entropy(theta_logits, theta_target)
    loss += softmax_cross_entropy(phi_logits, phi_target)
    loss += softmax_cross_entropy(omega_logits, omega_target)
    return loss


def coords_loss(
    pred_coords: jnp.ndarray,
    true_coords: jnp.ndarray,
    mask: jnp.ndarray,
    distogram_logits: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """End-to-end coordinate loss (reference train_end2end.py:150-159):
    Kabsch-align then RMSD; if distogram logits are given, add the
    dispersion-weighted distance-matrix term the reference combines in."""
    aligned, target = geo.kabsch(pred_coords, true_coords, mask=mask)
    loss = geo.rmsd(aligned, target, mask=mask).mean()

    if distogram_logits is not None:
        probs = jax.nn.softmax(distogram_logits.astype(jnp.float32), axis=-1)
        _, weights = geo.center_distogram(probs)
        pair_mask = (mask[..., :, None] & mask[..., None, :])
        loss = loss + geo.distmat_loss(
            pred_coords, true_coords, mask=weights * pair_mask)
    return loss


def lddt_confidence_loss(
    pred_confidence: jnp.ndarray,   # (b, n, 1) raw head output
    pred_coords: jnp.ndarray,
    true_coords: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """Regress the confidence head onto the actual per-residue CA lDDT of
    the prediction (net-new vs the reference, whose lddt_linear head ships
    untrained — alphafold2.py:621, :903)."""
    target = geo.lddt_ca(true_coords, pred_coords, mask=mask)
    target = jax.lax.stop_gradient(target)
    pred = jax.nn.sigmoid(pred_confidence[..., 0].astype(jnp.float32))
    m = mask.astype(jnp.float32)
    return (((pred - target) ** 2) * m).sum() / jnp.maximum(m.sum(), 1.0)
