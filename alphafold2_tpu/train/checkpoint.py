"""Checkpoint / resume via orbax.

Net-new relative to the reference, which has no torch.save/load anywhere
(SURVEY.md §5.4). Saves the full TrainState pytree (params, optimizer state,
step, PRNG); restore rebuilds onto an abstract target so shardings and
dtypes come back exactly.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import orbax.checkpoint as ocp

from alphafold2_tpu.train.state import TrainState


class CheckpointManager:
    """Thin orbax wrapper with a stable on-disk layout."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, state: TrainState, step: Optional[int] = None) -> int:
        step = int(state.step) if step is None else step
        saveable = {"params": state.params, "opt_state": state.opt_state,
                    "step": state.step, "rng": state.rng}
        self._mgr.save(step, args=ocp.args.StandardSave(saveable))
        self._mgr.wait_until_finished()
        return step

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state: TrainState,
                step: Optional[int] = None) -> TrainState:
        """Restore into the structure of `state` (which supplies tx/apply_fn
        and the pytree layout)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        target = {"params": state.params, "opt_state": state.opt_state,
                  "step": state.step, "rng": state.rng}
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        return state.replace(
            params=restored["params"], opt_state=restored["opt_state"],
            step=restored["step"], rng=restored["rng"])
