"""Inference API: fold sequences with recycling and confidence.

The reference leaves the recycling loop to user code (its tests do two
manual passes, test_attention.py:344-385) and has no inference entry
point at all. `fold()` packages it: N recycling iterations under one jit
(`lax.scan` over the recycle axis — static, compile-once), returning
coordinates, per-residue confidence, and the trunk outputs.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from alphafold2_tpu.model.alphafold2 import Recyclables


class FoldResult(NamedTuple):
    coords: jnp.ndarray          # (b, n, 3)
    confidence: jnp.ndarray      # (b, n) in [0, 1]
    distogram: jnp.ndarray       # (b, n, n, buckets)
    recyclables: Recyclables


def fold(
    model,
    params,
    seq: jnp.ndarray,
    msa: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
    msa_mask: Optional[jnp.ndarray] = None,
    num_recycles: int = 3,
    **extra,
) -> FoldResult:
    """Run the model with `num_recycles` recycling iterations.

    `model` must be constructed with predict_coords=True. Jit-safe: wrap
    in jax.jit(partial(fold, model), static_argnames='num_recycles') or
    call under jit via a closure.
    """
    assert model.predict_coords, "fold() needs predict_coords=True"

    def one_pass(recyclables):
        coords, ret = model.apply(
            params, seq, msa=msa, mask=mask, msa_mask=msa_mask,
            recyclables=recyclables, return_aux_logits=True,
            return_recyclables=True,
            # a deterministic 'performer' rng: under the trunk scan its
            # split_rngs give each layer an INDEPENDENT FAVOR+ projection
            # at inference (per-layer estimator errors average out instead
            # of adding coherently); unused collections are harmless for
            # models without Performer layers
            rngs={"performer": jax.random.PRNGKey(0)}, **extra)
        return coords, ret

    # first pass has no recyclables (params cover both traces via the
    # init-time branch coverage)
    coords, ret = one_pass(None)

    if num_recycles > 0:
        # carry the latest outputs instead of stacking per-iteration ys:
        # keeps one copy of the O(n^2) distogram live, not num_recycles
        def body(carry, _):
            recyclables, *_ = carry
            coords, ret = one_pass(recyclables)
            return (ret.recyclables, coords, ret.distance,
                    ret.confidence), None

        (recyclables, coords, distance, confidence), _ = jax.lax.scan(
            body, (ret.recyclables, coords, ret.distance, ret.confidence),
            None, length=num_recycles)
    else:
        distance = ret.distance
        confidence = ret.confidence
        recyclables = ret.recyclables

    conf = jax.nn.sigmoid(confidence[..., 0].astype(jnp.float32))
    return FoldResult(coords, conf, distance, recyclables)


def fold_and_write(model, params, seq, out_path: str, **kwargs) -> list:
    """fold() + PDB output of the CA trace (data/pdb_io.coords2pdb).

    Folds the whole (b, n) batch in ONE forward pass and writes one PDB
    per batch element: `out_path` for a batch of 1, `<stem>_k<ext>` for
    element k otherwise. Returns the list of written paths (length b).
    Pass `mask` to trim per-element padding from the written trace.
    """
    import os

    import numpy as np

    from alphafold2_tpu.data.pdb_io import coords2pdb

    result = fold(model, params, seq, **kwargs)
    seq_np = np.asarray(seq)
    coords_np = np.asarray(result.coords)
    mask = kwargs.get("mask")
    mask_np = None if mask is None else np.asarray(mask)

    b = seq_np.shape[0]
    stem, ext = os.path.splitext(out_path)
    ext = ext or ".pdb"
    paths = []
    for k in range(b):
        path = out_path if b == 1 else f"{stem}_{k}{ext}"
        idx = (slice(None) if mask_np is None
               else np.flatnonzero(mask_np[k]))
        paths.append(coords2pdb(seq_np[k][idx], coords_np[k][idx],
                                name=path))
    return paths
