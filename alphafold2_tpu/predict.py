"""Inference API: fold sequences with recycling and confidence.

The reference leaves the recycling loop to user code (its tests do two
manual passes, test_attention.py:344-385) and has no inference entry
point at all. `fold()` packages it: N recycling iterations under one jit
(`lax.scan` over the recycle axis — static, compile-once), returning
coordinates, per-residue confidence, and the trunk outputs.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from alphafold2_tpu.model.alphafold2 import Recyclables


class FoldResult(NamedTuple):
    coords: jnp.ndarray          # (b, n, 3)
    confidence: jnp.ndarray      # (b, n) in [0, 1]
    distogram: jnp.ndarray       # (b, n, n, buckets)
    recyclables: Recyclables


class FoldStepState(NamedTuple):
    """One recycle iteration's full output — the carry of the
    scheduler-owned step loop (serve/recycle.py). Identical fields to
    FoldResult on purpose: after the LAST step the state IS the fold
    result, and `recyclables` is the only part the next step consumes.
    `confidence` is already sigmoided to [0, 1] (the same
    `sigmoid(raw[..., 0])` fold() applies once at the end — applying it
    per step changes nothing for the final state and gives every
    intermediate state a client-meaningful confidence for progressive
    results)."""

    coords: jnp.ndarray          # (b, n, 3)
    confidence: jnp.ndarray      # (b, n) in [0, 1]
    distogram: jnp.ndarray       # (b, n, n, buckets)
    recyclables: Recyclables


# single source of truth for the recycling default: fold_and_write's
# cache keys hash the effective value, so a drifting duplicate literal
# would silently serve results computed under one default as another
DEFAULT_NUM_RECYCLES = 3


def fold(
    model,
    params,
    seq: jnp.ndarray,
    msa: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
    msa_mask: Optional[jnp.ndarray] = None,
    num_recycles: int = DEFAULT_NUM_RECYCLES,
    kernel=None,
    **extra,
) -> FoldResult:
    """Run the model with `num_recycles` recycling iterations.

    `model` must be constructed with predict_coords=True. Jit-safe: wrap
    in jax.jit(partial(fold, model), static_argnames='num_recycles') or
    call under jit via a closure.

    kernel: optional `ops.block_sparse.KernelSpec` — routes the trunk's
    residue-axis self-attention through the block-skipping Pallas
    kernel (or its masked-dense fallback) for this trace (ISSUE 12).
    STATIC: bake it into the jitted closure like num_recycles; the
    serving executor keys executables by its label. None (default) is
    byte-for-byte the dense path.
    """
    assert model.predict_coords, "fold() needs predict_coords=True"

    def one_pass(recyclables):
        # delegates to the SAME _one_pass the step-mode entry points
        # (fold_init/fold_step) trace, so the step-loop == scan
        # exactness contract cannot drift between two call sites
        return _one_pass(model, params, seq, msa, mask, msa_mask,
                         recyclables, extra, kernel=kernel)

    # first pass has no recyclables (params cover both traces via the
    # init-time branch coverage)
    coords, ret = one_pass(None)

    if num_recycles > 0:
        # carry the latest outputs instead of stacking per-iteration ys:
        # keeps one copy of the O(n^2) distogram live, not num_recycles
        def body(carry, _):
            recyclables, *_ = carry
            coords, ret = one_pass(recyclables)
            return (ret.recyclables, coords, ret.distance,
                    ret.confidence), None

        (recyclables, coords, distance, confidence), _ = jax.lax.scan(
            body, (ret.recyclables, coords, ret.distance, ret.confidence),
            None, length=num_recycles)
    else:
        distance = ret.distance
        confidence = ret.confidence
        recyclables = ret.recyclables

    conf = jax.nn.sigmoid(confidence[..., 0].astype(jnp.float32))
    return FoldResult(coords, conf, distance, recyclables)


def _one_pass(model, params, seq, msa, mask, msa_mask, recyclables,
              extra, kernel=None):
    """One trunk+structure pass — THE call fold()'s closure and the
    step-mode entry points (fold_init/fold_step) all trace, so the
    step-loop == scan exactness contract cannot drift between call
    sites. The deterministic 'performer' rng: under the trunk scan its
    split_rngs give each layer an INDEPENDENT FAVOR+ projection at
    inference (per-layer estimator errors average out instead of
    adding coherently); unused collections are harmless for models
    without Performer layers.

    `kernel` (a static ops.block_sparse.KernelSpec) activates the
    serving kernel-selection context for exactly this trace: the
    model's residue-axis self-attention reads it at trace time and
    dispatches to the block-sparse kernel; the spec never reaches
    model.apply as an argument, so the params/trace signature is
    unchanged."""
    import contextlib

    from alphafold2_tpu.ops.block_sparse import kernel_context

    ctx = kernel_context(kernel) if kernel is not None \
        else contextlib.nullcontext()
    with ctx:
        return model.apply(
            params, seq, msa=msa, mask=mask, msa_mask=msa_mask,
            recyclables=recyclables, return_aux_logits=True,
            return_recyclables=True,
            rngs={"performer": jax.random.PRNGKey(0)}, **extra)


def _step_state(coords, ret) -> FoldStepState:
    conf = jax.nn.sigmoid(ret.confidence[..., 0].astype(jnp.float32))
    return FoldStepState(coords, conf, ret.distance, ret.recyclables)


def fold_init(model, params, seq, msa=None, mask=None, msa_mask=None,
              kernel=None, **extra) -> FoldStepState:
    """The embed+first-pass executable of step-mode folding: exactly
    fold(..., num_recycles=0), but returning a FoldStepState whose
    `recyclables` seed `fold_step`. Jit-safe the same way fold() is.

    Step-mode contract (tests/test_recycle.py pins it): for any R,
        state = fold_init(...); repeat R times: state = fold_step(state)
    produces coords/confidence/distogram numerically identical to
    `fold(..., num_recycles=R)` — the scan body and the step body are
    one function (`_one_pass`), so splitting the loop moves WHO owns
    the iteration (the scheduler instead of XLA), never what it
    computes. The identity holds between COMPILED programs (jit both
    sides — the serving executor always does); eager op-by-op
    execution rounds differently than the scan body's compiled HLO and
    is not covered."""
    assert model.predict_coords, "fold_init() needs predict_coords=True"
    coords, ret = _one_pass(model, params, seq, msa, mask, msa_mask,
                            None, extra, kernel=kernel)
    return _step_state(coords, ret)


def fold_init_rows(model, params, seq, row_mask, state: FoldStepState,
                   msa=None, mask=None, msa_mask=None, kernel=None,
                   **extra) -> FoldStepState:
    """Row-masked init: the continuous-batching admission program
    (ISSUE 11). Rows where `row_mask` is True are (re)initialized from
    the CURRENT batch tensors — exactly `fold_init`'s embed+first pass,
    recyclables=None — while rows where it is False pass the carried
    `state` through untouched, so survivor rows keep stepping from
    their own recycle depth while freed rows restart at iteration 0
    with a newly admitted request's content.

    The pass computes the init over the WHOLE batch (one fixed-shape
    executable, no data-dependent shapes) and selects per row; rows are
    independent through the model (regression-pinned by the repack
    tests), so an admitted row's init is byte-identical to folding that
    request alone at the same batch signature, and a survivor row's
    carried state is byte-identical through the `where` pass-through.

    row_mask: (b,) bool — True = initialize this row fresh.
    state: the carried FoldStepState whose non-admitted rows survive.
    """
    fresh = fold_init(model, params, seq, msa=msa, mask=mask,
                      msa_mask=msa_mask, kernel=kernel, **extra)

    def sel(new, old):
        m = jnp.reshape(row_mask, row_mask.shape
                        + (1,) * (new.ndim - row_mask.ndim))
        return jnp.where(m, new, old)

    return jax.tree_util.tree_map(sel, fresh, state)


def snapshot_step_state(state):
    """Host-side snapshot of a step-loop carry (ISSUE 14: the carry-
    checkpointing half of the scheduler's step-loop fault domain).
    Device leaves are fetched to numpy WITH their sharding recorded, so
    `restore_step_state` can re-upload a mesh-sharded carry back onto
    the exact slice it left; non-array leaves (custom test-executor
    states are opaque objects) are kept by reference — they are
    host-side already and step stubs mint fresh state objects per
    iteration, so the reference stays immutable. The snapshot survives
    an executor rebuild: nothing in it references the executor or its
    compiled programs."""
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(state)
    snap = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            snap.append(("dev", np.asarray(leaf),
                         getattr(leaf, "sharding", None)))
        else:
            snap.append(("ref", leaf, None))
    return treedef, snap


def restore_step_state(snapshot):
    """Re-upload a `snapshot_step_state` checkpoint: device leaves go
    back through their recorded sharding (falling back to a fresh
    default-device `jnp.array` when the sharding no longer applies —
    e.g. after an executor rebuild changed device objects), reference
    leaves pass through untouched. The restored carry is byte-equal to
    the snapshotted one — a resumed step loop continues exactly where
    the checkpoint left it."""
    treedef, snap = snapshot
    leaves = []
    for kind, val, sharding in snap:
        if kind != "dev":
            leaves.append(val)
            continue
        arr = None
        if sharding is not None:
            try:
                arr = jax.device_put(val, sharding)
            except Exception:
                arr = None       # stale sharding: default placement
        if arr is None:
            arr = jnp.array(val)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def fold_step(model, params, seq, recyclables: Recyclables, msa=None,
              mask=None, msa_mask=None, kernel=None,
              **extra) -> FoldStepState:
    """One recycle iteration: the `lax.scan` body of fold() as its own
    executable. Feed it the previous state's `recyclables` (from
    fold_init or an earlier fold_step). `kernel` may DIFFER from the
    init pass's spec — the contact-prior flow (ISSUE 12) re-plans the
    block mask from the recycle-1 pair activations and runs the
    remaining recycles under the re-lowered step executable."""
    assert model.predict_coords, "fold_step() needs predict_coords=True"
    coords, ret = _one_pass(model, params, seq, msa, mask, msa_mask,
                            recyclables, extra, kernel=kernel)
    return _step_state(coords, ret)


def fold_and_write(model, params, seq, out_path: str, cache=None,
                   model_tag: str = "", tracer=None, **kwargs) -> list:
    """fold() + PDB output of the CA trace (data/pdb_io.coords2pdb).

    Folds the whole (b, n) batch in ONE forward pass and writes one PDB
    per batch element: `out_path` for a batch of 1, `<stem>_k<ext>` for
    element k otherwise. Returns the list of written paths (length b).
    Pass `mask` to trim per-element padding from the written trace.

    cache: optional `alphafold2_tpu.cache.FoldCache` — the same
    content-addressed memoization the serving scheduler uses, so
    offline batch scripts re-running overlapping inputs skip the fold.
    Keys cover each element's unpadded (seq, msa, msa_mask,
    num_recycles) plus `model_tag` (identify your weights whenever the
    cache outlives this process) and any scalar extra model kwargs; a
    call with array-valued or un-hashable extras (e.g. batched
    per-element conditioning, which can't be attributed to one
    element's key) folds uncached rather than risk serving another
    call's result. With no extras and a
    trivial msa_mask the key matches the serving scheduler's
    (msa_depth=None config), so one shared FoldCache deduplicates
    across offline and served folds of the same content. The
    forward pass is skipped only when EVERY element hits (partial
    batches would mint a new compiled shape); partial hits still fold
    once but refresh the store. Off by default.

    tracer: optional `alphafold2_tpu.obs.Tracer` — the call gets one
    request-scoped trace (cache_lookup / fold / write spans, cache
    hit/miss events, source "cache" when the forward pass was skipped)
    in the same JSONL schema the serving scheduler emits, so offline
    batch folds land in the same `tools/obs_report.py` waterfall.
    """
    from alphafold2_tpu.obs.trace import NULL_TRACER

    trace = (tracer or NULL_TRACER).start_trace(out_path)
    try:
        return _fold_and_write_traced(model, params, seq, out_path, cache,
                                      model_tag, trace, **kwargs)
    except BaseException as exc:
        # every trace reaches exactly one terminal state, failures too
        trace.finish("error", error=repr(exc))
        raise


def _fold_and_write_traced(model, params, seq, out_path, cache,
                           model_tag, trace, **kwargs) -> list:
    import os

    import numpy as np

    from alphafold2_tpu.data.pdb_io import coords2pdb

    seq_np = np.asarray(seq)
    mask = kwargs.get("mask")
    mask_np = None if mask is None else np.asarray(mask)
    msa = kwargs.get("msa")
    msa_np = None if msa is None else np.asarray(msa)
    msa_mask = kwargs.get("msa_mask")
    msa_mask_np = None if msa_mask is None else np.asarray(msa_mask)
    b = seq_np.shape[0]

    def trim(k):
        return (slice(None) if mask_np is None
                else np.flatnonzero(mask_np[k]))

    keys = cached = None
    if cache is not None:
        from alphafold2_tpu.cache import fold_key
        num_recycles = kwargs.get("num_recycles", DEFAULT_NUM_RECYCLES)
        # everything fold() forwards beyond the keyed inputs must reach
        # the key too — two calls differing only in an extra conditioning
        # kwarg are different computations. Only SCALAR extras are
        # keyable: an array-valued extra (e.g. batched per-element
        # conditioning like embedds) can't be attributed to one element
        # of the per-element key, so it disables caching for the call
        # rather than risk serving another element's/call's result.
        # The no-extras case keys exactly like the serving scheduler
        # (extras=None), so offline and served folds of the same content
        # share entries when msa_depth semantics match (scheduler
        # msa_depth=None). An all-True msa_mask is content-equivalent
        # to no mask (the scheduler's own construction) and doesn't
        # split the key.
        extra = tuple(sorted(
            (k, v) for k, v in kwargs.items()
            if k not in ("msa", "mask", "msa_mask", "num_recycles")))
        scalar_ok = all(
            v is None or isinstance(v, (str, bytes, bool, int, float,
                                        np.integer, np.floating))
            for _, v in extra)
        if scalar_ok:
            try:
                with trace.span("cache_lookup", batch=b):
                    keys, cached = [], []
                    for k in range(b):
                        idx = trim(k)
                        mm = (None if msa_mask_np is None
                              else msa_mask_np[k][:, idx])
                        if mm is not None and mm.all():
                            mm = None
                        extras = None if not extra and mm is None \
                            else (extra, mm)
                        keys.append(fold_key(
                            seq_np[k][idx],
                            None if msa_np is None else msa_np[k][:, idx],
                            num_recycles=num_recycles,
                            model_tag=model_tag, extras=extras))
                        cached.append(cache.get(keys[k], trace=trace))
            except TypeError:
                # un-content-hashable extra kwarg: fold uncached rather
                # than risk serving another call's result
                keys = cached = None

    coords_np = confidence_np = None
    all_hit = cached is not None and all(c is not None for c in cached)
    if not all_hit:
        with trace.span("fold", batch=b):
            result = fold(model, params, seq, **kwargs)
            coords_np = np.asarray(result.coords)
            confidence_np = np.asarray(result.confidence)

    stem, ext = os.path.splitext(out_path)
    ext = ext or ".pdb"
    paths = []
    with trace.span("write", batch=b):
        for k in range(b):
            path = out_path if b == 1 else f"{stem}_{k}{ext}"
            idx = trim(k)
            if cached is not None and cached[k] is not None:
                coords_k = cached[k].coords
            else:
                coords_k = coords_np[k][idx]
                if keys is not None:
                    cache.put(keys[k], coords_k, confidence_np[k][idx])
            paths.append(coords2pdb(seq_np[k][idx], coords_k, name=path))
    trace.finish("ok", source="cache" if all_hit else "fold")
    return paths
