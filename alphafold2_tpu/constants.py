"""Global constants for alphafold2-tpu.

Capability parity with the reference constants module
(/root/reference/alphafold2_pytorch/constants.py:5-113): bucket counts,
embedding dims, the 14-atom-per-residue sidechainnet layout and per-residue
covalent-bond graphs. Unlike the reference there is no global mutable DEVICE
(constants.py:29-30 there) — JAX manages placement via jit/sharding.
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# Model-level constants (reference constants.py:5-15)
# ---------------------------------------------------------------------------

MAX_NUM_MSA = 20
MAX_NUM_TEMPLATES = 10
NUM_AMINO_ACIDS = 21
NUM_EMBEDDS_TR = 1280  # ESM-1b embedding width
NUM_EMBEDDS_T5 = 1024  # ProtT5-XL embedding width
NUM_COORDS_PER_RES = 14  # sidechainnet atom slots per residue

DISTOGRAM_BUCKETS = 37
THETA_BUCKETS = 25
PHI_BUCKETS = 13
OMEGA_BUCKETS = 25

# Distogram bin edges span 2..20 Angstrom (reference utils.py:41,47)
DISTOGRAM_MIN_DIST = 2.0
DISTOGRAM_MAX_DIST = 20.0

IGNORE_INDEX = -100

# ---------------------------------------------------------------------------
# Pretrained-embedding constants (reference constants.py:19-25)
# ---------------------------------------------------------------------------

MSA_EMBED_DIM = 768
MSA_MODEL_PATH = ["facebookresearch/esm", "esm_msa1_t12_100M_UR50S"]

ESM_EMBED_DIM = 1280
ESM_MODEL_PATH = ["facebookresearch/esm", "esm1b_t33_650M_UR50S"]

PROTTRAN_EMBED_DIM = 1024

# ---------------------------------------------------------------------------
# Amino-acid vocabulary (sidechainnet ordering) and atom layout
# ---------------------------------------------------------------------------

# Sidechainnet / proteinnet ordering: alphabetical by 3-letter code, then pad.
AA_ALPHABET = "ARNDCQEGHILKMFPSTWYV_"

ONE_TO_THREE = {
    "A": "ALA", "R": "ARG", "N": "ASN", "D": "ASP", "C": "CYS",
    "Q": "GLN", "E": "GLU", "G": "GLY", "H": "HIS", "I": "ILE",
    "L": "LEU", "K": "LYS", "M": "MET", "F": "PHE", "P": "PRO",
    "S": "SER", "T": "THR", "W": "TRP", "Y": "TYR", "V": "VAL",
}

THREE_TO_ONE = {v: k for k, v in ONE_TO_THREE.items()}

# Sidechain atom names beyond the N/CA/C/O backbone, in sidechainnet build
# order (slot 4 onward of the 14-atom layout).
SIDECHAIN_ATOMS = {
    "ALA": ["CB"],
    "ARG": ["CB", "CG", "CD", "NE", "CZ", "NH1", "NH2"],
    "ASN": ["CB", "CG", "OD1", "ND2"],
    "ASP": ["CB", "CG", "OD1", "OD2"],
    "CYS": ["CB", "SG"],
    "GLN": ["CB", "CG", "CD", "OE1", "NE2"],
    "GLU": ["CB", "CG", "CD", "OE1", "OE2"],
    "GLY": [],
    "HIS": ["CB", "CG", "ND1", "CD2", "CE1", "NE2"],
    "ILE": ["CB", "CG1", "CG2", "CD1"],
    "LEU": ["CB", "CG", "CD1", "CD2"],
    "LYS": ["CB", "CG", "CD", "CE", "NZ"],
    "MET": ["CB", "CG", "SD", "CE"],
    "PHE": ["CB", "CG", "CD1", "CD2", "CE1", "CE2", "CZ"],
    "PRO": ["CB", "CG", "CD"],
    "SER": ["CB", "OG"],
    "THR": ["CB", "OG1", "CG2"],
    "TRP": ["CB", "CG", "CD1", "CD2", "NE1", "CE2", "CE3", "CZ2", "CZ3", "CH2"],
    "TYR": ["CB", "CG", "CD1", "CD2", "CE1", "CE2", "CZ", "OH"],
    "VAL": ["CB", "CG1", "CG2"],
}

BACKBONE_ATOMS = ["N", "CA", "C", "O"]

# Per-residue covalent-bond graphs over the 14-slot atom layout
# (reference constants.py:34-113).  Slot 0..3 = N,CA,C,O; 4.. = sidechain.
AA_DATA = {
    "A": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4]]},
    "R": {"bonds": [[0, 1], [1, 2], [2, 3], [2, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8], [8, 9], [8, 10]]},
    "N": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6], [5, 7]]},
    "D": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6], [5, 7]]},
    "C": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5]]},
    "Q": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [6, 8]]},
    "E": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8]]},
    "G": {"bonds": [[0, 1], [1, 2], [2, 3]]},
    "H": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8], [8, 9], [5, 9]]},
    "I": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6], [4, 7]]},
    "L": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6], [5, 7]]},
    "K": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8]]},
    "M": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6], [6, 7]]},
    "F": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8], [8, 9], [9, 10], [5, 10]]},
    "P": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6], [0, 6]]},
    "S": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5]]},
    "T": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [4, 6]]},
    "W": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8], [8, 9], [9, 10], [10, 11], [11, 12],
                    [12, 13], [5, 13], [8, 13]]},
    "Y": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [5, 6],
                    [6, 7], [7, 8], [8, 9], [8, 10], [10, 11], [5, 11]]},
    "V": {"bonds": [[0, 1], [1, 2], [2, 3], [1, 4], [4, 5], [4, 6]]},
    "_": {"bonds": []},
}


def _build_atom_ids() -> dict:
    """Token id per atom name (reference utils.py:108-116): sorted unique set
    of backbone + sidechain names plus the empty-slot token ''."""
    names = {"", "N", "CA", "C", "O"}
    for atoms in SIDECHAIN_ATOMS.values():
        names.update(atoms)
    return {name: i for i, name in enumerate(sorted(names))}


ATOM_IDS = _build_atom_ids()
NUM_ATOM_TOKENS = len(ATOM_IDS)


def _cloud_mask(aa: str) -> np.ndarray:
    """Occupied atom slots of the 14-slot layout (reference utils.py:118-127)."""
    mask = np.zeros(NUM_COORDS_PER_RES, dtype=np.float32)
    if aa == "_":
        return mask
    n_atoms = 4 + len(SIDECHAIN_ATOMS[ONE_TO_THREE[aa]])
    mask[:n_atoms] = 1
    return mask


def _atom_id_embedds(aa: str) -> np.ndarray:
    """Atom-token id per slot (reference utils.py:129-139)."""
    ids = np.zeros(NUM_COORDS_PER_RES, dtype=np.int32)
    if aa == "_":
        return ids
    atoms = BACKBONE_ATOMS + SIDECHAIN_ATOMS[ONE_TO_THREE[aa]]
    for i, atom in enumerate(atoms):
        ids[i] = ATOM_IDS[atom]
    return ids


CUSTOM_INFO = {
    aa: {"cloud_mask": _cloud_mask(aa), "atom_id_embedd": _atom_id_embedds(aa)}
    for aa in AA_ALPHABET
}

# Dense (21, 14) lookup tables indexed by token id — TPU-friendly gathers
# instead of per-residue Python dict lookups.
CLOUD_MASK_TABLE = np.stack(
    [CUSTOM_INFO[aa]["cloud_mask"] for aa in AA_ALPHABET]
)
ATOM_ID_TABLE = np.stack(
    [CUSTOM_INFO[aa]["atom_id_embedd"] for aa in AA_ALPHABET]
)

# Dense bond-adjacency lookup: (21, 14, 14) symmetric 0/1 per residue type.
def _bond_adjacency() -> np.ndarray:
    adj = np.zeros((len(AA_ALPHABET), NUM_COORDS_PER_RES, NUM_COORDS_PER_RES),
                   dtype=np.float32)
    for idx, aa in enumerate(AA_ALPHABET):
        for i, j in AA_DATA[aa]["bonds"]:
            adj[idx, i, j] = 1.0
            adj[idx, j, i] = 1.0
    return adj


BOND_ADJACENCY_TABLE = _bond_adjacency()
