"""In-process fleet: N schedulers wired into one logical server.

This is the fleet's executable spec — the loadtest's `--replicas`,
tools/serve_smoke.sh phase 4, and tests/test_fleet.py all run it. Each
replica is a full serving stack (FoldExecutor + FoldCache +
PeerCacheServer on 127.0.0.1 + ConsistentHashRouter + Scheduler),
sharing only the ReplicaRegistry and its RolloutState; forwarding uses
a `fleet.rpc.LocalTransport` over each peer Scheduler's bound `submit`
(same thread, same ticket — the pre-transport behavior behind the new
seam), peer cache fetches go over real localhost HTTP. A networked
deployment replaces exactly two things — the transport (`HttpTransport`
against each replica's `FrontDoorServer`; `fleet/procfleet.py` is the
executable spec) and how the registry is fed — and nothing in serve/,
cache/, or fleet/ routing changes.

Rollout: `bump_model_tag(tag)` flips the fleet's RolloutState, whose
subscriber re-tags every scheduler before bump() returns — subsequent
submits key under the new tag (old entries unreachable), and the peer
protocol 409s any straggler still fetching under the old tag.

`fleet=False` builds the same replicas UNWIRED (no router, no peer
tier): the two-independent-replicas baseline a fleet run is measured
against.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, List, Optional

from alphafold2_tpu.cache import FoldCache
from alphafold2_tpu.fleet.peer import PeerCacheClient, PeerCacheServer
from alphafold2_tpu.fleet.registry import ReplicaRegistry
from alphafold2_tpu.fleet.router import ConsistentHashRouter
from alphafold2_tpu.fleet.rpc import LocalTransport
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve.bucketing import BucketPolicy
from alphafold2_tpu.serve.metrics import ServeMetrics
from alphafold2_tpu.serve.scheduler import Scheduler, SchedulerConfig


class FleetReplica:
    """One member's full stack, as built by InProcessFleet."""

    def __init__(self, replica_id: str, scheduler: Scheduler,
                 cache: Optional[FoldCache],
                 peer_server: Optional[PeerCacheServer],
                 router: Optional[ConsistentHashRouter]):
        self.replica_id = replica_id
        self.scheduler = scheduler
        self.cache = cache
        self.peer_server = peer_server
        self.router = router


class InProcessFleet:
    """N in-process replicas behind one registry; context-manageable.

    make_executor: factory called once per replica (each replica owns
        its compiled-executable cache, as separate processes would).
    cache_kwargs: forwarded to each replica's FoldCache (tiering knobs;
        `peer`/`registry` are wired here). cache_kwargs=None still
        builds a FoldCache per replica — a fleet without result caching
        has nothing to share.
    fleet: False builds the independent-replicas baseline (no router,
        no peer tier, registry still tracks members for bookkeeping).
    metrics_factory: per-replica ServeMetrics factory (index -> metrics),
        e.g. distinct JSONL paths; None = in-memory defaults.
    retry: optional serve.resilience.RetryPolicy applied to EVERY
        replica's scheduler (failure-domain hardening; off when None).
    faults: optional serve.faults.FaultPlan threaded into every
        replica's FoldCache and PeerCacheClient (chaos harness; the
        executor side is the caller's to wire via make_executor).
    recycle_policy: optional serve.recycle.RecyclePolicy applied to
        EVERY replica's scheduler (step-mode recycle scheduling:
        early-exit, preemption, progressive results; off when None).
    feature_pool_factory: optional per-replica serve.FeaturePool
        factory (index -> FeaturePool or None) enabling the two-stage
        feature pipeline (ISSUE 10): raw jobs submitted via
        `submit_raw` route by FEATURE key to their ring owner, which
        featurizes replica-side (each replica owns its pool + feature
        cache, as separate processes would). Off when None.
    mesh_policy_factory: optional per-replica serve.MeshPolicy factory
        (index -> MeshPolicy or None) for mesh-aware replicas. A
        FACTORY, not a shared policy: in-process replicas share one
        device pool, so each needs its own policy/allocator over its
        own device subset (separate hosts in production own their
        chips outright). The mesh section then rides each replica's
        serve_stats()/health() through the fleet stats and /healthz
        passthrough unchanged.
    """

    def __init__(self, make_executor: Callable[[], object],
                 buckets: BucketPolicy,
                 config: Optional[SchedulerConfig] = None,
                 n_replicas: int = 2,
                 model_tag: str = "fleet",
                 cache_kwargs: Optional[dict] = None,
                 fleet: bool = True,
                 tracer=None,
                 metrics_factory: Optional[
                     Callable[[int], ServeMetrics]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 retry=None,
                 faults=None,
                 mesh_policy_factory: Optional[
                     Callable[[int], object]] = None,
                 recycle_policy=None,
                 feature_pool_factory: Optional[
                     Callable[[int], object]] = None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.fleet_enabled = bool(fleet)
        self.registry = ReplicaRegistry(model_tag=model_tag,
                                        registry=registry)
        self.replicas: List[FleetReplica] = []
        self._started = False
        self._lock = threading.Lock()
        self._rr = 0

        for i in range(n_replicas):
            rid = f"r{i}"
            kw = dict(cache_kwargs or {})
            if kw.get("disk_dir"):
                # each replica gets its own disk namespace (they are
                # separate hosts in production); shared-volume
                # deployments mount an ObjectStorePeer instead
                kw["disk_dir"] = os.path.join(kw["disk_dir"], rid)
            cache = FoldCache(registry=registry, faults=faults, **kw)
            peer_server = None
            if self.fleet_enabled:
                peer_server = PeerCacheServer(
                    cache, rollout=self.registry.rollout, replica_id=rid,
                    metrics=registry)
            self.registry.register(
                rid,
                peer_addr=peer_server.address if peer_server else None)
            router = None
            if self.fleet_enabled:
                router = ConsistentHashRouter(self.registry, rid,
                                              metrics=registry)
                cache.peer = PeerCacheClient(
                    self.registry, rid, router=router,
                    rollout=self.registry.rollout, metrics=registry,
                    faults=faults)
            # each replica gets its own policy copy with a per-replica
            # seed: identical jitter streams would make the fleet back
            # off in lockstep after a correlated transient episode,
            # defeating the thundering-herd protection
            rep_retry = (None if retry is None else
                         dataclasses.replace(retry,
                                             seed=retry.seed + i))
            if rep_retry is not None and rep_retry.checkpoint_spill:
                # per-replica spill namespace, same reasoning as the
                # cache disk_dir split above: replicas are separate
                # hosts in production, and cross-replica resume must
                # go over the peer wire, not through a shared path
                rep_retry = dataclasses.replace(
                    rep_retry,
                    checkpoint_spill=os.path.join(
                        rep_retry.checkpoint_spill, rid))
            scheduler = Scheduler(
                make_executor(), buckets, config,
                metrics=(metrics_factory(i) if metrics_factory else None),
                cache=cache, model_tag=model_tag, tracer=tracer,
                registry=registry, router=router, retry=rep_retry,
                mesh_policy=(mesh_policy_factory(i)
                             if mesh_policy_factory else None),
                recycle_policy=recycle_policy,
                feature_pool=(feature_pool_factory(i)
                              if feature_pool_factory else None))
            # the forwarding transport wraps the peer scheduler's
            # submit (LocalTransport — in-process, zero-copy); set
            # after construction so the registry row is complete
            # before any router can pick this owner. submit_raw rides
            # the same seam so feature-key routing can hand RAW jobs
            # to their owner for replica-side featurization
            info = self.registry.get(rid)
            info.transport = LocalTransport(scheduler.submit,
                                            scheduler.submit_raw)
            if peer_server is not None:
                # unified health: the peer probe payload carries the
                # same breaker/queue/drain truth the front door serves
                peer_server.health_source = scheduler.health
                # checkpoint artifact kind (ISSUE 18): spilled carries
                # become peer-fetchable, and this replica's resume
                # path can pull a dead peer's spill over the wire
                peer_server.checkpoint_source = \
                    scheduler.checkpoint_store
                if scheduler.checkpoint_store is not None:
                    scheduler.checkpoint_store.peer = cache.peer
                # served fetches emit continued trace records under
                # the requester's peer_fetch hop (ISSUE 15) — the
                # in-process harness shares the one tracer, so the
                # stitched pair lands in the same JSONL
                peer_server.tracer = tracer
            self.replicas.append(
                FleetReplica(rid, scheduler, cache, peer_server, router))

        # weight rollout re-tags every scheduler inside bump(): by the
        # time bump_model_tag returns, no submit keys under the old tag
        def _retag(tag: str, epoch: int):
            for replica in self.replicas:
                replica.scheduler.model_tag = tag

        self.registry.rollout.subscribe(_retag)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "InProcessFleet":
        if self._started:
            return self
        self._started = True
        for r in self.replicas:
            if r.peer_server is not None:
                r.peer_server.start()
            r.scheduler.start()
            self.registry.heartbeat(r.replica_id)
        return self

    def stop(self, drain: bool = True):
        for r in self.replicas:
            # feature pools first: their workers submit into the
            # schedulers, and a drained pool cannot race a stopping
            # queue
            pool = getattr(r.scheduler, "feature_pool", None)
            if pool is not None:
                pool.stop()
        for r in self.replicas:
            r.scheduler.stop(drain=drain)
        for r in self.replicas:
            if r.peer_server is not None:
                r.peer_server.stop()
        self._started = False

    def __enter__(self) -> "InProcessFleet":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- serving ---------------------------------------------------------

    def warmup(self) -> int:
        return sum(r.scheduler.warmup() for r in self.replicas)

    def submit(self, request, replica: Optional[int] = None):
        """Submit through one replica's front door (round-robin when
        `replica` is None — the dumb-load-balancer model the router is
        supposed to beat)."""
        if replica is None:
            with self._lock:
                replica = self._rr
                self._rr = (self._rr + 1) % len(self.replicas)
        return self.replicas[replica].scheduler.submit(request)

    def submit_raw(self, raw, replica: Optional[int] = None):
        """Submit one RAW job through one replica's front door (same
        round-robin model as submit). The receiving replica featurizes
        — or, with feature pools wired, routes the raw job by feature
        key to its ring owner first (ISSUE 10)."""
        if replica is None:
            with self._lock:
                replica = self._rr
                self._rr = (self._rr + 1) % len(self.replicas)
        return self.replicas[replica].scheduler.submit_raw(raw)

    # -- fleet ops -------------------------------------------------------

    def bump_model_tag(self, new_tag: str) -> int:
        """Weight rollout: returns the new model epoch."""
        return self.registry.rollout.bump(new_tag)

    def mark(self, replica_id: str, up: bool):
        self.registry.mark(replica_id, up)

    def stats(self) -> dict:
        per_replica = {r.replica_id: r.scheduler.serve_stats()
                       for r in self.replicas}
        agg = {"served": 0, "batches": 0, "cache_hits": 0,
               "coalesced": 0, "peer_hits": 0, "leader_promotions": 0}
        for snap in per_replica.values():
            agg["served"] += snap.get("served", 0)
            agg["batches"] += snap.get("batches", 0)
            cache = snap.get("cache", {})
            agg["cache_hits"] += cache.get("hits", 0)
            agg["coalesced"] += cache.get("coalesced", 0)
            store = cache.get("store", {})
            agg["peer_hits"] += store.get("peer_hits", 0)
            inflight = cache.get("inflight", {})
            agg["leader_promotions"] += inflight.get(
                "leader_promotions", 0)
        return {"fleet": self.registry.snapshot(),
                "aggregate": agg,
                "replicas": per_replica}
