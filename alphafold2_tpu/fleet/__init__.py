"""alphafold2_tpu.fleet — N replicas as one logical serving fleet.

The serve/cache layers made one process efficient (batching, result
cache, coalescing); this package makes N of them add up instead of
multiply: behind a dumb load balancer, every replica folds the Zipf
head independently — with the fleet tier, each fold_key has ONE owner
and one cached home. Pieces, each usable alone:

- registry:     ReplicaRegistry — membership + health + membership
                epochs; RolloutState — the fleet-wide (model_tag,
                epoch) that weight rollout bumps atomically
- router:       ConsistentHashRouter — fold_key -> owner replica over
                a vnode hash ring; one-hop bounded forwarding with
                local fallback (`Scheduler(router=...)`)
- peer:         PeerCacheClient/PeerCacheServer — npz-over-HTTP peer
                cache tier (`FoldCache(peer=client)`), stdlib only,
                same validation/quarantine trust model as the disk
                tier, rollout-tag checked at both ends
- object_store: ObjectStoreBackend/FilesystemObjectStore/
                ObjectStorePeer — the same peer tier over a shared
                volume instead of HTTP
- rpc:          LocalTransport/HttpTransport — the forwarding
                transport seam (FoldTicket semantics over a process
                boundary; failover marker, remote cancel)
- frontdoor:    FrontDoorServer — per-replica HTTP front door
                (submit/long-poll result/cancel/healthz/admin), the
                surface HttpTransport speaks
- local:        InProcessFleet — N fully-wired replicas in one process
                (the loadtest/smoke/test harness and the deployment's
                executable spec)
- procfleet:    ProcFleet/FleetClient — N REAL replica processes with
                crash/partition/drain chaos (serve_loadtest --procs,
                serve_smoke.sh phase 6)
- scaling:      ScalingPolicy + pure decision functions (decide_scale/
                decide_feature_workers/drain_target) — the control
                plane's brain, unit-testable without processes
- controlplane: FleetController — the reconcile loop that scales,
                rolls out, resizes, and warms the fleet from its own
                /metrics + /admin/stats scrapes (serve_loadtest
                --controller, serve_smoke.sh phase 15)

Everything is OFF by default: a Scheduler without `router=` and a
FoldCache without `peer=` behave exactly as before (README "Fleet
serving" / "Deployment", MIGRATING "Fleet").
"""

from alphafold2_tpu.fleet.controlplane import FleetController  # noqa: F401
from alphafold2_tpu.fleet.frontdoor import FrontDoorServer  # noqa: F401
from alphafold2_tpu.fleet.local import FleetReplica, InProcessFleet  # noqa: F401
from alphafold2_tpu.fleet.object_store import (FilesystemObjectStore,  # noqa: F401
                                               ObjectStoreBackend,
                                               ObjectStorePeer)
from alphafold2_tpu.fleet.peer import PeerCacheClient, PeerCacheServer  # noqa: F401
from alphafold2_tpu.fleet.registry import (ReplicaInfo, ReplicaRegistry,  # noqa: F401
                                           RolloutState)
from alphafold2_tpu.fleet.router import (ConsistentHashRouter,  # noqa: F401
                                         RouteDecision)
from alphafold2_tpu.fleet.rpc import (HttpTransport, LocalTransport,  # noqa: F401
                                      RPC_TRANSPORT_MARKER)
from alphafold2_tpu.fleet.scaling import (HOLD, SCALE_DOWN,  # noqa: F401
                                          SCALE_UP, ReplicaSignals,
                                          ScalingDecision, ScalingPolicy,
                                          decide_feature_workers,
                                          decide_scale, drain_target)
