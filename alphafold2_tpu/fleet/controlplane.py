"""Control-plane actuation: the fleet runs itself (ISSUE 16).

PR 15 gave the fleet a complete signal surface — per-replica `/metrics`
expositions, `/admin/stats` with windowed SLO burn rates, stitched
cross-replica traces. Every actuation, though, was still an operator
verb: spawn, drain, rollout, warm. `FleetController` closes the loop
with one reconcile cycle, run on a timer against any actuator that
duck-types the fleet verbs (`ProcFleet` does):

    observe   poll every endpoint's /healthz, /admin/stats, /metrics
    decide    fleet/scaling.py pure functions over the polled signals
    actuate   spawn / SIGTERM-drain replicas, POST /admin/resize,
              POST /admin/peers membership fan-out, /admin/rollout
              convergence, owner-routed cache warming

Per cycle, in order:

1. ENDPOINT WATCH — each endpoint the actuator lists is probed at
   /healthz; a running replica is registered (join) or heartbeated in
   the controller's own `ReplicaRegistry`; an endpoint that vanished is
   unregistered (leave). Both bump the membership epoch.
2. TTL SWEEP — `registry.sweep()` auto-downs wedged-but-listening
   members (fresh TCP accept, stale heartbeat) with an epoch bump, so
   rings rebuild around them (the ISSUE-16 registry satellite).
   With `orphan_store=` set (ISSUE 20), a dead replica — preemption
   notice seen on /healthz, endpoint gone, or TTL-swept — has its
   orphan manifest read from the shared checkpoint backend and its
   folds actively assigned to the least-loaded survivor via
   `POST /admin/adopt`, so adoption latency is reconcile-tick-bounded.
3. MEMBERSHIP FAN-OUT — joins/leaves/health flips are announced to
   every healthy replica's `POST /admin/peers`, so the DATA plane's
   per-replica registries (and therefore their consistent-hash rings)
   track runtime membership — a replica spawned mid-run starts
   receiving forwards; a swept one stops.
4. SIGNAL POLL — /admin/stats + /metrics per healthy member. The two
   must agree on identity (replica_id + incarnation boot nonce,
   mirrored between the stats "identity" block and the
   `fleet_replica_identity` series): a restarted replica's stale
   scrape is DISCARDED (neutral signals, `controller_stale_scrapes_
   total`), never acted on.
5. SCALE DECISION — `decide_scale` maps max latency burn rate, mean
   executor idle fraction, and featurize queue pressure to one of
   hold / scale_up / scale_down with hysteresis + cooldown + min/max
   bounds; scale-down drains the least-loaded replica (SIGTERM — the
   drain contract), never below quorum; a fleet observed below
   `min_replicas` is restored immediately (cooldown does not apply to
   outages).
6. FEATURE-POOL RESIZE — `decide_feature_workers` per replica, actuated
   through the new `POST /admin/resize` (in-place executor swap).
7. ROLLOUT CONVERGENCE — after `controller.rollout(tag)` (fan-out with
   per-replica retry/backoff), every cycle re-rolls stragglers and
   late joiners until the whole healthy fleet reports the tag — a
   replica spawned mid-rollout converges too.
8. TELEMETRY-DRIVEN WARMING — tails the replicas' served-key frequency
   JSONL (`Scheduler(key_log=)`) and submits the traffic head as
   low-priority folds; the data plane's own ring routing concentrates
   each key on its owner, so the warm lands exactly where forwards and
   peer fetches will look (the cache_warm contract, fed by live
   traffic instead of an offline Zipf profile).

Every cycle appends one structured record to a decisions JSONL
(`controller.decisions.jsonl`) — `tools/obs_fleet.py` renders it so a
run artifact explains WHY the fleet scaled — and runs under a
`reconcile` trace span on an origin-tagged tracer, so control-plane
latency sits in the same waterfall as the requests it shepherds.
`controller_*` counters/gauges ride the driver's registry.

Off by default everywhere: nothing constructs a controller unless
asked (`ProcFleet(controller=...)`), and a controller-less fleet is
byte-identical to PR 15.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Dict, List, Optional
from urllib import request as urlrequest

from alphafold2_tpu.fleet.registry import ReplicaRegistry
from alphafold2_tpu.fleet.scaling import (SCALE_DOWN, SCALE_UP,
                                          ReplicaSignals, ScalingPolicy,
                                          decide_feature_workers,
                                          decide_scale)
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry

# -- plumbing -------------------------------------------------------------


def http_get_json(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    try:
        with urlrequest.urlopen(url, timeout=timeout_s) as resp:
            if resp.status != 200:
                return None
            return json.loads(resp.read().decode("utf-8"))
    except Exception:
        return None


def http_get_text(url: str, timeout_s: float = 2.0) -> Optional[str]:
    try:
        with urlrequest.urlopen(url, timeout=timeout_s) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8")
    except Exception:
        return None


def http_post_json(url: str, payload: dict,
                   timeout_s: float = 2.0) -> Optional[dict]:
    req = urlrequest.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urlrequest.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:
        return None


def http_probe_json(url: str, timeout_s: float = 2.0):
    """(status, body-dict) even for error statuses — a preempting
    replica answers /healthz with a 503 whose BODY carries the state
    (ISSUE 20), and the plain getter above would collapse that to
    None. (None, None) on transport failure."""
    try:
        with urlrequest.urlopen(url, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except Exception as exc:
        code = getattr(exc, "code", None)
        if code is None:
            return None, None
        try:
            return code, json.loads(exc.read().decode("utf-8"))
        except Exception:
            return code, None


_SERIES_RE = re.compile(
    r"^fleet_replica_identity\{([^}]*)\}\s+([0-9eE.+-]+)\s*$",
    re.MULTILINE)
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_identity(metrics_text: str) -> Optional[dict]:
    """The CURRENT identity a /metrics exposition claims: the single
    fleet_replica_identity series at value 1. None when the exposition
    carries no current identity (or an ambiguous one — more than one
    series at 1 is treated as no identity, which a polling controller
    must read as 'do not act')."""
    current = [dict(_LABEL_RE.findall(labels))
               for labels, value in _SERIES_RE.findall(metrics_text)
               if float(value) == 1.0]
    return current[0] if len(current) == 1 else None


def content_digest(seq, msa=None) -> Optional[str]:
    """Stable digest of a (seq, msa) token payload — the controller's
    dedup key for warm submissions (same construction as
    serve.metrics.KeyFrequencyLog's aggregation key)."""
    import hashlib

    import numpy as np

    try:
        h = hashlib.blake2b(digest_size=16)
        h.update(np.asarray(seq).astype(np.int64, copy=False).tobytes())
        if msa is not None:
            h.update(b"|msa|")
            h.update(np.asarray(msa).astype(np.int64,
                                            copy=False).tobytes())
        return h.hexdigest()
    except Exception:
        return None


def merge_key_profiles(paths) -> List[dict]:
    """Merge served-key frequency JSONL files (KeyFrequencyLog format)
    into one profile, hottest first. Duplicate keys across replicas
    (each ingress counts its own arrivals) SUM — fleet-wide frequency
    is what warming should rank by. Unreadable/torn lines skip."""
    merged: Dict[str, dict] = {}
    for path in paths:
        try:
            with open(path) as fh:
                lines = fh.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                   # torn tail mid-rewrite
            digest = content_digest(rec.get("seq"), rec.get("msa"))
            if digest is None:
                continue
            ent = merged.get(digest)
            if ent is None:
                merged[digest] = {"digest": digest,
                                  "seq": rec["seq"],
                                  "msa": rec.get("msa"),
                                  "count": int(rec.get("count", 1))}
            else:
                ent["count"] += int(rec.get("count", 1))
    return sorted(merged.values(), key=lambda r: -r["count"])


# -- the controller -------------------------------------------------------

def terminal_fold_keys(ledger_paths=(), quarantine_paths=()):
    """Fold keys with a TERMINAL record — the sweep set for
    `CheckpointStore.sweep_orphans` (ISSUE 19).

    ledger_paths: bulk-campaign ledgers (tools/bulk_submit.py JSONL);
        a record contributes when it carries a `fold_key` AND its
        status is done-forever ("ok"/"poisoned"/"too_large" — the
        driver's own DONE set; retryable statuses keep their
        checkpoints, a resumed campaign wants them).
    quarantine_paths: Quarantine persistence JSONL ({"key", "reason"});
        every quarantined key is terminal by definition — its
        checkpoint would only resume into another poisoning.

    Unreadable files and torn lines are skipped: GC is best-effort and
    must never take down the reconcile loop over a disk error.
    """
    done = ("ok", "poisoned", "too_large")
    keys = set()
    for path in ledger_paths:
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    fk = rec.get("fold_key")
                    if fk and str(rec.get("status")) in done:
                        keys.add(str(fk))
        except OSError:
            continue
    for path in quarantine_paths:
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("key"):
                        keys.add(str(rec["key"]))
        except OSError:
            continue
    return keys


class CheckpointGC:
    """Reconcile-wired checkpoint GC (ISSUE 19): rate-limited
    `sweep_orphans` over the terminal fold keys the campaign ledgers
    and quarantine files record. TTL already bounds checkpoint
    lifetime; this reclaims the disk EARLY for folds that provably
    finished for good — a proteome campaign's served checkpoints must
    not sit out their TTL on every replica's spill volume.

    store: `cache.CheckpointStore` (anything with sweep_orphans).
    ledger_paths / quarantine_paths: JSONL sources (static paths or a
        zero-arg callable returning paths, for actuators whose
        replica set moves).
    interval_s: minimum seconds between sweeps — the reconcile loop
        runs ~1/s and re-reading ledgers that often buys nothing.
    """

    def __init__(self, store, ledger_paths=(), quarantine_paths=(),
                 interval_s: float = 60.0, clock=time.monotonic):
        if store is None or not hasattr(store, "sweep_orphans"):
            raise ValueError(
                "CheckpointGC.store must expose sweep_orphans()")
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.store = store
        self.ledger_paths = ledger_paths
        self.quarantine_paths = quarantine_paths
        self.interval_s = float(interval_s)
        self._clock = clock
        self._last: Optional[float] = None
        self.sweeps = 0
        self.swept_groups = 0

    def _paths(self, spec):
        return list(spec() if callable(spec) else spec)

    def run(self, now: Optional[float] = None) -> int:
        """One rate-limited sweep; returns groups swept (0 when the
        interval has not elapsed)."""
        now = self._clock() if now is None else now
        if self._last is not None and now - self._last < self.interval_s:
            return 0
        self._last = now
        keys = terminal_fold_keys(self._paths(self.ledger_paths),
                                  self._paths(self.quarantine_paths))
        if not keys:
            return 0
        swept = int(self.store.sweep_orphans(sorted(keys)))
        self.sweeps += 1
        self.swept_groups += swept
        return swept


class FleetController:
    """One reconcile loop over an actuator exposing the fleet verbs.

    fleet: the actuator. Required surface:
        endpoints() -> {replica_id: frontdoor_base_url}   (live procs)
        scale_up() -> Optional[replica_id]                (spawn)
        scale_down(replica_id) -> bool                    (async drain)
      Optional surface:
        peer_rows() -> [{replica_id, host, frontdoor_port, peer_port}]
            enables data-plane membership fan-out (/admin/peers)
        key_log_paths() -> {replica_id: keys.jsonl path}
            enables telemetry-driven warming (with warm=True)
    policy: fleet/scaling.py knobs (default ScalingPolicy()).
    heartbeat_timeout_s: the registry TTL behind sweep auto-down. Keep
        it a small multiple of interval_s — each cycle's successful
        /healthz probe IS the heartbeat.
    decisions_path: structured JSONL, one record per reconcile (and
        one per rollout verb); obs_fleet renders it. None = no log.
    decision_log_max_bytes / decision_log_max_age_s: decision-log
        retention (ISSUE 18). Off by default (0 / None — unbounded,
        the old behavior). When set, the JSONL rotates in place
        keeping the newest records under the byte bound and dropping
        records older than the age bound; the in-memory mirror trims
        by the same age. `tools/obs_fleet.py --since` narrows reads
        the same way.
    tracer: optional obs.Tracer — each cycle runs under a `reconcile`
        span so control-plane latency sits in the fleet waterfall.
    warm / warm_top_k / warm_min_count / warm_max_inflight: telemetry-
        driven warming of the served-traffic head (needs the actuator's
        key_log_paths and replicas running `Scheduler(key_log=)`).
        Warm folds ride `qos="bulk"` (ISSUE 19): on replicas with a
        BulkPolicy they park in the bulk queue and are admitted only
        through freed batch rows, so warming NEVER competes with
        online traffic; bulk-less replicas serve them on the online
        queue at priority -1, the old behavior.
    checkpoint_gc: optional `CheckpointGC` — each reconcile runs one
        rate-limited `CheckpointStore.sweep_orphans` pass over the
        fold keys the campaign ledgers / quarantine files record as
        terminal (ISSUE 19). None (default) = no GC, byte-identical
        reconcile records.
    orphan_store: optional shared `ObjectStoreBackend` (the one the
        replicas' CheckpointStores mirror into) — enables orphan
        adoption (ISSUE 20): dead replicas' manifests are read from
        it and assigned to survivors. None (default) = no adoption,
        byte-identical records and metric-name set.
    resize: feature-pool resize actuation on/off.
    boot_grace_s: how long a spawned-but-not-yet-joined endpoint
        counts as PENDING toward quorum and the max bound. A replica
        whose boot spans many reconcile intervals (executor warm-up)
        must not be re-spawned every cycle while it comes up; one
        whose boot hangs past the grace stops counting, so quorum
        restore can try again.
    clock: injectable monotonic clock (tests drive cooldowns without
        sleeping).
    """

    def __init__(self, fleet, policy: Optional[ScalingPolicy] = None,
                 interval_s: float = 1.0,
                 heartbeat_timeout_s: float = 5.0,
                 probe_timeout_s: float = 2.0,
                 decisions_path: Optional[str] = None,
                 tracer=None,
                 registry: Optional[MetricsRegistry] = None,
                 warm: bool = False, warm_top_k: int = 4,
                 warm_min_count: int = 2, warm_max_inflight: int = 4,
                 resize: bool = True,
                 rollout_attempts: int = 5,
                 rollout_backoff_s: float = 0.2,
                 boot_grace_s: float = 180.0,
                 decision_log_max_bytes: int = 0,
                 decision_log_max_age_s: Optional[float] = None,
                 checkpoint_gc: Optional[CheckpointGC] = None,
                 orphan_store=None,
                 clock=time.monotonic):
        self.fleet = fleet
        self.policy = policy or ScalingPolicy()
        self.interval_s = float(interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.decisions_path = decisions_path
        self.tracer = tracer
        self.warm = bool(warm)
        self.warm_top_k = int(warm_top_k)
        self.warm_min_count = int(warm_min_count)
        self.warm_max_inflight = int(warm_max_inflight)
        self.resize = bool(resize)
        self.rollout_attempts = int(rollout_attempts)
        self.rollout_backoff_s = float(rollout_backoff_s)
        self.boot_grace_s = float(boot_grace_s)
        self.checkpoint_gc = checkpoint_gc
        # orphan adoption (ISSUE 20): the shared ObjectStoreBackend the
        # replicas spill checkpoints + orphan manifests into. None
        # (default) = no adoption, byte-identical reconcile records and
        # registry metric-name set.
        self.orphan_store = orphan_store
        # decision-log retention (ISSUE 18): a controller that runs
        # for weeks appends one JSONL record per reconcile — unbounded
        # by default (byte-identical to PR 16/17 behavior). When
        # either bound is set, _log rotates the file in place (newest
        # records kept under max_bytes/2 so rotation is amortized, and
        # records older than max_age_s dropped) and trims the
        # in-memory mirror by the same age cutoff.
        self.decision_log_max_bytes = int(decision_log_max_bytes)
        self.decision_log_max_age_s = (
            None if decision_log_max_age_s is None
            else float(decision_log_max_age_s))
        self._clock = clock
        reg = registry or get_registry()
        # the controller's OWN membership view — sweep() needs the TTL
        # armed; replicas keep their mark-driven registries
        self.registry = ReplicaRegistry(
            heartbeat_timeout_s=float(heartbeat_timeout_s),
            clock=clock, registry=reg)
        self._m_reconciles = reg.counter(
            "controller_reconciles_total", "reconcile cycles run")
        self._m_scale_ups = reg.counter(
            "controller_scale_ups_total", "replicas spawned by policy")
        self._m_scale_downs = reg.counter(
            "controller_scale_downs_total", "replicas drained by policy")
        self._m_resizes = reg.counter(
            "controller_resizes_total",
            "feature-pool resizes actuated via /admin/resize")
        self._m_warms = reg.counter(
            "controller_warm_submissions_total",
            "warm folds submitted from served-traffic telemetry")
        self._m_stale = reg.counter(
            "controller_stale_scrapes_total",
            "polls discarded on identity mismatch "
            "(stats vs metrics incarnation)")
        self._m_joins = reg.counter(
            "controller_membership_joins_total",
            "replicas joined via the endpoint watch")
        self._m_leaves = reg.counter(
            "controller_membership_leaves_total",
            "replicas unregistered (endpoint gone)")
        self._m_healthy = reg.gauge(
            "controller_replicas_observed",
            "healthy replicas the controller last observed")
        self._m_stragglers = reg.gauge(
            "controller_rollout_stragglers",
            "healthy replicas not yet on the rollout target tag")
        # adoption series exist only with the knob on (identity pin:
        # a controller without an orphan store mints no new names)
        self._m_adoptions = None
        self._m_adopt_latency = None
        if orphan_store is not None:
            self._m_adoptions = reg.counter(
                "fleet_orphan_adoptions_total",
                "orphaned folds assigned to survivors by the "
                "controller, by detection source", ("source",))
            self._m_adopt_latency = reg.histogram(
                "fleet_orphan_adoption_seconds",
                "manifest publish -> survivor adoption latency")
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._n = 0                       # reconcile counter
        self._last_action_s: Optional[float] = None
        self._last_health: Dict[str, dict] = {}
        self._last_poll: Dict[tuple, dict] = {}   # (rid, inc) -> sample
        self._pending_since: Dict[str, float] = {}  # rid -> first seen
        self._announced_up: set = set()   # rids the data plane knows up
        # adoption state (ISSUE 20): rids whose /healthz announced
        # preempting (first-seen stamp -> source="notice"), and rids
        # whose death still owes an adoption attempt
        self._preempting_seen: Dict[str, float] = {}
        self._pending_adoptions: set = set()
        self._rollout_tag: Optional[str] = None
        self._warmed: set = set()
        self._warm_tickets: list = []
        self._transports: Dict[str, object] = {}
        self.decisions: List[dict] = []   # in-memory mirror of the log

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetController":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-controller", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.reconcile()
            except Exception as exc:      # the loop must never die
                self._log({"event": "reconcile_error",
                           "error": repr(exc)})
            self._stop.wait(self.interval_s)

    # -- the cycle ---------------------------------------------------------

    def reconcile(self) -> dict:
        """One observe-decide-actuate cycle; returns (and logs) its
        decision record. Safe to call inline (tests, one-shot CLIs)
        with the loop stopped."""
        self._n += 1
        self._m_reconciles.inc()
        trace = None
        if self.tracer is not None:
            trace = self.tracer.start_trace(f"reconcile-{self._n}")
            trace.begin("reconcile")
        try:
            record = self._reconcile_inner(trace)
        finally:
            if trace is not None:
                trace.end("reconcile")
                trace.finish("ok", source="controller")
        record["reconcile"] = self._n
        self._log(record)
        return record

    def _reconcile_inner(self, trace) -> dict:
        now = self._clock()
        endpoints = dict(self.fleet.endpoints())
        record: dict = {"event": "reconcile", "ts": time.time(),
                        "endpoints": sorted(endpoints)}

        # 1. endpoint watch: join / heartbeat / leave
        joined, health = [], {}
        known = set(self.registry.member_ids())
        for rid in sorted(endpoints):
            status, hz = http_probe_json(endpoints[rid] + "/healthz",
                                         self.probe_timeout_s)
            if hz is not None and hz.get("preempting"):
                # announced reclaim (ISSUE 20): the 503 body names the
                # state — remember WHEN, so the adoption that follows
                # this replica's death is source="notice" and the
                # manifest is read the tick it appears instead of
                # waiting out a TTL sweep
                self._preempting_seen.setdefault(rid, now)
                self._pending_adoptions.add(rid)
            if status != 200 or hz is None or not hz.get("running"):
                continue           # no heartbeat: the sweep judges it
            if rid not in known:
                self.registry.register(rid)
                self._m_joins.inc()
                joined.append(rid)
            self.registry.heartbeat(rid)   # revives auto-downed too
            health[rid] = hz
        left = sorted(known - set(endpoints))
        for rid in left:
            self.registry.unregister(rid)
            self._m_leaves.inc()
        self._last_health = health

        # pending = spawned endpoints that never joined (boot still in
        # flight). They hold further scaling: re-spawning every cycle
        # while one boot warms up is the runaway-restore failure mode.
        # A boot hung past the grace stops counting, so restore retries.
        known_now = set(self.registry.member_ids())
        for rid in list(self._pending_since):
            if rid in known_now or rid not in endpoints:
                del self._pending_since[rid]
        pending_ids = []
        for rid in sorted(set(endpoints) - known_now):
            first = self._pending_since.setdefault(rid, now)
            if now - first <= self.boot_grace_s:
                pending_ids.append(rid)

        # 2. TTL sweep: wedged-but-listening members go down WITH an
        # epoch bump — they stop owning keys, not just failing them
        swept = self.registry.sweep()

        # 2b. orphan adoption (ISSUE 20): a dead replica's manifest is
        # actively assigned to a least-loaded survivor THIS tick —
        # adoption latency is reconcile-bounded, never waiting on a
        # duplicate submit to stumble into a lazy peer probe
        adoptions: List[dict] = []
        if self.orphan_store is not None:
            self._pending_adoptions.update(left)
            self._pending_adoptions.update(swept)
            adoptions = self._adopt_orphans(endpoints, health)

        # 3. data-plane membership fan-out
        announced = self._announce_membership(endpoints, health)

        # 4. signal poll (identity-checked)
        signals, stale = self._poll_signals(endpoints, health)
        healthy_n = sum(1 for s in signals
                        if s.healthy and not s.draining)
        self._m_healthy.set(healthy_n)

        # 5. scale decision + actuation
        decision = decide_scale(self.policy, signals, now,
                                self._last_action_s,
                                pending=len(pending_ids))
        actions = []
        if decision.action == SCALE_UP:
            rid = None
            try:
                rid = self.fleet.scale_up()
            except Exception as exc:
                actions.append({"verb": "scale_up",
                                "error": repr(exc)})
            if rid is not None:
                self._m_scale_ups.inc()
                self._last_action_s = now
                actions.append({"verb": "scale_up", "replica": rid})
        elif decision.action == SCALE_DOWN:
            ok = False
            try:
                ok = bool(self.fleet.scale_down(decision.drain_target))
            except Exception as exc:
                actions.append({"verb": "scale_down",
                                "error": repr(exc)})
            if ok:
                self._m_scale_downs.inc()
                self._last_action_s = now
                actions.append({"verb": "scale_down",
                                "replica": decision.drain_target})

        # 6. feature-pool resize
        resized = self._actuate_resize(endpoints, signals) \
            if self.resize else {}

        # 7. rollout convergence: re-roll stragglers and late joiners
        stragglers = self._converge_rollout(endpoints, health)

        # 8. telemetry-driven warming
        warmed = self._warm_from_telemetry(endpoints, health) \
            if self.warm else 0

        # 9. checkpoint GC (ISSUE 19): reclaim spill disk for folds
        # the ledgers/quarantine prove finished for good
        gc_swept = 0
        if self.checkpoint_gc is not None:
            try:
                gc_swept = self.checkpoint_gc.run(now)
            except Exception as exc:
                # GC is best-effort; a disk error must not stop
                # scaling/rollout actuation
                record["checkpoint_gc_error"] = repr(exc)

        record.update({
            "joined": joined, "left": left, "swept": swept,
            "announced": announced,
            "healthy": healthy_n,
            "pending": pending_ids,
            "stale_scrapes": stale,
            "signals": [{"replica": s.replica_id,
                         "burn": round(s.burn_rate, 4),
                         "idle": round(s.idle_fraction, 4),
                         "queue": s.queue_depth,
                         "featurize_queue": s.featurize_queue_depth,
                         "draining": s.draining}
                        for s in signals],
            "decision": decision.to_dict(),
            "actions": actions,
            "resized": resized,
            "rollout_target": self._rollout_tag,
            "rollout_stragglers": stragglers,
            "warm_submissions": warmed,
        })
        if self.checkpoint_gc is not None:
            # only with the knob on: default reconcile records keep
            # their PR-18 shape
            record["checkpoint_gc_swept"] = gc_swept
        if self.orphan_store is not None:
            # only with the knob on, same contract as checkpoint_gc
            record["orphan_adoptions"] = adoptions
        return record

    # -- orphan adoption (ISSUE 20) ----------------------------------------

    def _adopt_orphans(self, endpoints, health) -> List[dict]:
        """Assign every pending dead replica's orphan manifest to a
        live survivor via POST /admin/adopt. A rid stays pending until
        its manifest is adopted (the manifest may publish a beat after
        the death is detected — the replica spends its grace window
        spilling first), or until the rid rejoins (a restart reclaims
        its own checkpoints through boot discovery)."""
        from alphafold2_tpu.cache.checkpoints import (clear_manifest,
                                                      read_manifest)
        out: List[dict] = []
        for rid in sorted(self._pending_adoptions):
            if rid in health:
                # back from the dead (restart): its own boot discovery
                # owns the checkpoints now
                self._pending_adoptions.discard(rid)
                self._preempting_seen.pop(rid, None)
                continue
            manifest = read_manifest(self.orphan_store, rid)
            if manifest is None:
                continue                # not published yet: retry
            orphans = manifest.get("orphans") or []
            source = ("notice" if rid in self._preempting_seen
                      else "sweep")
            if orphans:
                survivor = self._pick_survivor(endpoints, health, rid)
                if survivor is None:
                    continue            # no live member yet: retry
                resp = http_post_json(
                    endpoints[survivor] + "/admin/adopt",
                    {"replica_id": rid, "source": source,
                     "model_tag": manifest.get("model_tag", ""),
                     "published_s": manifest.get("published_s"),
                     "orphans": orphans},
                    self.probe_timeout_s)
                if resp is None:
                    continue            # survivor refused: retry
                adopted = int(resp.get("adopted", 0) or 0)
                if self._m_adoptions is not None and adopted:
                    self._m_adoptions.inc(adopted, source=source)
                if self._m_adopt_latency is not None:
                    try:
                        self._m_adopt_latency.observe(max(
                            0.0, time.time()
                            - float(manifest["published_s"])))
                    except (KeyError, TypeError, ValueError):
                        pass
                out.append({"replica": rid, "source": source,
                            "survivor": survivor,
                            "orphans": len(orphans),
                            "adopted": adopted})
            else:
                out.append({"replica": rid, "source": source,
                            "survivor": None, "orphans": 0,
                            "adopted": 0})
            clear_manifest(self.orphan_store, rid)
            self._pending_adoptions.discard(rid)
            self._preempting_seen.pop(rid, None)
        return out

    def _pick_survivor(self, endpoints, health,
                       dead_rid: str) -> Optional[str]:
        """Least-loaded live member to adopt onto: healthy in the
        controller's registry, responding, not draining/preempting —
        sorted by the health payload's queue depth (the same
        least-loaded notion scaling's drain-target pick uses), rid as
        the deterministic tiebreak."""
        candidates = []
        for rid in sorted(health):
            if rid == dead_rid or rid not in endpoints:
                continue
            hz = health[rid]
            if not self.registry.is_healthy(rid):
                continue
            if hz.get("draining") or hz.get("preempting"):
                continue
            candidates.append((int(hz.get("queue_depth", 0) or 0),
                               rid))
        if not candidates:
            return None
        return min(candidates)[1]

    # -- membership fan-out ------------------------------------------------

    def _peer_rows(self) -> Dict[str, dict]:
        rows = getattr(self.fleet, "peer_rows", None)
        if rows is None:
            return {}
        try:
            return {r["replica_id"]: r for r in rows()}
        except Exception:
            return {}

    def _announce_membership(self, endpoints, health) -> List[dict]:
        """Push membership deltas to every healthy replica's
        /admin/peers, so data-plane rings track runtime join/leave.
        Healthy-up set = members the controller's registry says are
        healthy right now; deltas vs the last announcement fan out as
        register+up / down verbs."""
        rows = self._peer_rows()
        if not rows:
            return []
        up_now = {rid for rid in self.registry.member_ids()
                  if self.registry.is_healthy(rid)}
        went_up = sorted(up_now - self._announced_up)
        went_down = sorted(self._announced_up - up_now)
        if not went_up and not went_down:
            return []
        out = []
        targets = [(rid, endpoints[rid]) for rid in sorted(health)
                   if rid in endpoints]
        for rid in went_up:
            row = rows.get(rid)
            if row is None:
                continue
            for target_rid, url in targets:
                if target_rid == rid:
                    continue
                resp = http_post_json(
                    url + "/admin/peers",
                    {"op": "register", "peer": row},
                    self.probe_timeout_s)
                if resp is not None:
                    http_post_json(url + "/admin/peers",
                                   {"op": "up",
                                    "peer": {"replica_id": rid}},
                                   self.probe_timeout_s)
            out.append({"op": "up", "replica": rid})
        for rid in went_down:
            for target_rid, url in targets:
                if target_rid == rid:
                    continue
                http_post_json(url + "/admin/peers",
                               {"op": "down",
                                "peer": {"replica_id": rid}},
                               self.probe_timeout_s)
            out.append({"op": "down", "replica": rid})
        self._announced_up = up_now
        return out

    # -- signal poll -------------------------------------------------------

    def _poll_signals(self, endpoints, health):
        """ReplicaSignals per healthy member. A replica whose stats and
        metrics disagree on identity (restart between the two reads, or
        a scrape of a different incarnation) contributes NEUTRAL
        signals — observed healthy, but never a reason to act."""
        signals, stale = [], 0
        for rid in sorted(health):
            url = endpoints.get(rid)
            hz = health[rid]
            s = ReplicaSignals(replica_id=rid,
                               healthy=self.registry.is_healthy(rid),
                               draining=bool(hz.get("draining")),
                               model_tag=str(hz.get("tag", "")),
                               idle_fraction=0.0)
            signals.append(s)
            if url is None or not s.healthy:
                continue
            stats = http_get_json(url + "/admin/stats",
                                  self.probe_timeout_s)
            mtext = http_get_text(url + "/metrics",
                                  self.probe_timeout_s)
            if stats is None:
                continue
            ident = stats.get("identity") or {}
            claimed = parse_identity(mtext) if mtext else None
            if (not ident or claimed is None
                    or ident.get("replica_id") != rid
                    or claimed.get("replica_id") != rid
                    or claimed.get("incarnation")
                    != ident.get("incarnation")):
                # stale scrape: a restarted replica's old incarnation
                # (or a torn poll across a restart) must never steer
                # scaling — neutral signals, counted, skipped
                stale += 1
                self._m_stale.inc()
                continue
            s.incarnation = str(ident.get("incarnation", ""))
            s.queue_depth = int(stats.get("queue_depth", 0) or 0)
            s.served = int(stats.get("served", 0) or 0)
            s.burn_rate = self._burn_from_stats(stats)
            s.idle_fraction = self._idle_fraction(
                rid, s.incarnation, stats)
            feat = stats.get("featurize") or {}
            s.featurize_queue_depth = int(feat.get("queue_depth", 0)
                                          or 0)
            s.featurize_workers = int(feat.get("workers", 1) or 1)
        return signals, stale

    @staticmethod
    def _burn_from_stats(stats: dict) -> float:
        """Max latency burn rate across the replica's SLO classes
        (0.0 when no SLO engine is attached — burn never fires)."""
        worst = 0.0
        classes = (stats.get("slo") or {}).get("classes") or {}
        for cls in classes.values():
            lat = cls.get("latency") or {}
            rate = lat.get("burn_rate")
            if rate is not None:
                try:
                    worst = max(worst, float(rate))
                except (TypeError, ValueError):
                    pass
        return worst

    def _idle_fraction(self, rid: str, incarnation: str,
                       stats: dict) -> float:
        """1 - (executor busy-seconds delta / wall delta) between this
        poll and the previous one OF THE SAME INCARNATION — a restart
        resets the busy counter, and differencing across it would
        read as instant idleness. First poll reads as busy (0.0):
        a replica must EARN a scale-down with an observed-idle window."""
        try:
            busy = float(stats.get("exec_busy_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0
        key = (rid, incarnation)
        now = self._clock()
        prev = self._last_poll.get(key)
        self._last_poll[key] = {"t": now, "busy": busy}
        if prev is None:
            return 0.0
        wall_dt = now - prev["t"]
        busy_dt = busy - prev["busy"]
        if wall_dt <= 0 or busy_dt < 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - busy_dt / wall_dt))

    # -- resize ------------------------------------------------------------

    def _actuate_resize(self, endpoints, signals) -> Dict[str, int]:
        out = {}
        for s in signals:
            if not s.healthy or s.draining or not s.incarnation:
                continue       # unpolled/stale replicas are never resized
            url = endpoints.get(s.replica_id)
            if url is None:
                continue
            want = decide_feature_workers(self.policy, s)
            if want is None:
                continue
            resp = http_post_json(url + "/admin/resize",
                                  {"workers": want},
                                  self.probe_timeout_s)
            if resp is not None and "workers" in resp:
                self._m_resizes.inc()
                out[s.replica_id] = int(resp["workers"])
        return out

    # -- rollout -----------------------------------------------------------

    def rollout(self, tag: str) -> dict:
        """Fleet-wide rollout as ONE verb: fan out /admin/rollout to
        every endpoint with per-replica retry/backoff, then check
        convergence (every healthy replica's /healthz reports the tag).
        Non-converged replicas come back as `stragglers` — and stay a
        standing goal: every subsequent reconcile re-rolls stragglers
        and late joiners until the fleet converges (a replica spawned
        mid-rollout, or down during it, is rolled when it appears)."""
        tag = str(tag)
        with self._lock:
            self._rollout_tag = tag
        endpoints = dict(self.fleet.endpoints())
        epochs: Dict[str, Optional[int]] = {}
        for rid in sorted(endpoints):
            resp = None
            for attempt in range(self.rollout_attempts):
                resp = http_post_json(endpoints[rid] + "/admin/rollout",
                                      {"tag": tag},
                                      self.probe_timeout_s)
                if resp is not None:
                    break
                time.sleep(self.rollout_backoff_s * (2 ** attempt))
            epochs[rid] = None if resp is None else resp.get("epoch")
        stragglers = []
        for rid in sorted(endpoints):
            hz = http_get_json(endpoints[rid] + "/healthz",
                               self.probe_timeout_s)
            if hz is None or hz.get("tag") != tag:
                stragglers.append(rid)
        self._m_stragglers.set(len(stragglers))
        report = {"event": "rollout", "ts": time.time(), "tag": tag,
                  "epochs": epochs, "stragglers": stragglers,
                  "converged": not stragglers}
        self._log(report)
        return report

    def _converge_rollout(self, endpoints, health) -> List[str]:
        with self._lock:
            tag = self._rollout_tag
        if tag is None:
            return []
        stragglers = [rid for rid in sorted(health)
                      if health[rid].get("tag") != tag]
        for rid in stragglers:
            url = endpoints.get(rid)
            if url is not None:
                http_post_json(url + "/admin/rollout", {"tag": tag},
                               self.probe_timeout_s)
        self._m_stragglers.set(len(stragglers))
        return stragglers

    # -- warming -----------------------------------------------------------

    def _warm_from_telemetry(self, endpoints, health) -> int:
        """Submit the served-traffic head as low-priority folds. Any
        healthy front door works as the entry point: the data plane's
        own consistent-hash forwarding lands each key on its ring
        owner, which is exactly where future forwards and peer-cache
        fetches will look (the cache_warm --fleet contract, driven by
        live telemetry)."""
        paths_fn = getattr(self.fleet, "key_log_paths", None)
        if paths_fn is None or not health:
            return 0
        self._warm_tickets = [t for t in self._warm_tickets
                              if not t.done()]
        budget = self.warm_max_inflight - len(self._warm_tickets)
        if budget <= 0:
            return 0
        try:
            profile = merge_key_profiles(paths_fn().values())
        except Exception:
            return 0
        entry_rid = sorted(health)[0]
        url = endpoints.get(entry_rid)
        if url is None:
            return 0
        transport = self._transport(url)
        submitted = 0
        for rec in profile[:self.warm_top_k]:
            if submitted >= budget:
                break
            if rec["count"] < self.warm_min_count:
                continue
            if rec["digest"] in self._warmed:
                continue
            try:
                import numpy as np

                from alphafold2_tpu.serve.request import FoldRequest
                req = FoldRequest(
                    seq=np.asarray(rec["seq"], np.int32),
                    msa=(None if rec.get("msa") is None
                         else np.asarray(rec["msa"], np.int32)),
                    request_id=f"warm-{rec['digest'][:12]}",
                    priority=-1,       # traffic always outranks warming
                    # bulk tier (ISSUE 19): on a BulkPolicy replica a
                    # warm fold is admitted only through freed rows;
                    # without one it rides online at priority -1 as
                    # before — either way warming never preempts
                    qos="bulk")
                ticket = transport.submit(req)
            except Exception:
                continue               # warm is best-effort by definition
            self._warmed.add(rec["digest"])
            self._warm_tickets.append(ticket)
            self._m_warms.inc()
            submitted += 1
        return submitted

    def _transport(self, url: str):
        t = self._transports.get(url)
        if t is None:
            from alphafold2_tpu.fleet.rpc import HttpTransport
            t = HttpTransport(url, poll_budget_s=120.0)
            self._transports[url] = t
        return t

    # -- decision log ------------------------------------------------------

    def _log(self, record: dict):
        record.setdefault("ts", time.time())
        with self._lock:
            self.decisions.append(record)
            if self.decision_log_max_age_s is not None:
                # trim the in-memory mirror by the same age contract
                # as the file — snapshot() math stays over the
                # retained window, not the process lifetime
                cutoff = record["ts"] - self.decision_log_max_age_s
                while self.decisions and \
                        float(self.decisions[0].get("ts", 0)) < cutoff:
                    self.decisions.pop(0)
        if not self.decisions_path:
            return
        try:
            d = os.path.dirname(os.path.abspath(self.decisions_path))
            os.makedirs(d, exist_ok=True)
            with open(self.decisions_path, "a") as fh:
                fh.write(json.dumps(record, default=str) + "\n")
            self._maybe_rotate_log(float(record["ts"]))
        except OSError:
            pass               # the log must never break the loop

    def _maybe_rotate_log(self, now_ts: float):
        """Retention for the decision JSONL: when the file outgrows
        `decision_log_max_bytes` (or, age-only configs, once per
        max_age_s/4), rewrite it atomically keeping the NEWEST records
        — age cutoff first, then newest-first bytes down to half the
        byte bound so a rotation buys headroom instead of running
        every append. Torn lines are dropped (the rewrite is also the
        repair). OSError propagates to _log's swallow."""
        max_b = self.decision_log_max_bytes
        max_age = self.decision_log_max_age_s
        if max_b <= 0 and max_age is None:
            return
        path = self.decisions_path
        due = False
        if max_b > 0 and os.path.getsize(path) > max_b:
            due = True
        if not due and max_age is not None:
            last = getattr(self, "_last_age_rotate", 0.0)
            if now_ts - last >= max_age / 4.0:
                self._last_age_rotate = now_ts
                due = True
        if not due:
            return
        with open(path) as fh:
            lines = fh.readlines()
        kept = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if max_age is not None and \
                    float(rec.get("ts", 0)) < now_ts - max_age:
                continue
            kept.append(line + "\n")
        if max_b > 0:
            budget, tail = max_b // 2, []
            for line in reversed(kept):
                budget -= len(line)
                if budget < 0 and tail:
                    break
                tail.append(line)
            kept = list(reversed(tail))
        tmp = path + ".rotate"
        with open(tmp, "w") as fh:
            fh.writelines(kept)
        os.replace(tmp, path)

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            decisions = list(self.decisions)
        actions = [a for d in decisions
                   for a in d.get("actions", [])]
        out = {
            "reconciles": self._n,
            "registry": self.registry.snapshot(),
            "scale_ups": sum(1 for a in actions
                             if a.get("verb") == "scale_up"
                             and "replica" in a),
            "scale_downs": sum(1 for a in actions
                               if a.get("verb") == "scale_down"
                               and "replica" in a),
            "rollout_target": self._rollout_tag,
            "warmed": len(self._warmed),
            "decisions": len(decisions),
        }
        if self.orphan_store is not None:
            # adoption summary (ISSUE 20) — key exists only with the
            # knob on, same identity contract as the metric series
            ads = [a for d in decisions
                   for a in d.get("orphan_adoptions", ())]
            by_source: Dict[str, int] = {}
            for a in ads:
                src = str(a.get("source", "?"))
                by_source[src] = (by_source.get(src, 0)
                                  + int(a.get("adopted", 0) or 0))
            out["orphan_adoptions"] = {
                "events": len(ads),
                "adopted": sum(int(a.get("adopted", 0) or 0)
                               for a in ads),
                "by_source": by_source}
        return out
