"""Pluggable object-store backend: one logical store on shared media.

The HTTP peer tier (fleet/peer.py) is the zero-infrastructure path —
replicas serve each other directly. Deployments that already have a
shared medium (an NFS/Filestore volume mounted on every pod, a FUSE-
mounted bucket) instead want every replica reading and writing ONE
namespace; `ObjectStoreBackend` is that seam. It moves opaque bytes by
key and knows nothing about folds; `ObjectStorePeer` adapts a backend
to the `FoldCache(peer=)` tier interface, applying the same
`encode_fold`/`decode_fold` codec and validation the disk and HTTP
tiers use, so a corrupt shared object degrades to a miss (and is
deleted — the shared-store analogue of quarantine) rather than an
outage.

`FilesystemObjectStore` is the bundled implementation: same
2-hex-char fan-out and atomic tmp+rename writes as the FoldCache disk
tier, safe for many concurrent writers on one volume. A cloud-bucket
implementation is the same four methods over an SDK; nothing else in
the fleet changes.

Rollout note: keys embed `model_tag` (cache/keys.py), so after an
epoch bump the old tag's objects are unreachable garbage, not hazards;
`ObjectStorePeer` needs no tag check of its own. Run a sweeper over
old fan-out dirs at leisure.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from alphafold2_tpu.cache.store import (CachedFold, decode_fold,
                                        encode_fold)
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE


class ObjectStoreBackend:
    """Opaque bytes by key. Implementations must make `put` atomic
    (readers see the old object or the new one, never a torn write)
    and `get`/`delete` of a missing key quiet (None / no-op)."""

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: str, data: bytes):
        raise NotImplementedError

    def delete(self, key: str):
        raise NotImplementedError

    def __len__(self) -> int:          # optional; tooling/report sugar
        raise NotImplementedError


class FilesystemObjectStore(ObjectStoreBackend):
    """Shared-volume backend: one file per key under `root`."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key[:2], f"{key}.npz")

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as fh:
                return fh.read()
        except OSError:
            return None

    def put(self, key: str, data: bytes):
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)          # atomic on one filesystem

    def delete(self, key: str):
        try:
            os.remove(self._path(key))
        except OSError:
            pass

    def __len__(self) -> int:
        n = 0
        for _, _, files in os.walk(self.root):
            n += sum(1 for f in files if f.endswith(".npz"))
        return n


class ObjectStorePeer:
    """`FoldCache(peer=...)` tier over an ObjectStoreBackend.

    Supports `put` as well as `get`, so `FoldCache(...,
    peer_write_through=True)` makes every replica's folds land in the
    shared store — the whole fleet reads one namespace with no peer
    servers at all. Backend exceptions degrade to misses / dropped
    writes (counted), matching every other tier's failure model.
    """

    def __init__(self, backend: ObjectStoreBackend,
                 metrics: Optional[MetricsRegistry] = None):
        self.backend = backend
        self._m_ops = (metrics or get_registry()).counter(
            "fleet_object_store_ops_total",
            "object-store tier operations by outcome", ("op", "outcome"))

    def get(self, key: str, trace=NULL_TRACE) -> Optional[CachedFold]:
        try:
            data = self.backend.get(key)
        except Exception:
            self._m_ops.inc(op="get", outcome="error")
            return None
        if data is None:
            self._m_ops.inc(op="get", outcome="miss")
            return None
        try:
            value = decode_fold(key, data)
        except Exception:
            # shared-store quarantine: a corrupt object would cost every
            # replica a failed parse per miss until someone removes it
            try:
                self.backend.delete(key)
            except Exception:
                pass
            self._m_ops.inc(op="get", outcome="corrupt")
            trace.event("peer_fetch", peer="object_store",
                        outcome="corrupt")
            return None
        self._m_ops.inc(op="get", outcome="hit")
        trace.event("peer_fetch", peer="object_store", outcome="hit")
        return value

    def put(self, key: str, value: CachedFold):
        try:
            self.backend.put(key, encode_fold(key, value))
            self._m_ops.inc(op="put", outcome="ok")
        except Exception:
            self._m_ops.inc(op="put", outcome="error")
