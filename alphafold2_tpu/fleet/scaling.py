"""Pure scaling decisions for the fleet controller (ISSUE 16).

This module is the control plane's BRAIN with everything operational
amputated: no threads, no sockets, no clocks it didn't get handed.
`decide_scale(policy, signals, now, last_action_s)` is a pure function
from observed fleet state to one `ScalingDecision`, which makes every
policy property a unit test instead of a soak test — burn-rate
scale-up, idle scale-down, the hysteresis band between them, cooldown,
min/max bounds, quorum, least-loaded drain-target selection.

The signal vocabulary is exactly what PR 15 already exports per
replica (`/admin/stats` + `/metrics`): the SLO engine's
`slo_latency_burn_rate` (how fast the latency error budget burns, 1.0
= exactly at budget), the executor's busy-seconds counter (differenced
into an idle fraction by the poller), and the featurize queue depth.
The controller (fleet/controlplane.py) does the polling and the
actuation; this module only ever decides.

Hysteresis is the load-bearing design point: scale-up triggers above
`up_burn_rate`, scale-down requires BOTH idleness above
`down_idle_fraction` AND burn below `down_burn_rate` — the dead band
between the two burn thresholds absorbs oscillating input so a fleet
hovering near its SLO neither flaps up/down nor thrashes the ring.
`cooldown_s` serializes actions in time on top of that: one actuation,
then silence until its effect has had time to land in the signals.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

HOLD = "hold"
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"


@dataclass(frozen=True)
class ScalingPolicy:
    """Knobs for `decide_scale` / `decide_feature_workers`.

    min_replicas is BOTH the floor and the quorum: a scale-down that
    would leave fewer healthy members than this is refused, and a
    fleet observed below it is scaled up regardless of burn (a kill -9
    victim is replaced because membership dropped, not because latency
    already degraded).
    """

    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up when any replica's latency burn exceeds this ...
    up_burn_rate: float = 1.0
    # ... or its featurize queue backs up past this many per worker
    up_queue_per_worker: float = 4.0
    # scale-down only when the fleet is this idle AND burn is below
    # down_burn_rate (the hysteresis dead band lives between
    # down_burn_rate and up_burn_rate)
    down_idle_fraction: float = 0.80
    down_burn_rate: float = 0.5
    cooldown_s: float = 30.0
    # feature-pool resize band: desired workers = ceil(queue/target),
    # resized only when outside [min, max] clamp and != current
    feature_workers_min: int = 1
    feature_workers_max: int = 8
    feature_queue_per_worker: float = 2.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas < min_replicas")
        if self.down_burn_rate > self.up_burn_rate:
            raise ValueError(
                "down_burn_rate must not exceed up_burn_rate "
                "(the hysteresis band would be inverted)")
        if self.feature_workers_max < self.feature_workers_min:
            raise ValueError("feature_workers_max < feature_workers_min")


@dataclass
class ReplicaSignals:
    """One replica's observed state, as the controller polled it."""

    replica_id: str
    healthy: bool = True
    draining: bool = False
    # announced spot reclaim (ISSUE 20): the replica is draining under
    # a grace deadline and WILL die — its burn contribution is the
    # reclaim's fault, not organic load growth
    preempting: bool = False
    queue_depth: int = 0
    served: int = 0
    burn_rate: float = 0.0        # max latency burn across SLO classes
    idle_fraction: float = 1.0    # 1 - busy-seconds delta / wall delta
    featurize_queue_depth: int = 0
    featurize_workers: int = 1
    model_tag: str = ""
    incarnation: str = ""


@dataclass
class ScalingDecision:
    action: str = HOLD            # HOLD | SCALE_UP | SCALE_DOWN
    reason: str = ""
    drain_target: Optional[str] = None   # set when action == SCALE_DOWN
    # observed inputs the decision was made from, for the JSONL log
    healthy: int = 0
    pending: int = 0              # spawned, alive, not yet joined
    fleet_burn: float = 0.0
    fleet_idle: float = 0.0

    def to_dict(self) -> dict:
        return {"action": self.action, "reason": self.reason,
                "drain_target": self.drain_target,
                "healthy": self.healthy,
                "pending": self.pending,
                "fleet_burn": round(self.fleet_burn, 4),
                "fleet_idle": round(self.fleet_idle, 4)}


def _load(s: ReplicaSignals) -> tuple:
    """Sort key for drain-target selection: least loaded first.
    Queue depth dominates (work not yet started is work another
    replica can absorb), then in-flight featurize backlog, then
    lifetime served as the tiebreak toward draining the youngest,
    then id for determinism."""
    return (s.queue_depth, s.featurize_queue_depth, s.served,
            s.replica_id)


def drain_target(signals: Sequence[ReplicaSignals]) -> Optional[str]:
    """Pick the replica to drain on scale-down: the least-loaded
    healthy, non-draining member (its queue is the cheapest to let
    empty; its ring share redistributes with the least displaced
    in-flight work). None when no member is eligible."""
    eligible = [s for s in signals if s.healthy and not s.draining]
    if not eligible:
        return None
    return min(eligible, key=_load).replica_id


def decide_scale(policy: ScalingPolicy,
                 signals: Sequence[ReplicaSignals],
                 now: float,
                 last_action_s: Optional[float] = None,
                 pending: int = 0
                 ) -> ScalingDecision:
    """One reconcile round's verdict. Pure: same inputs, same output.

    Precedence: quorum restore (membership below min) beats cooldown —
    a killed replica is replaced immediately, not after the cooldown
    from the controller's own last scale-down. Everything else
    (burn/queue scale-up, idle scale-down) honors the cooldown.

    pending: replicas spawned but not yet serving (endpoint up, never
    joined). They count toward quorum and the max bound — a replica
    whose boot takes many reconcile intervals must not be re-spawned
    every cycle while it warms up (the runaway-restore bug) — and any
    nonzero pending holds tuning actions entirely: the fleet is
    mid-change, and acting again before the spawn lands would
    double-provision (up) or fight the provisioning (down).
    """
    healthy = [s for s in signals if s.healthy and not s.draining]
    n = len(healthy)
    pending = max(0, int(pending))
    fleet_burn = max((s.burn_rate for s in healthy), default=0.0)
    if not math.isfinite(fleet_burn):
        fleet_burn = policy.up_burn_rate + 1.0   # inf burn = way over
    fleet_idle = (sum(s.idle_fraction for s in healthy) / n
                  if n else 0.0)
    d = ScalingDecision(healthy=n, pending=pending,
                        fleet_burn=fleet_burn, fleet_idle=fleet_idle)

    # quorum restore: below the floor is an outage, not a tuning call
    if n + pending < policy.min_replicas:
        d.action = SCALE_UP
        d.reason = (f"healthy {n} + pending {pending} < min_replicas "
                    f"{policy.min_replicas} (quorum restore)")
        return d

    in_cooldown = (last_action_s is not None
                   and now - last_action_s < policy.cooldown_s)
    if in_cooldown:
        d.reason = (f"cooldown ({now - last_action_s:.1f}s < "
                    f"{policy.cooldown_s:.1f}s since last action)")
        return d
    if pending:
        d.reason = (f"{pending} spawn(s) pending: waiting for the "
                    f"fleet to settle before tuning")
        return d

    # scale-up: SLO burn or featurize backlog, bounded by max
    queue_pressure = max(
        (s.featurize_queue_depth / max(1, s.featurize_workers)
         for s in healthy), default=0.0)
    if fleet_burn > policy.up_burn_rate:
        if any(getattr(s, "preempting", False) for s in signals):
            # announced reclaim in progress (ISSUE 20): the survivors'
            # burn spike is the preemption window's fault — the failover
            # wave plus the reclaimed member's lost capacity — and
            # quorum restore (above, cooldown-exempt) already replaces
            # the member once it is gone. Scaling up on this burn too
            # would double-provision, then flap back down.
            d.reason = (f"burn {fleet_burn:.2f} > "
                        f"{policy.up_burn_rate:.2f} but attributable "
                        f"to an announced preemption window: "
                        f"suppressed (quorum restore replaces the "
                        f"reclaimed member)")
            return d
        if n >= policy.max_replicas:
            d.reason = (f"burn {fleet_burn:.2f} > "
                        f"{policy.up_burn_rate:.2f} but at "
                        f"max_replicas {policy.max_replicas}")
            return d
        d.action = SCALE_UP
        d.reason = (f"burn {fleet_burn:.2f} > "
                    f"up_burn_rate {policy.up_burn_rate:.2f}")
        return d
    if queue_pressure > policy.up_queue_per_worker:
        if n >= policy.max_replicas:
            d.reason = (f"featurize queue {queue_pressure:.1f}/worker "
                        f"but at max_replicas {policy.max_replicas}")
            return d
        d.action = SCALE_UP
        d.reason = (f"featurize queue {queue_pressure:.1f}/worker > "
                    f"{policy.up_queue_per_worker:.1f}")
        return d

    # scale-down: requires idle AND burn safely below the band
    if (fleet_idle > policy.down_idle_fraction
            and fleet_burn < policy.down_burn_rate):
        if n <= policy.min_replicas:
            d.reason = (f"idle {fleet_idle:.2f} but at min_replicas "
                        f"{policy.min_replicas}")
            return d
        target = drain_target(healthy)
        if target is None:
            d.reason = "idle but no drainable target"
            return d
        d.action = SCALE_DOWN
        d.drain_target = target
        d.reason = (f"idle {fleet_idle:.2f} > "
                    f"{policy.down_idle_fraction:.2f} and burn "
                    f"{fleet_burn:.2f} < {policy.down_burn_rate:.2f}")
        return d

    d.reason = (f"in band (burn {fleet_burn:.2f}, "
                f"idle {fleet_idle:.2f})")
    return d


def decide_feature_workers(policy: ScalingPolicy,
                           s: ReplicaSignals) -> Optional[int]:
    """Desired FeaturePool worker count for one replica, or None to
    leave it alone. Sized so the queue drains at
    `feature_queue_per_worker` items per worker, clamped to the
    policy's bounds; a one-worker hysteresis margin on the way DOWN
    keeps a queue hovering at a worker boundary from resizing every
    poll (growing is immediate — backlog is latency)."""
    want = max(policy.feature_workers_min,
               min(policy.feature_workers_max,
                   math.ceil(s.featurize_queue_depth
                             / max(1e-9, policy.feature_queue_per_worker))
                   if s.featurize_queue_depth > 0
                   else policy.feature_workers_min))
    cur = max(1, s.featurize_workers)
    if want > cur:
        return want
    if want < cur - 1:            # shrink only past the hysteresis margin
        return want
    return None
