"""Per-replica HTTP front door: full FoldTicket semantics over the wire.

One `FrontDoorServer` fronts one `Scheduler` (stdlib
ThreadingHTTPServer — the same zero-dependency trust model as the peer
cache tier in `fleet/peer.py`). The protocol is deliberately tiny:

    POST /v1/submit              npz body (seq[, msa]) + QoS headers
                                 -> 200 {"ticket": id}
                                 -> 409 model-tag mismatch
                                 -> 429 queue full     (retry elsewhere)
                                 -> 503 draining/stopped/partitioned
    GET  /v1/result/<id>?wait_s= long-poll; 200 npz + X-Status/X-Source/
                                 X-Attempts/X-Error when terminal
                                 (single pickup: the slot is freed),
                                 204 still in flight, 404 unknown.
                                 `&progress=1` opts into PROGRESSIVE
                                 results (step-mode scheduling,
                                 serve.recycle.RecyclePolicy(stream=
                                 True)): the long-poll returns 206 +
                                 the latest per-recycle coords/
                                 confidence npz with X-Recycle = its
                                 iteration index as soon as an update
                                 NEWER than `&after=<recycle>`
                                 (default -1) exists — poll again with
                                 after=<last X-Recycle> to stream; the
                                 slot stays parked and the terminal
                                 200 still follows
    POST /v1/cancel/<id>         best-effort: drop the parked slot
    GET  /healthz                the fleet's ONE health payload:
                                 replica, tag, epoch, breaker, queue
                                 depth, draining — the same shape the
                                 peer cache server serves, so the
                                 router's health walk and the recovery
                                 probe share one truth (a mesh-aware
                                 scheduler adds its device-slice
                                 occupancy under "mesh"; /admin/stats
                                 likewise carries serve_stats()["mesh"]
                                 — the passthrough needs no wiring here
                                 because both payloads come whole from
                                 the scheduler)
    GET  /metrics                Prometheus text exposition 0.0.4 of
                                 this process's MetricsRegistry
                                 (obs/export.py) — the scrape surface
                                 the SLO engine's slo_* gauges and
                                 every serve_*/fleet_* series ride;
                                 control-plane like /admin (served
                                 through an induced partition)
    POST /admin/rollout          {"tag": t} -> bump RolloutState
    GET  /admin/stats            serve_stats() as JSON, plus an
                                 "identity" block (replica_id /
                                 model_tag / incarnation boot nonce)
                                 mirrored by the /metrics
                                 fleet_replica_identity series — a
                                 controller cross-checks the two so a
                                 restarted replica's stale scrape is
                                 discarded, never acted on (ISSUE 16)
    POST /admin/resize           {"workers": n} -> resize the
                                 scheduler's FeaturePool in place
                                 (400 when no pool is attached)
    POST /admin/peers            {"op": register|unregister|up|down,
                                 "peer": {...}} -> runtime membership
                                 verb against this replica's registry
                                 (epoch-bumped ring rebuild); 400
                                 unless the owner wired `peer_admin`
    POST /admin/partition        {"duration_s": f} -> data-plane 503s
                                 for f seconds (chaos: an induced
                                 network partition as every caller
                                 experiences it; admin stays reachable)
    POST /admin/adopt            {"replica_id": dead, "source":
                                 "notice"|"sweep", "orphans": [...]}
                                 -> the fleet controller assigns a dead
                                 replica's orphaned folds to THIS
                                 replica (ISSUE 20); it pulls each
                                 orphan's spilled checkpoint and
                                 resumes mid-loop; 400 unless the
                                 owner wired `adopt_handler`

A replica that has received a preemption notice (ISSUE 20) reports
`"preempting": true` in /healthz (as a 503, so probes mark it down
immediately) and in the /v1/submit draining rejection body, so clients
fail over on the FIRST refusal instead of counting strikes.

Every terminal status travels verbatim — ok / shed / error / cancelled
/ degraded / poisoned, plus source cache/coalesced/forwarded — so a
remote caller sees exactly what an in-process caller would. Deadlines
and priorities propagate in headers and are re-anchored at the
receiving scheduler (the deadline clock restarts at the owner's
submit, matching the one-hop forwarding contract).

Parked results are TTL-bounded (`ticket_ttl_s`): a client that dies
between submit and pickup costs one slot for the TTL, never forever;
`/v1/cancel` (sent by `HttpTransport` when a forwarded ticket's
`result(timeout=)` expires) frees it early.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib import parse as urlparse

from alphafold2_tpu.fleet.rpc import (decode_raw_request, decode_request,
                                      encode_response, _HDR_TAG)
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import TraceContext


class _TicketSlot:
    """One submitted request's parked result."""

    __slots__ = ("ticket", "event", "response", "resolved_at",
                 "cancelled")

    def __init__(self, ticket):
        self.ticket = ticket
        self.event = threading.Event()
        self.response = None
        self.resolved_at = None      # set when the result parks
        self.cancelled = False


class FrontDoorServer:
    """Serve one Scheduler's submit/result surface over localhost HTTP.

    scheduler: the replica's `serve.Scheduler` (already started by the
        owner; this server never starts/stops it — except via `drain`
        wiring owned by the process, not the protocol).
    rollout: optional `fleet.RolloutState`; when set, submits carrying
        a different `X-Model-Tag` are refused 409 (the same rule the
        peer cache protocol enforces) and `/admin/rollout` bumps it.
    partition: optional `threading.Event`; while set, every data-plane
        request is refused 503 — the chaos harness's induced network
        partition. `/admin/partition` arms it on a timer. The same
        event can be shared with the replica's `PeerCacheServer` so a
        partition severs both planes at once.
    """

    def __init__(self, scheduler, rollout=None,
                 host: str = "127.0.0.1", port: int = 0,
                 replica_id: str = "", ticket_ttl_s: float = 300.0,
                 max_wait_s: float = 30.0,
                 partition: Optional[threading.Event] = None,
                 metrics: Optional[MetricsRegistry] = None):
        self.scheduler = scheduler
        self.rollout = rollout
        self.replica_id = replica_id
        self.ticket_ttl_s = float(ticket_ttl_s)
        self.max_wait_s = float(max_wait_s)
        self.partition = partition if partition is not None \
            else threading.Event()
        self._lock = threading.Lock()
        self._slots: dict = {}
        self._ticket_counter = [0]
        # boot nonce in every ticket id: a restarted replica reuses its
        # port, and without the nonce its counter would reissue the
        # dead process's ids — a pre-crash caller's stale poll could
        # then fetch (and mislabel) a NEW request's fold, and its
        # timed-out ticket's late cancel could drop one. With the
        # nonce both get a clean 404 -> transport-marker failover.
        self._boot_nonce = uuid.uuid4().hex[:8]
        self._partition_timer: Optional[threading.Timer] = None
        # optional zero-arg callable merged into /admin/stats under
        # "extra" — the owning process adds what the scheduler cannot
        # see (peer-client counters, front-door snapshot)
        self.extra_stats = None
        # optional zero-arg callable fired (best-effort) before each
        # GET /metrics render — the owning process refreshes gauges a
        # scrape should see fresh (the SLO engine's slo_* set, which
        # otherwise only update when serve_stats() runs)
        self.metrics_hook = None
        # optional callable(op, peer_dict) -> dict handling
        # POST /admin/peers (ISSUE 16 runtime membership): the owning
        # process registers/unregisters/marks peers in ITS registry so
        # a control plane can rebuild data-plane rings at runtime;
        # None = 400 (static-membership replicas take no peer verbs)
        self.peer_admin = None
        # optional callable(payload_dict) -> dict handling
        # POST /admin/adopt (ISSUE 20 orphan adoption): the owning
        # process resubmits a dead peer's manifest-listed folds into
        # ITS scheduler (resuming from the spilled checkpoints); None
        # = 400 (replicas without a checkpoint store adopt nothing)
        self.adopt_handler = None
        reg = metrics or get_registry()
        # the registry GET /metrics exposes — the same one the rpc
        # counter below reports into (the process default unless the
        # owner isolated one)
        self._registry = reg
        # distinct name from the client-side fleet_rpc_requests_total:
        # a procfleet replica both serves a front door and forwards via
        # HttpTransports on the same registry, and the registry dedups
        # by metric name — one shared name would silently sum sent and
        # served RPCs into a single series
        self._m_rpc = reg.counter(
            "fleet_rpc_served_total",
            "front-door RPCs served by this process, by route/outcome",
            ("route", "outcome"))
        # who-am-I series (ISSUE 16): every /metrics exposition carries
        # exactly one fleet_replica_identity sample at value 1 whose
        # labels name this replica, its CURRENT model tag, and this
        # process incarnation (the boot nonce) — a control plane that
        # polled a restarted replica can cross-check the scrape against
        # /admin/stats's identity block and discard a stale one instead
        # of acting on another incarnation's numbers. Superseded label
        # sets (pre-rollout tags) are zeroed, not removed, so exactly
        # one series is ever at 1.
        self._m_identity = reg.gauge(
            "fleet_replica_identity",
            "1 for this process's current identity "
            "(replica_id/model_tag/incarnation), 0 for superseded",
            ("replica_id", "model_tag", "incarnation"))
        self._identity_labels: Optional[dict] = None
        self._refresh_identity()
        server = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):
                pass

            def _reply(self, code: int, body: bytes = b"",
                       headers: Optional[dict] = None,
                       content_type: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    if k != "Content-Type":
                        self.send_header(k, v)
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _json(self, code: int, payload: dict):
                self._reply(code, json.dumps(payload).encode("utf-8"))

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", "0") or 0)
                return self.rfile.read(n) if n else b""

            def do_GET(self):
                try:
                    server._handle(self, "GET")
                except Exception as exc:
                    try:
                        self._json(500, {"error": repr(exc)})
                    except Exception:
                        pass

            def do_POST(self):
                try:
                    server._handle(self, "POST")
                except Exception as exc:
                    try:
                        self._json(500, {"error": repr(exc)})
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self._httpd.server_address[:2]
        return str(host), int(port)

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "FrontDoorServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name=f"frontdoor-{self.replica_id or self.address[1]}")
            self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            if self._partition_timer is not None:
                self._partition_timer.cancel()
                self._partition_timer = None

    def __enter__(self) -> "FrontDoorServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- routing ---------------------------------------------------------

    def _handle(self, h, method: str):
        parsed = urlparse.urlsplit(h.path)
        path = parsed.path
        if path == "/healthz" and method == "GET":
            return self._healthz(h)
        if path == "/metrics" and method == "GET":
            # Prometheus scrape (ISSUE 15): control-plane like /admin —
            # served through an induced partition, because the chaos
            # window is exactly when an operator needs the numbers
            return self._metrics(h)
        if path.startswith("/admin/"):
            return self._admin(h, method, path)
        if self.partition.is_set():
            # induced partition: the data plane is unreachable exactly
            # the way a firewalled host is — callers time out or error,
            # mark this replica down, and fail over
            self._m_rpc.inc(route="data", outcome="partitioned")
            return h._json(503, {"error": "partitioned"})
        if path == "/v1/submit" and method == "POST":
            return self._submit(h)
        if path.startswith("/v1/result/") and method == "GET":
            return self._result(h, path[len("/v1/result/"):], parsed)
        if path.startswith("/v1/cancel/") and method == "POST":
            return self._cancel(h, path[len("/v1/cancel/"):])
        h._json(404, {"error": "not found"})

    # -- endpoints -------------------------------------------------------

    def _healthz(self, h):
        payload = {"replica": self.replica_id,
                   "tag": self.rollout.tag if self.rollout else "",
                   "epoch": self.rollout.epoch if self.rollout else 0,
                   "partitioned": self.partition.is_set()}
        health = getattr(self.scheduler, "health", None)
        if callable(health):
            try:
                payload.update(health())
            except Exception:
                pass
        if self.partition.is_set():
            # a partitioned replica is unreachable, health included —
            # the recovery probe must keep it marked down
            self._m_rpc.inc(route="healthz", outcome="partitioned")
            return h._reply(503, json.dumps(payload).encode("utf-8"))
        if payload.get("preempting"):
            # reclaim announced (ISSUE 20): this replica dies within
            # the grace window — 503 with the state in the body, so a
            # single probe marks it down AND tells the prober why
            self._m_rpc.inc(route="healthz", outcome="preempting")
            return h._reply(503, json.dumps(payload).encode("utf-8"))
        self._m_rpc.inc(route="healthz", outcome="ok")
        h._json(200, payload)

    def identity(self) -> dict:
        """This process's identity triple: who the scrape/stats came
        from. `incarnation` is the boot nonce — two boots of the same
        replica_id never share it, which is what lets a controller
        reject a stale scrape from a pre-restart incarnation."""
        return {"replica_id": self.replica_id,
                "model_tag": self.rollout.tag if self.rollout else "",
                "incarnation": self._boot_nonce}

    def _refresh_identity(self):
        """Keep exactly one fleet_replica_identity series at 1: the
        current triple. A rollout changes the tag label — the old
        series is zeroed (kept, so the flip is visible in a scrape)."""
        labels = self.identity()
        with self._lock:
            prev = self._identity_labels
            if prev == labels:
                return
            self._identity_labels = labels
        if prev is not None:
            self._m_identity.set(0, **prev)
        self._m_identity.set(1, **labels)

    def _metrics(self, h):
        """Prometheus text exposition of this process's registry (the
        0.0.4 format obs.export.prometheus_text renders) — the registry
        was previously only reachable as JSON through /admin/stats."""
        from alphafold2_tpu.obs.export import prometheus_text

        if self.metrics_hook is not None:
            try:
                self.metrics_hook()
            except Exception:
                pass      # a broken refresher never breaks the scrape
        self._refresh_identity()
        try:
            text = prometheus_text(self._registry)
        except Exception as exc:
            self._m_rpc.inc(route="metrics", outcome="error")
            return h._json(500, {"error": repr(exc)})
        self._m_rpc.inc(route="metrics", outcome="ok")
        h._reply(200, text.encode("utf-8"),
                 content_type="text/plain; version=0.0.4")

    def _submit(self, h):
        from alphafold2_tpu.serve.scheduler import (DrainingError,
                                                    QueueFullError)

        tag = h.headers.get(_HDR_TAG, "")
        if self.rollout is not None and tag \
                and tag != self.rollout.tag:
            self._m_rpc.inc(route="submit", outcome="stale_tag")
            return h._json(409, {"error": "model tag mismatch",
                                 "tag": self.rollout.tag})
        # two body formats, told apart by Content-Type: npz = tokenized
        # FoldRequest (the classic path, and what forwarded hops carry),
        # application/json = a RAW job (ISSUE 10) — sequence string (or
        # token list) + raw MSA, featurized REPLICA-SIDE through
        # scheduler.submit_raw (the feature pool when attached, inline
        # otherwise), so web clients never need a tokenizer
        ctype = (h.headers.get("Content-Type") or "").split(";")[0]
        raw_body = ctype.strip().lower() == "application/json"
        if raw_body and not callable(getattr(self.scheduler,
                                             "submit_raw", None)):
            self._m_rpc.inc(route="submit", outcome="bad_request")
            return h._json(400, {"error": "raw submissions unsupported "
                                          "by this replica"})
        try:
            request = (decode_raw_request(h._body(), h.headers)
                       if raw_body
                       else decode_request(h._body(), h.headers))
        except ValueError as exc:
            self._m_rpc.inc(route="submit", outcome="bad_request")
            return h._json(400, {"error": str(exc)})
        # cross-process trace continuation (ISSUE 15): a submit whose
        # headers carry a TraceContext — a forwarded fold, a raw job
        # routed by feature key, a traced driver — continues the
        # SENDER's trace on this replica's tracer, so the fold stages
        # here stitch under the sender's rpc span instead of starting
        # a disconnected trace. No headers (or tracing off here) is
        # byte-for-byte the old path.
        trace = None
        ctx = TraceContext.from_headers(h.headers)
        if ctx is not None:
            tracer = getattr(self.scheduler, "tracer", None)
            if tracer is not None and getattr(tracer, "enabled", False):
                trace = tracer.start_trace(request.request_id,
                                           context=ctx)
        try:
            if trace is not None:
                ticket = (self.scheduler.submit_raw(request, trace=trace)
                          if raw_body
                          else self.scheduler.submit(request,
                                                     trace=trace))
            else:
                ticket = (self.scheduler.submit_raw(request) if raw_body
                          else self.scheduler.submit(request))
        except DrainingError:
            self._finish_trace(trace, "rejected", "draining")
            self._m_rpc.inc(route="submit", outcome="draining")
            body = {"error": "draining"}
            if getattr(self.scheduler, "preempting", False):
                # tell the refused caller WHY (ISSUE 20): a preempting
                # drain never heals, so the client marks this replica
                # down immediately instead of counting strikes
                body["preempting"] = True
            return h._json(503, body)
        except QueueFullError:
            self._finish_trace(trace, "rejected", "queue full")
            self._m_rpc.inc(route="submit", outcome="queue_full")
            return h._json(429, {"error": "queue full"})
        except ValueError as exc:
            # deterministic input problem (e.g. length exceeds the
            # largest bucket): the CLIENT's error, 400 — never 500,
            # which failover layers would misread as a server fault
            # and retry across the whole fleet
            self._finish_trace(trace, "rejected", str(exc))
            self._m_rpc.inc(route="submit", outcome="bad_request")
            return h._json(400, {"error": str(exc)})
        except RuntimeError as exc:
            # stopped scheduler: same caller story as draining —
            # this replica cannot take the work, go elsewhere
            self._finish_trace(trace, "error", str(exc))
            self._m_rpc.inc(route="submit", outcome="unavailable")
            return h._json(503, {"error": str(exc)})
        slot = _TicketSlot(ticket)
        with self._lock:
            self._ticket_counter[0] += 1
            ticket_id = f"{self.replica_id or 'fd'}-" \
                        f"{self._boot_nonce}-" \
                        f"{self._ticket_counter[0]}"
            self._gc_locked()
            self._slots[ticket_id] = slot

        def _on_done(response):
            slot.response = response
            slot.resolved_at = time.monotonic()
            slot.event.set()
            if slot.cancelled:
                with self._lock:
                    self._slots.pop(ticket_id, None)

        ticket.add_done_callback(_on_done)
        self._m_rpc.inc(route="submit", outcome="ok")
        h._json(200, {"ticket": ticket_id,
                      "request_id": request.request_id})

    def _result(self, h, ticket_id: str, parsed):
        ticket_id = urlparse.unquote(ticket_id)
        with self._lock:
            slot = self._slots.get(ticket_id)
        if slot is None:
            self._m_rpc.inc(route="result", outcome="unknown")
            return h._json(404, {"error": "unknown ticket"})
        try:
            wait_s = float(urlparse.parse_qs(parsed.query).get(
                "wait_s", ["0"])[0])
        except ValueError:
            wait_s = 0.0
        wait_s = max(0.0, min(wait_s, self.max_wait_s))
        query = urlparse.parse_qs(parsed.query)
        if query.get("progress", ["0"])[0] == "1":
            # progressive long-poll: return 206 + the latest
            # per-recycle update as soon as one NEWER than the
            # client's `after=<recycle>` cursor exists, instead of
            # sitting out the whole wait on the terminal event (a
            # streaming client would otherwise see at most one stale
            # update per window). Short wait slices: FoldTicket has no
            # progress event to block on, and recycles are
            # 10s-of-ms-granular.
            try:
                after = int(query.get("after", ["-1"])[0])
            except ValueError:
                after = -1
            deadline = time.monotonic() + wait_s
            while True:
                if slot.event.is_set():
                    break                        # terminal: 200 below
                latest = self._latest_progress(slot)
                if latest is not None and int(latest.recycle) > after:
                    from alphafold2_tpu.fleet.rpc import encode_arrays
                    self._m_rpc.inc(route="result", outcome="progress")
                    return h._reply(
                        206, encode_arrays(latest.coords,
                                           latest.confidence),
                        headers={"X-Status": "running",
                                 "X-Recycle": str(int(latest.recycle))},
                        content_type="application/octet-stream")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._m_rpc.inc(route="result", outcome="pending")
                    return h._reply(204, b"")
                slot.event.wait(min(0.05, remaining))
        elif not slot.event.wait(wait_s):
            self._m_rpc.inc(route="result", outcome="pending")
            return h._reply(204, b"")
        body, headers = encode_response(slot.response)
        with self._lock:
            self._slots.pop(ticket_id, None)   # single pickup
        self._m_rpc.inc(route="result", outcome="ok")
        h._reply(200, body, headers=headers,
                 content_type="application/octet-stream")

    @staticmethod
    def _finish_trace(trace, status: str, error: str):
        """A continued trace refused at the door still owes the fleet
        one terminal record (the scheduler usually finishes it, but the
        pre-entry fail-fasts — bucket_for on an over-length sequence —
        raise before it adopts the trace). finish() is idempotent, so
        double cover costs nothing."""
        if trace is not None:
            try:
                trace.finish(status, error=error)
            except Exception:
                pass

    @staticmethod
    def _latest_progress(slot):
        getter = getattr(slot.ticket, "latest_progress", None)
        if not callable(getter):
            return None
        try:
            return getter()
        except Exception:
            return None

    def _cancel(self, h, ticket_id: str):
        ticket_id = urlparse.unquote(ticket_id)
        with self._lock:
            slot = self._slots.pop(ticket_id, None)
        if slot is not None:
            # the fold itself may already be batched — best-effort
            # means the RESULT slot is dropped (and a late resolution
            # self-cleans via the done callback), not that the
            # accelerator work is yanked back
            slot.cancelled = True
        self._m_rpc.inc(route="cancel",
                        outcome="ok" if slot is not None else "unknown")
        h._json(200, {"cancelled": slot is not None})

    def _admin(self, h, method: str, path: str):
        if path == "/admin/rollout" and method == "POST":
            if self.rollout is None:
                return h._json(400, {"error": "no rollout state"})
            try:
                payload = json.loads(h._body().decode("utf-8"))
                tag = payload["tag"]
            except Exception as exc:
                return h._json(400, {"error": f"bad payload: {exc!r}"})
            epoch = self.rollout.bump(str(tag))
            self._m_rpc.inc(route="admin_rollout", outcome="ok")
            return h._json(200, {"tag": self.rollout.tag,
                                 "epoch": epoch})
        if path == "/admin/stats" and method == "GET":
            try:
                stats = self.scheduler.serve_stats()
                if self.extra_stats is not None:
                    stats["extra"] = self.extra_stats()
                # identity rides every stats reply (ISSUE 16): a
                # controller cross-checks it against the /metrics
                # fleet_replica_identity series so a restarted
                # replica's stale scrape is discarded, never acted on
                stats["identity"] = self.identity()
                body = json.dumps(stats, default=float).encode("utf-8")
            except Exception as exc:
                return h._json(500, {"error": repr(exc)})
            self._m_rpc.inc(route="admin_stats", outcome="ok")
            return h._reply(200, body)
        if path == "/admin/resize" and method == "POST":
            pool = getattr(self.scheduler, "feature_pool", None)
            if pool is None or not hasattr(pool, "resize"):
                self._m_rpc.inc(route="admin_resize", outcome="error")
                return h._json(400, {"error": "no feature pool"})
            try:
                payload = json.loads(h._body().decode("utf-8"))
                workers = int(payload["workers"])
            except Exception as exc:
                self._m_rpc.inc(route="admin_resize", outcome="error")
                return h._json(400, {"error": f"bad payload: {exc!r}"})
            try:
                new = pool.resize(workers)
            except (ValueError, RuntimeError) as exc:
                self._m_rpc.inc(route="admin_resize", outcome="error")
                return h._json(400, {"error": str(exc)})
            self._m_rpc.inc(route="admin_resize", outcome="ok")
            return h._json(200, {"replica": self.replica_id,
                                 "workers": new})
        if path == "/admin/peers" and method == "POST":
            if self.peer_admin is None:
                self._m_rpc.inc(route="admin_peers", outcome="error")
                return h._json(400, {"error": "no peer admin"})
            try:
                payload = json.loads(h._body().decode("utf-8"))
                op = str(payload["op"])
                peer = dict(payload["peer"])
                if op not in ("register", "unregister", "up", "down"):
                    raise ValueError(f"unknown op {op!r}")
            except Exception as exc:
                self._m_rpc.inc(route="admin_peers", outcome="error")
                return h._json(400, {"error": f"bad payload: {exc!r}"})
            try:
                out = self.peer_admin(op, peer)
            except Exception as exc:
                self._m_rpc.inc(route="admin_peers", outcome="error")
                return h._json(500, {"error": repr(exc)})
            self._m_rpc.inc(route="admin_peers", outcome="ok")
            return h._json(200, dict(out or {}, op=op))
        if path == "/admin/adopt" and method == "POST":
            if self.adopt_handler is None:
                self._m_rpc.inc(route="admin_adopt", outcome="error")
                return h._json(400, {"error": "no adopt handler"})
            try:
                payload = json.loads(h._body().decode("utf-8"))
                if not isinstance(payload.get("orphans"), list):
                    raise ValueError("orphans must be a list")
            except Exception as exc:
                self._m_rpc.inc(route="admin_adopt", outcome="error")
                return h._json(400, {"error": f"bad payload: {exc!r}"})
            try:
                out = self.adopt_handler(payload)
            except Exception as exc:
                self._m_rpc.inc(route="admin_adopt", outcome="error")
                return h._json(500, {"error": repr(exc)})
            self._m_rpc.inc(route="admin_adopt", outcome="ok")
            return h._json(200, dict(out or {},
                                     replica=self.replica_id))
        if path == "/admin/partition" and method == "POST":
            try:
                payload = json.loads(h._body().decode("utf-8") or "{}")
                duration = float(payload.get("duration_s", 0.0))
            except Exception as exc:
                return h._json(400, {"error": f"bad payload: {exc!r}"})
            self.set_partition(duration)
            self._m_rpc.inc(route="admin_partition", outcome="ok")
            return h._json(200, {"partitioned": duration > 0,
                                 "duration_s": duration})
        h._json(404, {"error": "not found"})

    # -- partition / gc --------------------------------------------------

    def set_partition(self, duration_s: float):
        """Arm (duration_s > 0) or clear (<= 0) the induced partition;
        a positive duration auto-heals on a timer."""
        with self._lock:
            if self._partition_timer is not None:
                self._partition_timer.cancel()
                self._partition_timer = None
            if duration_s > 0:
                self.partition.set()
                self._partition_timer = threading.Timer(
                    duration_s, self.partition.clear)
                self._partition_timer.daemon = True
                self._partition_timer.start()
            else:
                self.partition.clear()

    def _gc_locked(self):
        """Drop RESOLVED slots unpicked for longer than the TTL (caller
        holds _lock). Unresolved slots are never evicted: they are
        in-flight scheduler work whose client may legitimately
        long-poll past any TTL (HttpTransport's poll budget exceeds
        it by design), and the scheduler owes every ticket a terminal
        state, so an unresolved slot always becomes collectable.
        Runs on the submit path, so an idle server holds stale slots
        until the next submit — fine: the TTL bounds memory, not
        correctness."""
        if not self._slots:
            return
        cutoff = time.monotonic() - self.ticket_ttl_s
        dead = [tid for tid, slot in self._slots.items()
                if slot.resolved_at is not None
                and slot.resolved_at < cutoff]
        for tid in dead:
            self._slots.pop(tid, None)

    def snapshot(self) -> dict:
        with self._lock:
            return {"replica": self.replica_id,
                    "address": list(self.address),
                    "parked_tickets": len(self._slots),
                    "partitioned": self.partition.is_set()}
