"""Multi-process fleet harness: N REAL replica processes, one front
door each, and the failure modes a single process cannot have.

`fleet.InProcessFleet` is the fleet's executable spec, but everything
in it shares one Python process — a replica there can never crash,
hang, or partition away from its peers. This module runs the SAME
stack (FoldExecutor + FoldCache + PeerCacheServer + router + Scheduler)
as separate OS processes wired by `fleet.rpc.HttpTransport` against
each replica's `fleet.frontdoor.FrontDoorServer`, so the chaos the
ROADMAP's north star is defined by becomes inducible:

- kill -9 one replica mid-run: its in-flight forwarded tickets
  error-resolve with the transport marker and FAIL OVER to local folds
  on the replicas that forwarded them; driver-side submits to the dead
  front door retry on the next replica (`FleetClient`, backed by the
  same `serve.RetryPolicy` classification/backoff the scheduler uses);
- partition one replica (`POST /admin/partition`): both its planes
  (front door AND peer cache, one shared event) refuse with 503 for a
  window — callers mark it down and route around it; the recovery
  probe heals it when the window closes, and `breaker=open` or
  `draining` in the unified health payload keeps a sick-but-listening
  replica marked down;
- rolling drain-restart: SIGTERM wires to `Scheduler.drain()` — stop
  admitting (503 to callers, who go elsewhere), let outstanding
  forwards resolve, fold everything queued, let parked results be
  picked up, exit 0. On restart the replica rejoins at the PERSISTED
  rollout epoch (`<state>/rollout.json`) with its PERSISTED poison
  quarantine (`<state>/quarantine.jsonl`) — no stale-tag serving, no
  re-bisecting known poisons.

Driven by `tools/serve_loadtest.py --procs N` and serve_smoke.sh
phase 6; tests/test_frontdoor.py's `slow`-marked tier asserts the same
invariants in miniature. The replica child is this module's `__main__`
(`python -m alphafold2_tpu.fleet.procfleet --config <json-file>`).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional
from urllib import request as urlrequest

from alphafold2_tpu.fleet.rpc import RPC_TRANSPORT_MARKER, HttpTransport
from alphafold2_tpu.obs.trace import NULL_TRACE

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    """An ephemeral port the OS just considered free. Classic
    check-then-use race, acceptable for a localhost harness: the
    window is microseconds and a collision fails loudly at bind."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _scrubbed_env() -> dict:
    """Child env mirroring tests/conftest.py's hardening: CPU platform,
    no ambient PJRT plugin injection (a replica that dials a wedged
    TPU tunnel at import hangs the whole harness)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    return env


# -- parent: the process fleet -------------------------------------------

class ReplicaHandle:
    """One spawned replica process + its addresses and state dirs."""

    def __init__(self, index: int, config: dict, config_path: str):
        self.index = index
        self.config = config
        self.config_path = config_path
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = os.path.join(
            os.path.dirname(config_path), "replica.log")

    @property
    def replica_id(self) -> str:
        return self.config["replica_id"]

    @property
    def frontdoor_url(self) -> str:
        return (f"http://{self.config['host']}:"
                f"{self.config['frontdoor_port']}")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ProcFleet:
    """Spawn, address, and torment N replica processes.

    run_dir: every replica gets `<run_dir>/<rid>/` holding its config,
        log, state (rollout.json / quarantine.jsonl), cache dir, and
        trace JSONL — kill -9 loses the process, never the state.
    model: dict of tiny-model knobs the child builds its executor from
        (dim, depth, msa_depth — the loadtest's synthetic serving
        model, small enough that N replicas compile in seconds on CPU).
    """

    def __init__(self, n_replicas: int, run_dir: str,
                 model_tag: str = "procfleet@v1",
                 buckets: tuple = (32, 64),
                 max_batch: int = 2, max_wait_ms: float = 25.0,
                 num_recycles: int = 0,
                 model: Optional[dict] = None,
                 retry: bool = True,
                 host: str = "127.0.0.1",
                 mesh_policy: str = "",
                 mesh_hbm_gb: float = 16.0,
                 recycle: Optional[dict] = None,
                 feature_pool: Optional[dict] = None,
                 slo: str = "",
                 slo_window_s: float = 60.0,
                 key_log: bool = False,
                 controller: Optional[dict] = None,
                 checkpoint_spill: bool = False,
                 bulk: Optional[dict] = None,
                 cascade: Optional[dict] = None,
                 preemption: bool = False):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.replicas: List[ReplicaHandle] = []
        self.host = host
        self._n_boot = n_replicas
        # knobs every replica config (boot-time AND runtime-added)
        # inherits — add_replica() writes configs from the same dict
        self._knobs = dict(
            model_tag=model_tag, buckets=list(buckets),
            max_batch=int(max_batch), max_wait_ms=float(max_wait_ms),
            num_recycles=int(num_recycles),
            model=dict(model or {"dim": 32, "depth": 1,
                                 "msa_depth": 3}),
            mesh_policy=str(mesh_policy),
            mesh_hbm_gb=float(mesh_hbm_gb),
            recycle=(None if recycle is None else dict(recycle)),
            feature_pool=(None if feature_pool is None
                          else dict(feature_pool)),
            slo=str(slo), slo_window_s=float(slo_window_s),
            retry=bool(retry), key_log=bool(key_log),
            # durable mid-loop checkpoints (ISSUE 18): each replica
            # spills step-loop carries under its state dir and serves
            # them to failover peers over the checkpoint artifact kind.
            # preemption (ISSUE 20) implies it: a grace-budgeted drain
            # with nowhere to spill could only cancel.
            checkpoint_spill=bool(checkpoint_spill) or bool(preemption),
            # spot-preemptible serving (ISSUE 20): each replica runs a
            # PreemptionWatcher on a file notice source, mirrors its
            # spills + orphan manifest into <run_dir>/shared_checkpoints,
            # and takes /admin/adopt assignments; the preempt() chaos
            # verb and the controller's adoption step ride this knob
            preemption=bool(preemption),
            # bulk tier (ISSUE 18): serve.BulkPolicy kwargs; None =
            # no BulkQueue, qos="bulk" submits fold as plain online
            bulk=(None if bulk is None else dict(bulk)),
            # speculative cascade (ISSUE 19): each replica builds a
            # small DRAFT model + scheduler (its own registry, shared
            # fold cache under a distinct model_tag) and serves
            # interactive traffic draft-first behind a confidence
            # gate. Keys: model (draft model dims, default dim 16 /
            # depth 1), num_recycles, accept_plddt, max_entropy,
            # escalation_priority, draft_deadline_s. None = no
            # cascade, byte-identical replicas
            cascade=(None if cascade is None else dict(cascade)))
        # optional control plane (ISSUE 16, OFF when None — the
        # default, byte-identical to a controller-less fleet): dict of
        # fleet.ScalingPolicy knobs + FleetController kwargs; start()
        # builds and runs the reconcile loop against THIS fleet's
        # spawn/drain verbs, stop() stops it first
        self.controller_cfg = (None if controller is None
                               else dict(controller))
        self.controller = None
        ports = [(_free_port(), _free_port()) for _ in range(n_replicas)]
        peer_rows = [{"replica_id": f"r{i}", "host": host,
                      "frontdoor_port": fd, "peer_port": pp}
                     for i, (fd, pp) in enumerate(ports)]
        for i, row in enumerate(peer_rows):
            self._add_handle(i, row, peer_rows, n_replicas)

    def _add_handle(self, i: int, row: dict, all_rows: List[dict],
                    n_total: int) -> "ReplicaHandle":
        """Write replica i's config.json from `row` + the shared knobs
        and append its handle. `all_rows` is the full membership the
        config's static `peers` list is cut from; `n_total` sizes the
        mesh device share."""
        k = self._knobs
        rdir = os.path.join(self.run_dir, row["replica_id"])
        os.makedirs(rdir, exist_ok=True)
        config = dict(
            row,
            model_tag=k["model_tag"],
            state_dir=os.path.join(rdir, "state"),
            cache_dir=os.path.join(rdir, "cache"),
            trace_path=os.path.join(rdir, "traces.jsonl"),
            buckets=list(k["buckets"]),
            max_batch=k["max_batch"],
            max_wait_ms=k["max_wait_ms"],
            num_recycles=k["num_recycles"],
            model=dict(k["model"]),
            # per-replica mesh serving (ISSUE 9 satellite closing
            # the PR-7 ROADMAP item): the spec string rides the
            # config and each replica PROCESS builds its own
            # MeshPolicy over its own device pool at boot
            # (serve.MeshPolicy.parse: "", "auto", or
            # "BUCKET=CHIPS,..."; shapes wider than the pool clamp
            # cleanly, so one fleet config serves 1-device CI and
            # 8-chip hosts alike)
            mesh_policy=k["mesh_policy"],
            mesh_hbm_gb=k["mesh_hbm_gb"],
            # each replica claims the i-th 1/N share of whatever
            # device pool its PROCESS sees: co-hosted replicas must
            # not double-book chips (separate hosts see disjoint
            # pools anyway, so the share is the whole pool there)
            mesh_device_share=[i, n_total],
            # optional step-mode recycle scheduling knobs
            # (serve.RecyclePolicy kwargs); None = opaque folds
            recycle=(None if k["recycle"] is None
                     else dict(k["recycle"])),
            # optional feature pipeline (ISSUE 10): e.g.
            # {"workers": 2, "latency_ms": 0} builds a per-replica
            # serve.FeaturePool + disk-tiered FeatureCache, so raw
            # (JSON) front-door submissions featurize off the hot
            # path; None = inline featurize (today's behavior)
            feature_pool=(None if k["feature_pool"] is None
                          else dict(k["feature_pool"])),
            # optional SLO objectives (ISSUE 15): the
            # obs.slo.SLOPolicy.parse spec string; each replica
            # builds its own engine over its own registry, so the
            # slo_* gauges ride its GET /metrics scrape and
            # serve_stats()["slo"] reports its window
            slo=k["slo"],
            slo_window_s=k["slo_window_s"],
            retry=k["retry"],
            checkpoint_spill=k.get("checkpoint_spill", False),
            bulk=(None if k.get("bulk") is None else dict(k["bulk"])),
            cascade=(None if k.get("cascade") is None
                     else dict(k["cascade"])),
            peers=[p for p in all_rows
                   if p["replica_id"] != row["replica_id"]])
        if k["key_log"]:
            # served-key frequency telemetry (ISSUE 16): the profile
            # the controller's telemetry-driven warming (and
            # cache_warm --from-serve-log) reads
            config["key_log_path"] = os.path.join(rdir, "keys.jsonl")
        if k.get("preemption"):
            # spot-preemptible serving (ISSUE 20): the file the
            # preempt() verb writes its notice to, and the shared
            # backend every replica mirrors checkpoints + manifests
            # into (what survives the process is what gets adopted)
            config["preemption"] = True
            config["preempt_notice_path"] = os.path.join(
                rdir, "preempt.notice")
            config["shared_checkpoints"] = os.path.join(
                self.run_dir, "shared_checkpoints")
        config_path = os.path.join(rdir, "config.json")
        with open(config_path, "w") as fh:
            json.dump(config, fh, indent=1)
        handle = ReplicaHandle(i, config, config_path)
        self.replicas.append(handle)
        return handle

    # -- lifecycle -------------------------------------------------------

    def spawn(self, index: int) -> ReplicaHandle:
        h = self.replicas[index]
        if h.alive():
            return h
        log = open(h.log_path, "a")
        h.proc = subprocess.Popen(
            [sys.executable, "-m", "alphafold2_tpu.fleet.procfleet",
             "--config", h.config_path],
            cwd=_REPO, env=_scrubbed_env(),
            stdout=log, stderr=subprocess.STDOUT)
        log.close()          # the child holds the fd
        return h

    def start(self, timeout_s: float = 180.0) -> "ProcFleet":
        for i in range(len(self.replicas)):
            self.spawn(i)
        self.wait_ready(timeout_s=timeout_s)
        if self.controller_cfg is not None and self.controller is None:
            self.controller = self._build_controller().start()
        return self

    def _build_controller(self):
        """FleetController over THIS fleet's verbs: policy knobs are
        split out of the config dict by ScalingPolicy's field names;
        the rest pass through to the controller. min/max default to
        [boot size, boot size + 2] so an unconfigured controller holds
        the fleet it was given rather than shrinking it to 1."""
        import dataclasses

        from alphafold2_tpu.fleet.controlplane import FleetController
        from alphafold2_tpu.fleet.scaling import ScalingPolicy
        from alphafold2_tpu.obs.trace import Tracer

        cfg = dict(self.controller_cfg or {})
        policy_fields = {f.name for f in
                         dataclasses.fields(ScalingPolicy)}
        policy_kwargs = {key: cfg.pop(key) for key in list(cfg)
                         if key in policy_fields}
        policy_kwargs.setdefault("min_replicas", self._n_boot)
        policy_kwargs.setdefault(
            "max_replicas",
            max(policy_kwargs["min_replicas"], self._n_boot + 2))
        cfg.setdefault("decisions_path", os.path.join(
            self.run_dir, "controller.decisions.jsonl"))
        if self._knobs.get("preemption"):
            # orphan adoption (ISSUE 20): the controller reads dead
            # replicas' manifests from the same shared backend the
            # replicas mirror their spills into
            from alphafold2_tpu.fleet.object_store import \
                FilesystemObjectStore
            cfg.setdefault("orphan_store", FilesystemObjectStore(
                os.path.join(self.run_dir, "shared_checkpoints")))
        cfg.setdefault("tracer", Tracer(
            jsonl_path=os.path.join(self.run_dir,
                                    "controller-traces.jsonl"),
            origin="controller"))
        return FleetController(self,
                               policy=ScalingPolicy(**policy_kwargs),
                               **cfg)

    def wait_ready(self, indices: Optional[List[int]] = None,
                   timeout_s: float = 180.0):
        """Block until each replica's /healthz answers 200 with
        running=True (warm executor, both servers up)."""
        deadline = time.monotonic() + timeout_s
        for i in (indices if indices is not None
                  else range(len(self.replicas))):
            h = self.replicas[i]
            while True:
                if not h.alive():
                    raise RuntimeError(
                        f"{h.replica_id} exited rc={h.proc.poll()} "
                        f"before ready (log: {h.log_path})")
                snap = self.healthz(i)
                if snap is not None and snap.get("running"):
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{h.replica_id} not ready in {timeout_s}s "
                        f"(log: {h.log_path})")
                time.sleep(0.2)

    def stop(self, timeout_s: float = 60.0):
        """SIGTERM every live replica (graceful drain) and reap;
        escalate to SIGKILL past the timeout. The controller (if any)
        stops FIRST — a reconcile racing the teardown would respawn
        what this is tearing down."""
        if self.controller is not None:
            self.controller.stop()
            tracer = self.controller.tracer
            if tracer is not None:
                try:
                    tracer.close()
                except Exception:
                    pass
        for h in self.replicas:
            if h.alive():
                h.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout_s
        for h in self.replicas:
            if h.proc is None:
                continue
            try:
                h.proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(10)

    def __enter__(self) -> "ProcFleet":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- chaos verbs -----------------------------------------------------

    def kill(self, index: int) -> int:
        """kill -9: the crash no handler sees. Returns the (negative)
        returncode."""
        h = self.replicas[index]
        h.proc.kill()
        return h.proc.wait(30)

    def sigterm(self, index: int, timeout_s: float = 60.0) -> int:
        """Graceful drain via SIGTERM; returns the exit code (the
        drain contract is exit 0)."""
        h = self.replicas[index]
        h.proc.send_signal(signal.SIGTERM)
        return h.proc.wait(timeout_s)

    def restart(self, index: int, timeout_s: float = 180.0):
        """Respawn a dead replica on its ORIGINAL ports/state (crash
        recovery: persisted rollout epoch + quarantine load at boot)."""
        self.spawn(index)
        self.wait_ready([index], timeout_s=timeout_s)

    def preempt(self, index: int, grace_s: float = 5.0) -> None:
        """Spot reclaim (ISSUE 20): deliver a preemption notice with a
        grace window, then hard-kill (-9) whatever is still alive when
        the window closes — exactly the cloud's contract. The replica's
        PreemptionWatcher polls the notice file; a well-behaved replica
        spills its in-flight loops, publishes its orphan manifest, and
        exits clean before the kill lands. Requires preemption=True.

        Returns immediately; the kill runs on a daemon timer so the
        test/loadtest can keep driving the survivors through the grace
        window (where the interesting behavior is)."""
        h = self.replicas[index]
        path = h.config.get("preempt_notice_path")
        if not path:
            raise RuntimeError(
                f"{h.replica_id} has no preempt_notice_path "
                f"(ProcFleet(preemption=True) required)")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"grace_s": float(grace_s),
                       "detail": "procfleet.preempt"}, f)
        os.replace(tmp, path)

        def _kill():
            if h.alive():
                h.proc.kill()
                try:
                    h.proc.wait(30)
                except Exception:
                    pass

        t = threading.Timer(float(grace_s), _kill)
        t.daemon = True
        t.start()

    def partition(self, index: int, duration_s: float) -> bool:
        """Induce a network partition: both the replica's planes refuse
        for `duration_s`, then auto-heal."""
        return self._admin_post(
            index, "/admin/partition",
            {"duration_s": float(duration_s)}) is not None

    def rollout(self, new_tag: str) -> Dict[str, Optional[int]]:
        """Bump the model tag on every LIVE replica (the deployment's
        rollout driver). Dead/partitioned replicas are skipped — they
        rejoin at the right tag from their persisted epoch or are
        409-fenced until an operator rolls them."""
        out = {}
        for i, h in enumerate(self.replicas):
            resp = self._admin_post(i, "/admin/rollout",
                                    {"tag": new_tag})
            out[h.replica_id] = (None if resp is None
                                 else resp.get("epoch"))
        return out

    # -- control-plane actuator surface (ISSUE 16) -----------------------

    def add_replica(self) -> int:
        """Provision a NEW replica slot at runtime (fresh id, ports,
        state dirs; static `peers` = the whole current membership so
        its boot registry sees everyone). Returns its index — spawn()
        it to bring it up. Existing replicas learn about it through
        the controller's /admin/peers fan-out, not their configs."""
        i = len(self.replicas)
        row = {"replica_id": f"r{i}", "host": self.host,
               "frontdoor_port": _free_port(),
               "peer_port": _free_port()}
        all_rows = [{"replica_id": h.replica_id,
                     "host": h.config["host"],
                     "frontdoor_port": h.config["frontdoor_port"],
                     "peer_port": h.config["peer_port"]}
                    for h in self.replicas] + [row]
        self._add_handle(i, row, all_rows, len(all_rows))
        return i

    def scale_up(self) -> Optional[str]:
        """Controller verb: provision + spawn one replica; returns its
        id immediately (readiness shows up on the endpoint watch when
        the executor is warm — the controller never blocks on it)."""
        i = self.add_replica()
        self.spawn(i)
        return self.replicas[i].replica_id

    def scale_down(self, replica_id: str) -> bool:
        """Controller verb: graceful drain (SIGTERM — the same drain
        contract rolling restarts use) WITHOUT blocking; the exit is
        reaped in the background. Never kills: drain-before-kill is
        the policy, and the policy layer already refused sub-quorum
        targets."""
        for h in self.replicas:
            if h.replica_id == replica_id and h.alive():
                h.proc.send_signal(signal.SIGTERM)
                threading.Thread(target=h.proc.wait,
                                 name=f"reap-{replica_id}",
                                 daemon=True).start()
                return True
        return False

    def endpoints(self) -> Dict[str, str]:
        """Live replicas' front-door base URLs — the controller's
        endpoint-watch source. A dead process drops out here, which is
        what unregisters it from the controller's membership."""
        return {h.replica_id: h.frontdoor_url
                for h in self.replicas if h.alive()}

    def peer_rows(self) -> List[dict]:
        """Full address rows for every provisioned replica — what the
        controller fans out to /admin/peers on join."""
        return [{"replica_id": h.replica_id,
                 "host": h.config["host"],
                 "frontdoor_port": h.config["frontdoor_port"],
                 "peer_port": h.config["peer_port"]}
                for h in self.replicas]

    def key_log_paths(self) -> Dict[str, str]:
        """Served-key frequency files (empty unless key_log=True)."""
        return {h.replica_id: h.config["key_log_path"]
                for h in self.replicas
                if h.config.get("key_log_path")}

    # -- views -----------------------------------------------------------

    def healthz(self, index: int) -> Optional[dict]:
        return self._get_json(index, "/healthz")

    def stats(self, index: int) -> Optional[dict]:
        return self._get_json(index, "/admin/stats")

    def _get_json(self, index: int, path: str,
                  timeout_s: float = 5.0) -> Optional[dict]:
        url = self.replicas[index].frontdoor_url + path
        try:
            with urlrequest.urlopen(url, timeout=timeout_s) as resp:
                if resp.status != 200:
                    return None
                return json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None

    def _admin_post(self, index: int, path: str, payload: dict,
                    timeout_s: float = 5.0) -> Optional[dict]:
        url = self.replicas[index].frontdoor_url + path
        req = urlrequest.Request(
            url, data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urlrequest.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None

    def merge_traces(self, out_path: str, extra_paths: tuple = ()):
        """Concatenate every replica's trace JSONL (plus extra files,
        e.g. the driver's own) into one file for obs_report. A replica
        killed -9 mid-write can leave a torn tail line — skipped here
        (a torn line is the crash's signature, not an obs bug)."""
        paths = [h.config["trace_path"] for h in self.replicas]
        paths += list(extra_paths)
        with open(out_path, "w") as out:
            for p in paths:
                try:
                    with open(p) as fh:
                        for line in fh:
                            line = line.strip()
                            if not line:
                                continue
                            try:
                                json.loads(line)
                            except ValueError:
                                continue      # torn tail from kill -9
                            out.write(line + "\n")
                except OSError:
                    continue


class FleetClient:
    """The driver's front-door load balancer with failover.

    One `HttpTransport` per replica; `fold()` submits round-robin from
    a caller-chosen seat and retries on the NEXT replica whenever the
    chosen one cannot take or finish the work: refused/draining/queue-
    full submit, transport-marker error resolution (owner died or
    partitioned mid-fold), or a result timeout (which also fires the
    remote cancel). Classification and backoff come from the same
    `serve.RetryPolicy` the scheduler uses — the fleet has ONE notion
    of what is transient. A request only errors out when every replica
    in turn failed it `max_rounds` times — with one induced failure at
    a time and N >= 2 that never happens, which is exactly the
    zero-lost-requests property phase 6 asserts."""

    def __init__(self, urls: List[str], retry=None,
                 result_timeout_s: float = 120.0, max_rounds: int = 3,
                 metrics=None):
        from alphafold2_tpu.serve.resilience import RetryPolicy

        if not urls:
            raise ValueError("FleetClient needs at least one URL")
        self._metrics = metrics
        self.transports = [HttpTransport(u, metrics=metrics)
                           for u in urls]
        self.retry = retry or RetryPolicy(
            max_attempts=4, backoff_base_s=0.1, backoff_max_s=1.0)
        self.result_timeout_s = float(result_timeout_s)
        self.max_rounds = int(max_rounds)
        self._lock = threading.Lock()
        self.submit_retries = 0       # submit refused, went elsewhere
        self.failovers = 0            # terminal transport-marker errors
        self.timeouts = 0             # result timeouts (remote-cancelled)
        self.preempt_markdowns = 0    # replicas skipped on announced
        #                               reclaim (ISSUE 20)
        self.preempt_failovers = 0    # "preempted" terminals resubmitted
        self._preempting: set = set()  # base_urls marked preempting

    def _count(self, field: str):
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    def _note_preempting(self, transport, exc) -> bool:
        """503 with `"preempting": true` in the body (ISSUE 20): the
        replica announced its own death — mark it out of the rotation
        NOW (no strike count-up, no backoff) and return True. Any
        other refusal returns False and takes the normal retry path."""
        if getattr(exc, "code", None) != 503:
            return False
        try:
            snap = json.loads(exc.read().decode("utf-8"))
        except Exception:
            return False
        if not isinstance(snap, dict) or not snap.get("preempting"):
            return False
        self._mark_preempting(transport)
        return True

    def _mark_preempting(self, transport):
        with self._lock:
            if transport.base_url not in self._preempting:
                self._preempting.add(transport.base_url)
                self.preempt_markdowns += 1

    def _pick(self, seat: int):
        """The round-robin seat, skipping replicas marked preempting —
        unless every replica is marked, in which case the raw seat
        stands (a wrong guess beats refusing to try)."""
        n = len(self.transports)
        with self._lock:
            marked = set(self._preempting)
        if marked:
            for off in range(n):
                t = self.transports[(seat + off) % n]
                if t.base_url not in marked:
                    return t
        return self.transports[seat % n]

    def set_urls(self, urls: List[str]):
        """Grow the failover set at runtime (ISSUE 16: a controller-
        scaled fleet should receive driver traffic on its NEW replicas
        too). Add-only: a URL that died just keeps failing over — the
        fold loop already routes around it — so removal would only
        race in-flight seat arithmetic for no benefit."""
        with self._lock:
            known = {t.base_url for t in self.transports}
            fresh = [u for u in urls
                     if u.rstrip("/") not in known]
        for u in fresh:
            # append is atomic; fold()'s modulo seat math tolerates
            # growth between attempts
            self.transports.append(
                HttpTransport(u, metrics=self._metrics))

    def fold(self, request, hint: int = 0, trace=NULL_TRACE):
        """Submit `request` and block for its terminal FoldResponse,
        failing over across replicas. Raises RuntimeError only when
        every replica failed it repeatedly."""
        from urllib.error import HTTPError

        n = len(self.transports)
        last = None
        for attempt in range(self.max_rounds * n):
            transport = self._pick(hint + attempt)
            try:
                ticket = transport.submit(request, trace=trace)
            except HTTPError as exc:
                if self._note_preempting(transport, exc):
                    # announced reclaim (ISSUE 20): skip this replica
                    # for good and go straight at the next seat — no
                    # backoff, the refusal was authoritative, not flaky
                    last = exc
                    self._count("submit_retries")
                    continue
                if exc.code < 500 and exc.code != 429:
                    # deterministic client error (400 bad request,
                    # 409 tag fence): every replica will refuse it the
                    # same way — surface it, don't burn a failover
                    # round per replica
                    raise
                last = exc
                self._count("submit_retries")
                time.sleep(self.retry.delay_s(attempt + 1))
                continue
            except Exception as exc:
                # dead / draining / partitioned / full front door:
                # nothing was accepted, the next replica takes it
                last = exc
                self._count("submit_retries")
                time.sleep(self.retry.delay_s(attempt + 1))
                continue
            try:
                resp = ticket.result(timeout=self.result_timeout_s)
            except TimeoutError as exc:
                # result(timeout=) already sent the remote cancel
                last = exc
                self._count("timeouts")
                continue
            if resp.status == "error" and resp.error \
                    and RPC_TRANSPORT_MARKER in resp.error:
                # owner died mid-fold: at-least-once beats lost
                last = RuntimeError(resp.error)
                self._count("failovers")
                time.sleep(self.retry.delay_s(attempt + 1))
                continue
            if resp.status == "preempted":
                # the replica spilled this fold's mid-loop checkpoint
                # and is exiting (ISSUE 20): resubmit IMMEDIATELY on a
                # survivor — the survivor's submit consult resumes from
                # the spilled recycle, so the retry pays only the
                # recycles since the last checkpoint, and no backoff is
                # owed (the terminal was an announcement, not a fault)
                last = RuntimeError(resp.error or "replica preempted")
                self._count("preempt_failovers")
                self._mark_preempting(transport)
                continue
            return resp
        raise RuntimeError(
            f"all {n} replicas failed {request.request_id} "
            f"({self.max_rounds} rounds; last: {last!r})")

    def snapshot(self) -> dict:
        with self._lock:
            out = {"submit_retries": self.submit_retries,
                   "failovers": self.failovers,
                   "timeouts": self.timeouts}
            if self.preempt_markdowns or self.preempt_failovers:
                # keys absent until a reclaim happened, so baseline
                # loadtest reports compare byte-identical (ISSUE 20)
                out["preempt_markdowns"] = self.preempt_markdowns
                out["preempt_failovers"] = self.preempt_failovers
            return out


# -- child: one replica process ------------------------------------------

def replica_main(config: dict) -> int:
    """Build and serve one full replica from a ProcFleet config dict;
    blocks until SIGTERM (graceful drain, exit 0)."""
    # conftest-grade hardening, in-process too (belt over the parent's
    # env scrub: a bare operator invocation must not dial the tunnel)
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import __graft_entry__
    __graft_entry__.force_cpu_fallback()
    # N replicas compile the same tiny executables: the persistent,
    # platform-namespaced compile cache makes replicas 2..N (and every
    # restart) near-instant to warm
    __graft_entry__._enable_compile_cache()

    import jax
    import jax.numpy as jnp

    from alphafold2_tpu import Alphafold2, obs, serve
    from alphafold2_tpu.fleet.frontdoor import FrontDoorServer
    from alphafold2_tpu.fleet.peer import (PeerCacheClient,
                                           PeerCacheServer)
    from alphafold2_tpu.fleet.registry import ReplicaRegistry
    from alphafold2_tpu.fleet.router import ConsistentHashRouter

    rid = config["replica_id"]
    host = config["host"]
    state_dir = config["state_dir"]
    os.makedirs(state_dir, exist_ok=True)

    # membership: fed from the deployment config (the control plane of
    # this harness); rollout state is DURABLE so a crashed/drained
    # replica rejoins at the tag the fleet rolled to, not its boot tag
    registry = ReplicaRegistry(
        model_tag=config["model_tag"],
        rollout_persist_path=os.path.join(state_dir, "rollout.json"))
    rollout = registry.rollout

    policy = serve.BucketPolicy(config["buckets"])
    mcfg = config["model"]
    model = Alphafold2(dim=mcfg["dim"], depth=mcfg["depth"], heads=2,
                       dim_head=16, predict_coords=True,
                       structure_module_depth=1)
    n0 = policy.edges[0]
    msa_depth = int(mcfg["msa_depth"])
    init_kwargs = dict(mask=jnp.ones((1, n0), bool))
    if msa_depth > 0:
        init_kwargs["msa"] = jnp.zeros((1, msa_depth, n0), jnp.int32)
        init_kwargs["msa_mask"] = jnp.ones((1, msa_depth, n0), bool)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, n0), jnp.int32), **init_kwargs)
    executor = serve.FoldExecutor(model, params,
                                  max_entries=policy.num_buckets)

    from alphafold2_tpu.cache import FoldCache
    cache = FoldCache(disk_dir=config["cache_dir"])
    router = ConsistentHashRouter(registry, rid)
    client = PeerCacheClient(registry, rid, router=router,
                             rollout=rollout)
    cache.peer = client

    registry.register(rid)
    for peer in config["peers"]:
        registry.register(
            peer["replica_id"],
            peer_addr=(peer["host"], int(peer["peer_port"])),
            transport=HttpTransport(
                f"http://{peer['host']}:{peer['frontdoor_port']}",
                rollout=rollout))

    # origin-tagged tracer (ISSUE 15): globally unique trace ids +
    # an `origin` field on every record, so N replicas' JSONL merges
    # into one stitchable fleet set — and inbound submits carrying a
    # TraceContext continue the sender's trace on this tracer
    tracer = obs.Tracer(jsonl_path=config["trace_path"], origin=rid)
    retry = None
    if config.get("retry", True):
        retry_kw = dict(max_attempts=4, backoff_base_s=0.02,
                        backoff_max_s=0.5)
        if config.get("checkpoint_spill"):
            # durable spill rides the carry-checkpoint cadence under
            # the replica's state dir: kill -9 loses the process, the
            # restarted replica resumes survivors at their spilled age
            retry_kw.update(
                checkpoint_every=1,
                checkpoint_spill=os.path.join(state_dir, "checkpoints"))
        retry = serve.RetryPolicy(**retry_kw)
    # optional step-mode recycle scheduling from the fleet config:
    # the same RecyclePolicy knobs the loadtest's --recycle-sched sets
    recycle_cfg = config.get("recycle")
    recycle_policy = (None if not recycle_cfg
                      else serve.RecyclePolicy(**recycle_cfg))
    # optional feature pipeline from the fleet config: the pool's
    # feature cache gets its own disk tier NEXT TO the fold cache (same
    # crash-recovery story — a restarted replica re-reads its features)
    feat_cfg = config.get("feature_pool")
    feature_pool = None
    if feat_cfg:
        from alphafold2_tpu.cache import FeatureCache
        feature_pool = serve.FeaturePool(
            workers=int(feat_cfg.get("workers", 2)),
            cache=FeatureCache(disk_dir=os.path.join(
                config["cache_dir"], "features")),
            latency_s=float(feat_cfg.get("latency_ms", 0.0)) / 1000.0,
            # featurize executor backend (ISSUE 19): "process" runs
            # the pure featurize computation on a ProcessPoolExecutor
            # (the GIL prerequisite for real jackhmmer/mmseqs)
            executor=str(feat_cfg.get("executor", "thread")),
            # express lane (ISSUE 19): the deterministic stub embedder
            # stands in for a pretrained embedding-injection model, so
            # qos="express" raw submits skip MSA prep entirely
            express=(serve.StubEmbedder(
                dim=int(feat_cfg.get("express_dim", 16)))
                if feat_cfg.get("express") else None),
            express_deadline_s=(
                float(feat_cfg["express_deadline_ms"]) / 1000.0
                if feat_cfg.get("express_deadline_ms") else None))
    # per-replica mesh policy from the fleet config (PR-7 ROADMAP item:
    # each replica pins its own chip SUBSET): the config's
    # mesh_device_share = [i, n] hands this replica the i-th 1/n chunk
    # of whatever pool its process sees, so co-hosted replicas never
    # double-book a chip (on separate hosts the pools are disjoint and
    # the share covers them whole); shapes wider than the chunk clamp
    # cleanly, so the same spec serves 1-device CI and multi-chip hosts
    mesh_devices = None
    if config.get("mesh_policy"):
        share = config.get("mesh_device_share") or [0, 1]
        pool = jax.devices()
        chunk = max(1, len(pool) // max(int(share[1]), 1))
        i = int(share[0])
        mesh_devices = pool[i * chunk:(i + 1) * chunk] or pool[-chunk:]
    mesh_policy = serve.MeshPolicy.parse(
        config.get("mesh_policy", ""), model=model, params=params,
        buckets=policy, max_batch=int(config["max_batch"]),
        msa_depth=msa_depth,
        hbm_gb=float(config.get("mesh_hbm_gb", 16.0)),
        devices=mesh_devices,
        carry_recyclables=recycle_policy is not None,
        continuous=bool(recycle_policy is not None
                        and recycle_policy.continuous))
    # optional SLO engine (ISSUE 15): per-QoS-class objectives over
    # this process's default registry — the same one every serve_*
    # metric mirrors into and GET /metrics renders
    slo_engine = None
    if config.get("slo"):
        slo_engine = obs.SLOEngine(obs.SLOPolicy.parse(
            config["slo"],
            window_s=float(config.get("slo_window_s", 60.0))))
    # optional served-key frequency telemetry (ISSUE 16): ingress
    # submits aggregate into a cache_warm-format profile the control
    # plane's telemetry-driven warming tails
    key_log = None
    if config.get("key_log_path"):
        from alphafold2_tpu.serve.metrics import KeyFrequencyLog
        key_log = KeyFrequencyLog(config["key_log_path"])
    # speculative cascade (ISSUE 19): a small draft model + scheduler
    # on an ISOLATED registry (draft series must not sum into this
    # replica's scrape), SHARING the fold cache under a distinct
    # model_tag — tier isolation is by cache key construction
    casc_cfg = config.get("cascade")
    cascade_policy = None
    draft_scheduler = None
    if casc_cfg:
        dcfg = dict(casc_cfg.get("model") or {"dim": 16, "depth": 1})
        draft_model = Alphafold2(
            dim=int(dcfg.get("dim", 16)),
            depth=int(dcfg.get("depth", 1)), heads=2, dim_head=16,
            predict_coords=True, structure_module_depth=1)
        draft_params = draft_model.init(
            jax.random.PRNGKey(1),
            jnp.zeros((1, n0), jnp.int32), **init_kwargs)
        draft_executor = serve.FoldExecutor(
            draft_model, draft_params, max_entries=policy.num_buckets)
        draft_scheduler = serve.build_draft_scheduler(
            draft_executor, policy,
            config=serve.SchedulerConfig(
                max_batch_size=int(config["max_batch"]),
                max_wait_ms=float(config["max_wait_ms"]),
                num_recycles=int(casc_cfg.get("num_recycles", 0)),
                msa_depth=msa_depth,
                confidence_summary=True),
            model_tag=f"{rollout.tag}#draft",
            cache=cache)
        cascade_policy = serve.CascadePolicy(
            draft=draft_scheduler,
            gate=serve.ConfidenceGate(
                accept_plddt=float(casc_cfg.get("accept_plddt", 0.70)),
                max_entropy=casc_cfg.get("max_entropy")),
            escalation_priority=int(
                casc_cfg.get("escalation_priority", 10)),
            draft_deadline_s=casc_cfg.get("draft_deadline_s"))
    scheduler = serve.Scheduler(
        executor, policy,
        serve.SchedulerConfig(
            max_batch_size=int(config["max_batch"]),
            max_wait_ms=float(config["max_wait_ms"]),
            num_recycles=int(config["num_recycles"]),
            msa_depth=msa_depth),
        cache=cache, model_tag=rollout.tag, tracer=tracer,
        router=router, retry=retry,
        quarantine_path=os.path.join(state_dir, "quarantine.jsonl"),
        mesh_policy=mesh_policy, recycle_policy=recycle_policy,
        feature_pool=feature_pool, slo=slo_engine, key_log=key_log,
        bulk=(None if not config.get("bulk")
              else serve.BulkPolicy(**config["bulk"])),
        cascade=cascade_policy)
    # fleet tiers for the durable checkpoint store (ISSUE 18): this
    # replica's spills become fetchable by failover peers
    # (checkpoint_source below), and ITS resume path can pull a dead
    # peer's spill through the same client that fetches fold results
    if scheduler.checkpoint_store is not None:
        scheduler.checkpoint_store.peer = client
        if config.get("shared_checkpoints"):
            # spot preemption (ISSUE 20): mirror spills + the orphan
            # manifest into the fleet-shared backend — what survives
            # the reclaimed PROCESS is what the controller can hand a
            # survivor to adopt after the hard kill lands
            from alphafold2_tpu.fleet.object_store import \
                FilesystemObjectStore
            scheduler.checkpoint_store.backend = FilesystemObjectStore(
                config["shared_checkpoints"])
    # a rollout re-tags the executor, which orphans every executable
    # compiled under the previous tag (the ISSUE 7 staleness fix) —
    # re-warm in the BACKGROUND so a rolled replica re-compiles its
    # serving shapes eagerly instead of on the first unlucky request
    # (the cost exists either way; paying it off the request path is
    # what keeps a controller-driven rollout invisible to latency)
    rewarm = threading.Event()

    def _on_rollout(tag, epoch):
        scheduler.model_tag = tag    # O(1) under the state lock
        if draft_scheduler is not None:
            # the draft tier follows the rollout under its derived
            # tag, so cross-tier key distinctness survives re-tagging
            draft_scheduler.model_tag = f"{tag}#draft"
        rewarm.set()

    rollout.subscribe(_on_rollout)

    def _rewarm_loop():
        while True:
            rewarm.wait()
            rewarm.clear()
            try:
                scheduler.warmup()
            except Exception:
                pass             # cold-serve fallback: compile on use

    threading.Thread(target=_rewarm_loop, daemon=True,
                     name=f"{rid}-rewarm").start()

    partition = threading.Event()
    frontdoor = FrontDoorServer(scheduler, rollout=rollout,
                                host=host,
                                port=int(config["frontdoor_port"]),
                                replica_id=rid, partition=partition)
    peer_server = PeerCacheServer(cache, rollout=rollout, host=host,
                                  port=int(config["peer_port"]),
                                  replica_id=rid,
                                  health_source=scheduler.health,
                                  partition=partition)
    # checkpoint artifact kind (ISSUE 18): peers resuming this
    # replica's orphaned folds fetch its spilled carries here
    peer_server.checkpoint_source = scheduler.checkpoint_store
    frontdoor.extra_stats = lambda: {
        "peer": {"stale_tag_hits": client.stale_tag_hits,
                 "recoveries": client.recoveries},
        "frontdoor": frontdoor.snapshot(),
        "rollout": {"tag": rollout.tag, "epoch": rollout.epoch}}

    # runtime membership verbs (ISSUE 16): the control plane's
    # /admin/peers fan-out rebuilds THIS replica's ring at runtime —
    # a mid-run join starts receiving forwards, a swept member stops
    def _peer_admin(op: str, peer: dict) -> dict:
        pid = str(peer["replica_id"])
        if pid == rid:
            return {"replicas": registry.member_ids()}  # not my own row
        if op == "register":
            registry.register(
                pid,
                peer_addr=(peer["host"], int(peer["peer_port"])),
                transport=HttpTransport(
                    f"http://{peer['host']}:{peer['frontdoor_port']}",
                    rollout=rollout))
        elif op == "unregister":
            registry.unregister(pid)
        elif op in ("up", "down"):
            registry.mark(pid, op == "up")
        return {"replicas": registry.member_ids(),
                "epoch": registry.epoch}

    frontdoor.peer_admin = _peer_admin

    # orphan adoption (ISSUE 20): the controller POSTs a dead peer's
    # manifest rows here; each fold resumes from its spilled
    # checkpoint (shared backend / peer artifact tier) at the spilled
    # recycle age instead of refolding from zero — the fold_key is
    # content-derived, so the resumed result is byte-equal to an
    # uninterrupted fold of the same request
    def _adopt(payload: dict) -> dict:
        import numpy as np
        store = scheduler.checkpoint_store
        if store is None:
            raise RuntimeError("no checkpoint store: cannot adopt")
        adopted = failed = 0
        dead = str(payload.get("replica_id") or "?")
        for rec in payload.get("orphans") or []:
            fk = str((rec or {}).get("fold_key") or "")
            ck = store.latest(fk) if fk else None
            if ck is None or ck.seq is None:
                failed += 1
                continue
            trace = tracer.start_trace(f"adopt-{fk[:12]}")
            trace.begin("adopt")
            req = serve.FoldRequest(
                seq=np.asarray(ck.seq),
                msa=None if ck.msa is None else np.asarray(ck.msa),
                request_id=f"adopt-{dead}-{fk[:12]}")
            scheduler.submit(req, trace=trace)
            trace.end("adopt", source=dead, age=int(ck.age))
            adopted += 1
        return {"adopted": adopted, "failed": failed}

    if config.get("preemption"):
        frontdoor.adopt_handler = _adopt
    # peer-cache fetches served here emit continued trace records
    # under the requester's peer_fetch hop (ISSUE 15)
    peer_server.tracer = tracer
    if slo_engine is not None:
        # a /metrics scrape refreshes the slo_* gauges first, so the
        # scraped window is as fresh as a serve_stats() poll's —
        # whichever of the two ports the scraper targets
        frontdoor.metrics_hook = slo_engine.report
        peer_server.metrics_hook = slo_engine.report

    scheduler.warmup()
    scheduler.start()
    peer_server.start()
    frontdoor.start()

    stop_event = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop_event.set())
    signal.signal(signal.SIGINT, lambda *a: stop_event.set())

    # preemption watcher (ISSUE 20): a file notice (the preempt()
    # chaos verb; in real deployments the metadata/signal sources)
    # flips the scheduler into reclaim mode on the watcher thread,
    # then wakes the main thread to run the grace-budgeted shutdown.
    # SIGTERM stays the GRACEFUL drain (the scale-down contract) —
    # the notice file is the reclaim channel.
    notice_box: List = []
    watcher = None
    if config.get("preemption") and config.get("preempt_notice_path"):
        from alphafold2_tpu.serve.preemption import (FileNoticeSource,
                                                     PreemptionWatcher)

        def _on_notice(n):
            notice_box.append(n)
            stop_event.set()

        watcher = PreemptionWatcher(
            [FileNoticeSource(config["preempt_notice_path"])],
            scheduler=scheduler, on_notice=_on_notice,
            poll_s=0.1).start()
    print(json.dumps({"ready": rid,
                      "frontdoor": list(frontdoor.address),
                      "peer": list(peer_server.address),
                      "tag": rollout.tag,
                      "epoch": rollout.epoch}), flush=True)

    stop_event.wait()

    if notice_box:
        # spot reclaim (ISSUE 20): the grace window buys a MIGRATION,
        # not a finish — spill every loop the budget can't fit,
        # publish the orphan manifest into the shared backend, and be
        # gone before the hard kill lands. The last second of grace is
        # reserved for the manifest + the exit itself.
        notice = notice_box[0]
        if watcher is not None:
            watcher.stop()
        if feature_pool is not None:
            feature_pool.stop()
        budget = max(0.5, notice.deadline_s - time.monotonic() - 1.0)
        complete = scheduler.drain(grace_s=budget)
        manifest = None
        if scheduler.checkpoint_store is not None:
            try:
                manifest = scheduler.checkpoint_store.publish_manifest(
                    rid)
            except Exception:
                manifest = None
        frontdoor.stop()
        peer_server.stop()
        tracer.close()
        print(json.dumps({
            "preempted": rid, "complete": complete,
            "grace_s": notice.grace_s,
            "orphans": (0 if not manifest
                        else len(manifest.get("orphans", [])))}),
            flush=True)
        # _exit, not return: interpreter teardown joins every lingering
        # thread (spilled-but-stuck step loops, executor atexit hooks)
        # and can outlive the reclaim deadline — everything durable
        # (manifest, traces, stdout) is already flushed, so die now
        # rather than let the hard kill turn a clean exit into -9
        sys.stdout.flush()
        os._exit(0)

    # graceful drain: refuse new work, finish what we owe, let parked
    # results be picked up, then exit 0 — the SIGTERM contract a
    # rolling restart relies on
    if watcher is not None:
        watcher.stop()
    if feature_pool is not None:
        # featurize workers submit into the scheduler: drain them
        # first so the scheduler's drain sees every owed fold
        feature_pool.stop()
    complete = scheduler.drain()
    grace_deadline = time.monotonic() + 10.0
    while (frontdoor.snapshot()["parked_tickets"] > 0
           and time.monotonic() < grace_deadline):
        time.sleep(0.05)
    frontdoor.stop()
    peer_server.stop()
    tracer.close()
    print(json.dumps({"drained": rid, "complete": complete}),
          flush=True)
    return 0


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="one procfleet replica process")
    ap.add_argument("--config", required=True,
                    help="path to the replica's config.json")
    args = ap.parse_args(argv)
    with open(args.config) as fh:
        config = json.load(fh)
    return replica_main(config)


if __name__ == "__main__":
    sys.exit(_main())
