"""Fleet membership + weight-rollout state.

Two kinds of epoch live here, deliberately separate:

- the MEMBERSHIP epoch (`ReplicaRegistry.epoch`) bumps whenever the set
  of replicas or their health marks change, so consistent-hash routers
  know to rebuild their ring — a router never scans the registry on the
  submit hot path, it compares one integer;
- the MODEL epoch (`RolloutState.epoch`) bumps on weight rollout
  (`bump(new_tag)`), atomically retagging every component that keys or
  serves cached folds. Cache keys already namespace by `model_tag`
  (cache/keys.py), so a bump makes every pre-rollout entry unreachable
  by construction; the peer protocol additionally REJECTS cross-tag
  fetches (HTTP 409) so a replica that has not rolled yet can never be
  served a stale fold by one that has, or vice versa — HelixFold's
  operational rule that the model version namespaces everything cached.

Health is mark-driven plus optional heartbeat staleness: a replica is
healthy iff it is marked up AND (when `heartbeat_timeout_s` is set) its
last heartbeat is fresh. Mark changes bump the membership epoch;
heartbeat staleness does not (routers skip unhealthy members at lookup
time, so the ring itself need not rebuild).

Everything is process-local state: in a real deployment this registry
is fed by whatever control plane owns membership (k8s endpoints, a
gossip layer); the serving stack only ever reads it through this
interface, so the in-process two-replica harness (fleet/local.py) and a
networked deployment exercise identical code paths.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry


class RolloutState:
    """Fleet-wide (model_tag, epoch), thread-safe, with subscribers.

    `bump(new_tag)` is THE weight-rollout switch: it advances the model
    epoch, re-tags the fleet, and notifies subscribers (schedulers
    re-key, peer servers start rejecting the old tag) before returning —
    so by the time a rollout driver sees `bump` return, no component
    will serve or fetch a stale-tag fold. Subscribers run under the
    state lock: keep them O(1) attribute writes (the in-process harness
    uses them to swap each Scheduler.model_tag)."""

    def __init__(self, model_tag: str = "",
                 registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._tag = model_tag
        self._epoch = 0
        self._subscribers: List[Callable[[str, int], None]] = []
        reg = registry or get_registry()
        self._m_epoch = reg.gauge(
            "fleet_model_epoch", "current weight-rollout epoch")
        self._m_rollouts = reg.counter(
            "fleet_rollouts_total", "model_tag epoch bumps")
        self._m_epoch.set(0)

    @property
    def tag(self) -> str:
        with self._lock:
            return self._tag

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def current(self) -> Tuple[str, int]:
        with self._lock:
            return self._tag, self._epoch

    def subscribe(self, fn: Callable[[str, int], None]):
        """fn(tag, epoch) runs on every bump, under the state lock."""
        with self._lock:
            self._subscribers.append(fn)

    def bump(self, new_tag: str) -> int:
        """Roll the fleet to `new_tag`. Returns the new model epoch."""
        with self._lock:
            if new_tag == self._tag:
                return self._epoch      # idempotent re-announce
            self._tag = new_tag
            self._epoch += 1
            epoch = self._epoch
            subs = list(self._subscribers)
            for fn in subs:
                try:
                    fn(new_tag, epoch)
                except Exception:
                    pass    # a broken subscriber must not block rollout
        self._m_rollouts.inc()
        self._m_epoch.set(epoch)
        return epoch


@dataclass
class ReplicaInfo:
    """One fleet member as the registry sees it.

    peer_addr: (host, port) of its PeerCacheServer, None when the
        replica exposes no peer cache tier.
    submit: transport for request forwarding — a callable taking a
        FoldRequest and returning a FoldTicket (in-process: the peer
        Scheduler.submit bound method; a networked deployment plugs an
        RPC stub with the same signature). None = not forwardable.
    """

    replica_id: str
    peer_addr: Optional[Tuple[str, int]] = None
    submit: Optional[Callable[[Any], Any]] = None
    marked_up: bool = True
    last_heartbeat_s: float = field(default=0.0)


class ReplicaRegistry:
    """Membership + health + epochs for one logical serving fleet.

    heartbeat_timeout_s: when set, a replica also needs a heartbeat
        within this window to count as healthy; None (default) makes
        health purely mark-driven — deterministic for tests and for
        control planes that push liveness instead of pulling it.
    `clock` is injectable for tests (monotonic seconds).
    """

    def __init__(self, heartbeat_timeout_s: Optional[float] = None,
                 model_tag: str = "",
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._clock = clock
        self._members: Dict[str, ReplicaInfo] = {}
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.epoch = 0                 # membership epoch, lock-guarded
        reg = registry or get_registry()
        self.rollout = RolloutState(model_tag, registry=reg)
        self._m_healthy = reg.gauge(
            "fleet_replicas_healthy", "replicas currently routable")
        self._m_members = reg.gauge(
            "fleet_replicas_registered", "replicas in the registry")

    # -- membership ------------------------------------------------------

    def register(self, replica_id: str,
                 peer_addr: Optional[Tuple[str, int]] = None,
                 submit: Optional[Callable] = None) -> ReplicaInfo:
        """Add (or re-announce) a member; bumps the membership epoch.
        A re-announce UPDATES the existing row: fields not provided
        (peer_addr/submit left None) are preserved, as is an
        administrative down-mark — a periodic control-plane re-announce
        must neither strip a live member's forwarding transport nor
        resurrect a replica an operator pulled out."""
        with self._lock:
            info = self._members.get(replica_id)
            if info is None:
                info = ReplicaInfo(replica_id, peer_addr=peer_addr,
                                   submit=submit,
                                   last_heartbeat_s=self._clock())
                self._members[replica_id] = info
            else:
                if peer_addr is not None:
                    info.peer_addr = peer_addr
                if submit is not None:
                    info.submit = submit
                info.last_heartbeat_s = self._clock()
            self.epoch += 1
        self._report_gauges()
        return info

    def deregister(self, replica_id: str):
        with self._lock:
            if self._members.pop(replica_id, None) is not None:
                self.epoch += 1
        self._report_gauges()

    def get(self, replica_id: str) -> Optional[ReplicaInfo]:
        with self._lock:
            return self._members.get(replica_id)

    def members(self) -> List[ReplicaInfo]:
        """All registered members, sorted by id (healthy or not)."""
        with self._lock:
            return [self._members[k] for k in sorted(self._members)]

    def member_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    # -- health ----------------------------------------------------------

    def heartbeat(self, replica_id: str):
        """Freshness ping; does NOT bump the epoch (routers check
        staleness at lookup time, the ring does not change)."""
        with self._lock:
            info = self._members.get(replica_id)
            if info is not None:
                info.last_heartbeat_s = self._clock()

    def mark(self, replica_id: str, up: bool):
        """Administrative health mark; epoch bumps only on a change."""
        changed = False
        with self._lock:
            info = self._members.get(replica_id)
            if info is not None and info.marked_up != up:
                info.marked_up = up
                if up:
                    info.last_heartbeat_s = self._clock()
                self.epoch += 1
                changed = True
        if changed:
            self._report_gauges()

    def is_healthy(self, replica_id: str) -> bool:
        with self._lock:
            return self._healthy_locked(self._members.get(replica_id))

    def _healthy_locked(self, info: Optional[ReplicaInfo]) -> bool:
        if info is None or not info.marked_up:
            return False
        if self.heartbeat_timeout_s is None:
            return True
        return (self._clock() - info.last_heartbeat_s
                <= self.heartbeat_timeout_s)

    # -- views -----------------------------------------------------------

    def _report_gauges(self):
        with self._lock:
            healthy = sum(1 for i in self._members.values()
                          if self._healthy_locked(i))
            total = len(self._members)
        self._m_healthy.set(healthy)
        self._m_members.set(total)

    def snapshot(self) -> dict:
        tag, model_epoch = self.rollout.current()
        with self._lock:
            members = {
                rid: {"healthy": self._healthy_locked(info),
                      "marked_up": info.marked_up,
                      "peer_addr": (list(info.peer_addr)
                                    if info.peer_addr else None),
                      "forwardable": info.submit is not None}
                for rid, info in sorted(self._members.items())}
            return {"epoch": self.epoch,
                    "model_tag": tag,
                    "model_epoch": model_epoch,
                    "replicas": members,
                    "healthy": sum(1 for m in members.values()
                                   if m["healthy"])}
