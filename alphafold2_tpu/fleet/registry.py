"""Fleet membership + weight-rollout state.

Two kinds of epoch live here, deliberately separate:

- the MEMBERSHIP epoch (`ReplicaRegistry.epoch`) bumps whenever the set
  of replicas or their health marks change, so consistent-hash routers
  know to rebuild their ring — a router never scans the registry on the
  submit hot path, it compares one integer;
- the MODEL epoch (`RolloutState.epoch`) bumps on weight rollout
  (`bump(new_tag)`), atomically retagging every component that keys or
  serves cached folds. Cache keys already namespace by `model_tag`
  (cache/keys.py), so a bump makes every pre-rollout entry unreachable
  by construction; the peer protocol additionally REJECTS cross-tag
  fetches (HTTP 409) so a replica that has not rolled yet can never be
  served a stale fold by one that has, or vice versa — HelixFold's
  operational rule that the model version namespaces everything cached.

Health is mark-driven plus optional heartbeat staleness: a replica is
healthy iff it is marked up AND (when `heartbeat_timeout_s` is set) its
last heartbeat is fresh. Mark changes bump the membership epoch;
heartbeat staleness does not (routers skip unhealthy members at lookup
time, so the ring itself need not rebuild).

`sweep()` (ISSUE 16) turns staleness into a real down-mark: a member
whose heartbeat aged past `heartbeat_timeout_s` is auto-marked down
with the membership epoch bumped, so rings REBUILD around it instead
of merely skipping it at lookup time — a wedged-but-listening replica
(process alive, event loop stuck) stops owning keys entirely. Auto-
downed members are distinct from administratively downed ones: a fresh
`heartbeat()` revives an auto-downed member, but never one an operator
`mark()`-ed down.

Everything is process-local state: in a real deployment this registry
is fed by whatever control plane owns membership (k8s endpoints, a
gossip layer); the serving stack only ever reads it through this
interface, so the in-process two-replica harness (fleet/local.py) and a
networked deployment exercise identical code paths.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry


class RolloutState:
    """Fleet-wide (model_tag, epoch), thread-safe, with subscribers.

    `bump(new_tag)` is THE weight-rollout switch: it advances the model
    epoch, re-tags the fleet, and notifies subscribers (schedulers
    re-key, peer servers start rejecting the old tag) before returning —
    so by the time a rollout driver sees `bump` return, no component
    will serve or fetch a stale-tag fold. Subscribers run under the
    state lock: keep them O(1) attribute writes (the in-process harness
    uses them to swap each Scheduler.model_tag).

    `persist_path` makes (tag, epoch) durable: every bump atomically
    rewrites the file (tmp + os.replace) and construction loads it —
    a replica that crashed or was drain-restarted REJOINS at the tag
    the fleet had rolled to, instead of coming back up serving (and
    peer-refusing) under its boot-time default. The persisted epoch
    wins over the constructor's `model_tag` whenever the file exists;
    file trouble degrades to the in-memory default."""

    def __init__(self, model_tag: str = "",
                 registry: Optional[MetricsRegistry] = None,
                 persist_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._tag = model_tag
        self._epoch = 0
        self._persist_path = persist_path
        self._subscribers: List[Callable[[str, int], None]] = []
        if persist_path:
            try:
                with open(persist_path) as fh:
                    rec = json.load(fh)
                self._tag = str(rec["tag"])
                self._epoch = int(rec["epoch"])
            except Exception:
                pass           # first boot / unreadable: boot default
        reg = registry or get_registry()
        self._m_epoch = reg.gauge(
            "fleet_model_epoch", "current weight-rollout epoch")
        self._m_rollouts = reg.counter(
            "fleet_rollouts_total", "model_tag epoch bumps")
        self._m_epoch.set(self._epoch)

    def _persist_locked(self):
        """Caller holds self._lock. Atomic rewrite: a crash mid-rollout
        leaves either the old or the new epoch, never a torn file."""
        if not self._persist_path:
            return
        try:
            d = os.path.dirname(os.path.abspath(self._persist_path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{self._persist_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump({"tag": self._tag, "epoch": self._epoch}, fh)
            os.replace(tmp, self._persist_path)
        except OSError:
            pass               # durability is best-effort, serving wins

    @property
    def tag(self) -> str:
        with self._lock:
            return self._tag

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def current(self) -> Tuple[str, int]:
        with self._lock:
            return self._tag, self._epoch

    def subscribe(self, fn: Callable[[str, int], None]):
        """fn(tag, epoch) runs on every bump, under the state lock."""
        with self._lock:
            self._subscribers.append(fn)

    def bump(self, new_tag: str) -> int:
        """Roll the fleet to `new_tag`. Returns the new model epoch."""
        with self._lock:
            if new_tag == self._tag:
                return self._epoch      # idempotent re-announce
            self._tag = new_tag
            self._epoch += 1
            epoch = self._epoch
            self._persist_locked()      # durable BEFORE subscribers: a
            #                             crash mid-bump rejoins rolled
            subs = list(self._subscribers)
            for fn in subs:
                try:
                    fn(new_tag, epoch)
                except Exception:
                    pass    # a broken subscriber must not block rollout
        self._m_rollouts.inc()
        self._m_epoch.set(epoch)
        return epoch


@dataclass
class ReplicaInfo:
    """One fleet member as the registry sees it.

    peer_addr: (host, port) of its PeerCacheServer, None when the
        replica exposes no peer cache tier.
    transport: forwarding transport — an object with
        `submit(request, trace=) -> FoldTicket` (fleet.rpc: a
        `LocalTransport` for in-process wiring, an `HttpTransport`
        speaking the FrontDoorServer protocol for a networked
        deployment). None + submit=None = not forwardable.
    submit: LEGACY transport — a bare callable taking a FoldRequest and
        returning a FoldTicket. Kept so pre-transport callers (and
        tests that stub `info.submit`) work unchanged; the router
        wraps it in a LocalTransport at forward time. `transport` wins
        when both are set.
    """

    replica_id: str
    peer_addr: Optional[Tuple[str, int]] = None
    submit: Optional[Callable[[Any], Any]] = None
    transport: Optional[Any] = None
    marked_up: bool = True
    last_heartbeat_s: float = field(default=0.0)
    # True when the down-mark came from a heartbeat-TTL sweep rather
    # than an operator: only these members are revivable by heartbeat.
    auto_down: bool = field(default=False)


class ReplicaRegistry:
    """Membership + health + epochs for one logical serving fleet.

    heartbeat_timeout_s: when set, a replica also needs a heartbeat
        within this window to count as healthy; None (default) makes
        health purely mark-driven — deterministic for tests and for
        control planes that push liveness instead of pulling it.
    `clock` is injectable for tests (monotonic seconds).
    """

    def __init__(self, heartbeat_timeout_s: Optional[float] = None,
                 model_tag: str = "",
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None,
                 rollout_persist_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._clock = clock
        self._members: Dict[str, ReplicaInfo] = {}
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.epoch = 0                 # membership epoch, lock-guarded
        reg = registry or get_registry()
        self.rollout = RolloutState(model_tag, registry=reg,
                                    persist_path=rollout_persist_path)
        self._m_healthy = reg.gauge(
            "fleet_replicas_healthy", "replicas currently routable")
        self._m_members = reg.gauge(
            "fleet_replicas_registered", "replicas in the registry")
        # minted only when the TTL feature is armed: a default registry
        # keeps the PR-15 metric-name set byte-identical
        self._m_auto_downs = (reg.counter(
            "fleet_auto_downs_total",
            "members auto-marked down by heartbeat-TTL sweep")
            if heartbeat_timeout_s is not None else None)

    # -- membership ------------------------------------------------------

    def register(self, replica_id: str,
                 peer_addr: Optional[Tuple[str, int]] = None,
                 submit: Optional[Callable] = None,
                 transport: Optional[Any] = None) -> ReplicaInfo:
        """Add (or re-announce) a member; bumps the membership epoch.
        A re-announce UPDATES the existing row: fields not provided
        (peer_addr/submit/transport left None) are preserved, as is an
        administrative down-mark — a periodic control-plane re-announce
        must neither strip a live member's forwarding transport nor
        resurrect a replica an operator pulled out."""
        with self._lock:
            info = self._members.get(replica_id)
            if info is None:
                info = ReplicaInfo(replica_id, peer_addr=peer_addr,
                                   submit=submit, transport=transport,
                                   last_heartbeat_s=self._clock())
                self._members[replica_id] = info
            else:
                if peer_addr is not None:
                    info.peer_addr = peer_addr
                if submit is not None:
                    info.submit = submit
                if transport is not None:
                    info.transport = transport
                info.last_heartbeat_s = self._clock()
            self.epoch += 1
        self._report_gauges()
        return info

    def deregister(self, replica_id: str):
        with self._lock:
            if self._members.pop(replica_id, None) is not None:
                self.epoch += 1
        self._report_gauges()

    def unregister(self, replica_id: str):
        """Remove a member entirely (endpoint gone, not just unhealthy);
        bumps the membership epoch so rings rebuild without it."""
        self.deregister(replica_id)

    def get(self, replica_id: str) -> Optional[ReplicaInfo]:
        with self._lock:
            return self._members.get(replica_id)

    def members(self) -> List[ReplicaInfo]:
        """All registered members, sorted by id (healthy or not)."""
        with self._lock:
            return [self._members[k] for k in sorted(self._members)]

    def member_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    # -- health ----------------------------------------------------------

    def heartbeat(self, replica_id: str):
        """Freshness ping; does NOT bump the epoch (routers check
        staleness at lookup time, the ring does not change) — UNLESS it
        revives a sweep-auto-downed member, which is a membership change
        rings must see. An administrative down-mark is never revived."""
        revived = False
        with self._lock:
            info = self._members.get(replica_id)
            if info is not None:
                info.last_heartbeat_s = self._clock()
                if info.auto_down and not info.marked_up:
                    info.marked_up = True
                    info.auto_down = False
                    self.epoch += 1
                    revived = True
        if revived:
            self._report_gauges()

    def mark(self, replica_id: str, up: bool):
        """Administrative health mark; epoch bumps only on a change.
        An explicit mark always clears `auto_down` — the operator's
        word overrides (and un-arms) the TTL sweep's."""
        changed = False
        with self._lock:
            info = self._members.get(replica_id)
            if info is not None:
                if info.auto_down:
                    info.auto_down = False
                if info.marked_up != up:
                    info.marked_up = up
                    if up:
                        info.last_heartbeat_s = self._clock()
                    self.epoch += 1
                    changed = True
        if changed:
            self._report_gauges()

    def sweep(self) -> List[str]:
        """Auto-down every marked-up member whose heartbeat aged past
        `heartbeat_timeout_s` (no-op when the TTL is unset). Unlike the
        passive lookup-time staleness check, this BUMPS the membership
        epoch so consistent-hash rings rebuild without the wedged
        member — it stops owning keys instead of merely failing them.
        Returns the ids downed this sweep."""
        if self.heartbeat_timeout_s is None:
            return []
        downed: List[str] = []
        with self._lock:
            now = self._clock()
            for rid, info in self._members.items():
                if (info.marked_up and
                        now - info.last_heartbeat_s
                        > self.heartbeat_timeout_s):
                    info.marked_up = False
                    info.auto_down = True
                    downed.append(rid)
            if downed:
                self.epoch += 1
        if downed:
            if self._m_auto_downs is not None:
                self._m_auto_downs.inc(len(downed))
            self._report_gauges()
        return sorted(downed)

    def is_healthy(self, replica_id: str) -> bool:
        with self._lock:
            return self._healthy_locked(self._members.get(replica_id))

    def _healthy_locked(self, info: Optional[ReplicaInfo]) -> bool:
        if info is None or not info.marked_up:
            return False
        if self.heartbeat_timeout_s is None:
            return True
        return (self._clock() - info.last_heartbeat_s
                <= self.heartbeat_timeout_s)

    # -- views -----------------------------------------------------------

    def _report_gauges(self):
        with self._lock:
            healthy = sum(1 for i in self._members.values()
                          if self._healthy_locked(i))
            total = len(self._members)
        self._m_healthy.set(healthy)
        self._m_members.set(total)

    def snapshot(self) -> dict:
        tag, model_epoch = self.rollout.current()
        with self._lock:
            members = {
                rid: {"healthy": self._healthy_locked(info),
                      "marked_up": info.marked_up,
                      "peer_addr": (list(info.peer_addr)
                                    if info.peer_addr else None),
                      "forwardable": (info.transport is not None
                                      or info.submit is not None),
                      "transport": (None if info.transport is None
                                    else type(info.transport).__name__),
                      # only under an armed TTL: a default registry's
                      # snapshot stays byte-identical to PR 15
                      **({"auto_down": info.auto_down}
                         if self.heartbeat_timeout_s is not None else {})}
                for rid, info in sorted(self._members.items())}
            return {"epoch": self.epoch,
                    "model_tag": tag,
                    "model_epoch": model_epoch,
                    "replicas": members,
                    "healthy": sum(1 for m in members.values()
                                   if m["healthy"])}
