"""Peer cache protocol: npz-over-HTTP between replicas, stdlib only.

One replica's `FoldCache` becomes fleet-readable through a
`PeerCacheServer` (stdlib `ThreadingHTTPServer`; GET
`/cache/<key>?tag=<model_tag>` returns the entry as `encode_fold` npz
bytes) and fleet-reading through a `PeerCacheClient` mounted as the
cache's third tier (`FoldCache(peer=client)`): on a local memory+disk
miss the client asks the key's consistent-hash owner, validates the
bytes with the same `decode_fold` the disk tier trusts, and hands back
a `CachedFold` for promotion into the local tiers.

Rollout safety is enforced at BOTH ends (HelixFold's rule that the
model version namespaces everything cached):

- the client stamps every fetch with its current `RolloutState` tag;
- the server 409s any fetch whose tag differs from its own current tag
  (`stale_tag` counters on both sides), so during a rollout a replica
  that has not switched yet and one that has can never exchange folds —
  the epoch bump invalidates peer lookups for the old tag atomically,
  without touching a single stored entry (keys already embed the tag,
  so old entries are unreachable garbage, not hazards).

Failure model: every client-side problem — connect refused, timeout,
HTTP error, corrupt bytes — is a MISS plus a counter, never an
exception into the serving path. `fail_threshold` consecutive transport
errors against one peer mark it down in the registry (bumping the
membership epoch, so routers stop selecting it); corrupt bytes
additionally count as `corrupt` but do NOT mark the peer down (its
other entries are likely fine).

Markdown is NOT forever: the client remembers which peers IT marked
down and, once `recovery_cooldown_s` has passed, half-open-probes the
peer's `/healthz` (at most one probe per peer per cooldown window,
triggered by the next get() but run on a daemon thread so a dead
host's connect timeout never delays a live request) — a 200 marks the
peer back up in the registry (`fleet_peer_recoveries_total`), so a
restarted replica rejoins the peer tier without operator action; a
failed probe resets the cooldown clock. A peer someone ELSE marked
down (an operator, a different client) is never resurrected from here.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib import error as urlerror
from urllib import parse as urlparse
from urllib import request as urlrequest

from alphafold2_tpu.cache.store import CachedFold, decode_fold
from alphafold2_tpu.fleet.registry import ReplicaRegistry, RolloutState
from alphafold2_tpu.fleet.router import ConsistentHashRouter
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE, TraceContext

_TAG_HEADER = "X-Model-Tag"


class PeerCacheServer:
    """Serve one replica's FoldCache to its peers over localhost HTTP.

    Read-only by design: peers fetch what this replica folded; nothing
    is ever written through this surface, so a misbehaving peer can
    cost bandwidth but never poison the store. `port=0` binds an
    ephemeral port (the in-process harness registers the resolved
    address). `rollout=None` disables the tag check (single-tag
    deployments that never roll weights in place).
    """

    def __init__(self, cache, rollout: Optional[RolloutState] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 replica_id: str = "",
                 metrics: Optional[MetricsRegistry] = None,
                 health_source=None,
                 partition: Optional[threading.Event] = None):
        self.cache = cache
        self.rollout = rollout
        self.replica_id = replica_id
        # health_source: zero-arg callable merged into the /healthz
        # payload (Scheduler.health — breaker state, queue depth, drain
        # flag), so the recovery probe and the router's health walk
        # read the SAME truth the front door serves. Assignable after
        # construction (the in-process harness builds servers before
        # schedulers).
        self.health_source = health_source
        # partition: while set, every request (healthz included) is
        # refused 503 — the chaos harness's induced partition, shared
        # with the replica's FrontDoorServer so one event severs both
        # planes
        self.partition = partition
        # tracer: optional obs.Tracer (assignable after construction,
        # like health_source). When set and a fetch carries a
        # TraceContext, this server emits a tiny continued trace — one
        # `peer_serve` span sharing the requester's trace id — so a
        # peer-cache hit's two halves stitch into ONE fleet waterfall
        # (ISSUE 15) instead of a client-side span with no server story
        self.tracer = None
        # metrics_hook: optional zero-arg callable run before each
        # GET /metrics render (same contract as FrontDoorServer's) —
        # wire it to SLOEngine.report so a scrape of THIS port reads
        # gauges as fresh as the front-door port's
        self.metrics_hook = None
        # checkpoint_source: optional duck-typed
        # `latest_raw(group) -> bytes | None`
        # (cache.checkpoints.CheckpointStore) behind the
        # `kind=checkpoint` route — a failover peer fetches a dead
        # replica's spilled mid-loop carry through the SAME wire the
        # fold cache uses (ISSUE 18). Assignable after construction
        # like health_source; None keeps the route a clean 404, so a
        # spill-off replica answers checkpoint probes with a miss,
        # never an error.
        self.checkpoint_source = None
        reg = metrics or get_registry()
        self._registry = reg      # GET /metrics exposes this registry
        m_served = reg.counter(
            "fleet_peer_served_total",
            "peer-protocol fetches served by this process, by outcome",
            ("replica", "outcome"))
        server = self

        class _Handler(BaseHTTPRequestHandler):
            # one fetch per connection is fine at fold granularity;
            # keep-alive would only pin threads
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):      # stdlib default spams stderr
                pass

            def _count(self, outcome: str):
                m_served.inc(replica=server.replica_id, outcome=outcome)

            @staticmethod
            def _finish(trace, outcome: str, status: str):
                if trace is None:
                    return
                try:
                    trace.end("peer_serve", outcome=outcome)
                    trace.finish(status, source="peer")
                except Exception:
                    pass      # obs, never the fetch path

            def _reply(self, code: int, body: bytes,
                       content_type: str = "application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                if server.rollout is not None:
                    self.send_header(_TAG_HEADER, server.rollout.tag)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                trace = None
                try:
                    parsed = urlparse.urlsplit(self.path)
                    if parsed.path == "/metrics":
                        # the same scrape surface the front door grew
                        # (ISSUE 15) — a cache-only deployment without
                        # a front door is still scrapeable. Served
                        # BEFORE the partition check, matching the
                        # front door's rule: the chaos window is
                        # exactly when an operator needs the numbers.
                        # Render failures stay OFF the peer-fetch
                        # error counter the chaos smokes gate on.
                        from alphafold2_tpu.obs.export import \
                            prometheus_text
                        if server.metrics_hook is not None:
                            try:
                                server.metrics_hook()
                            except Exception:
                                pass
                        try:
                            text = prometheus_text(server._registry)
                        except Exception:
                            self._reply(500, b"metrics error",
                                        "text/plain")
                            return
                        self._reply(200, text.encode(),
                                    "text/plain; version=0.0.4")
                        return
                    if server.partition is not None \
                            and server.partition.is_set():
                        # induced partition: unreachable on every
                        # route, health included — probes must keep
                        # this replica marked down until it heals
                        self._reply(503, b"partitioned", "text/plain")
                        return
                    if parsed.path == "/healthz":
                        snap = {"replica": server.replica_id,
                                "tag": (server.rollout.tag
                                        if server.rollout else ""),
                                "epoch": (server.rollout.epoch
                                          if server.rollout else 0)}
                        if server.health_source is not None:
                            # one truth: the same Scheduler.health dict
                            # the front door serves (breaker state,
                            # queue depth, draining)
                            try:
                                snap.update(server.health_source())
                            except Exception:
                                pass
                        self._reply(200, json.dumps(snap).encode(),
                                    "application/json")
                        return
                    if not parsed.path.startswith("/cache/"):
                        self._reply(404, b"not found", "text/plain")
                        return
                    key = parsed.path[len("/cache/"):]
                    # continued trace for the fetch (tracing-on fleets
                    # only): one peer_serve span under the requester's
                    # peer_fetch hop
                    ctx = TraceContext.from_headers(self.headers)
                    if ctx is not None and server.tracer is not None \
                            and getattr(server.tracer, "enabled",
                                        False):
                        trace = server.tracer.start_trace(
                            f"peer:{key[:24]}", context=ctx)
                        trace.begin("peer_serve")
                    qs = urlparse.parse_qs(parsed.query)
                    tag = qs.get("tag", [""])[0]
                    if server.rollout is not None \
                            and tag != server.rollout.tag:
                        # cross-tag fetch: the requester and this
                        # replica disagree on the current weights —
                        # refuse, never guess (rollout invalidation)
                        self._count("stale_tag")
                        self._finish(trace, "stale_tag", "rejected")
                        self._reply(409, b"model tag mismatch",
                                    "text/plain")
                        return
                    if qs.get("kind", [""])[0] == "checkpoint":
                        # checkpoint artifact kind (ISSUE 18): <key>
                        # is a checkpoint GROUP digest; serve this
                        # replica's newest spilled carry for it. The
                        # decoded payload re-proves the tag client-
                        # side (decode_checkpoint), so the route
                        # shares the fold path's 409 gate above and
                        # needs no second check.
                        src = server.checkpoint_source
                        data = (None if src is None
                                else src.latest_raw(key))
                        if data is None:
                            self._count("ckpt_miss")
                            self._finish(trace, "ckpt_miss", "miss")
                            self._reply(404, b"miss", "text/plain")
                            return
                        self._count("ckpt_hit")
                        self._finish(trace, "ckpt_hit", "ok")
                        self._reply(200, data)
                        return
                    data = server.cache.read_raw(key)
                    if data is None:
                        self._count("miss")
                        self._finish(trace, "miss", "miss")
                        self._reply(404, b"miss", "text/plain")
                        return
                    self._count("hit")
                    self._finish(trace, "hit", "ok")
                    self._reply(200, data)
                except Exception:
                    # a broken fetch must cost the REQUESTER a miss,
                    # never wedge the serving replica's handler thread
                    self._count("error")
                    # a continued trace started before the failure
                    # still owes the fleet its serving-side record —
                    # the error outcome is the one an operator most
                    # needs the server half of
                    self._finish(trace, "error", "error")
                    try:
                        self._reply(500, b"peer error", "text/plain")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self._httpd.server_address[:2]
        return str(host), int(port)

    def start(self) -> "PeerCacheServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name=f"peer-cache-{self.replica_id or self.address[1]}")
            self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "PeerCacheServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class PeerCacheClient:
    """`FoldCache(peer=...)` tier that fetches from the key's owner.

    get(key) resolves the key's consistent-hash owner through `router`,
    skips the fetch when the owner is this replica (or unknown), and
    otherwise GETs the entry from the owner's PeerCacheServer with this
    replica's current rollout tag. Validation mirrors the disk tier
    (`decode_fold`); a response whose `X-Model-Tag` no longer matches
    ours is discarded as stale even on HTTP 200 (defense in depth — the
    server also 409s). Never raises out of get(); every outcome lands
    in `fleet_peer_fetch_total{peer,outcome}` and the fetch-latency
    histogram `fleet_peer_fetch_seconds`.
    """

    def __init__(self, registry: ReplicaRegistry, self_id: str,
                 router: Optional[ConsistentHashRouter] = None,
                 rollout: Optional[RolloutState] = None,
                 timeout_s: float = 2.0, fail_threshold: int = 3,
                 recovery_cooldown_s: float = 5.0,
                 metrics: Optional[MetricsRegistry] = None,
                 faults=None):
        self.registry = registry
        self.self_id = self_id
        self.router = router or ConsistentHashRouter(
            registry, self_id, metrics=metrics)
        self.rollout = rollout if rollout is not None else registry.rollout
        self.timeout_s = float(timeout_s)
        self.fail_threshold = max(1, int(fail_threshold))
        self.recovery_cooldown_s = float(recovery_cooldown_s)
        # optional serve.faults.FaultPlan: injected transport failures
        # (chaos) land in the same markdown/recovery machinery as real
        # ones
        self.faults = faults
        self._lock = threading.Lock()
        self._consecutive_failures: dict = {}
        self._down: dict = {}     # peer_id -> monotonic mark-down time
        self.recoveries = 0
        reg = metrics or get_registry()
        self._m_fetch = reg.counter(
            "fleet_peer_fetch_total",
            "peer-tier fetch attempts by owner and outcome",
            ("peer", "outcome"))
        self._m_latency = reg.histogram(
            "fleet_peer_fetch_seconds",
            "wall time of one peer-tier fetch attempt")
        self._m_recoveries = reg.counter(
            "fleet_peer_recoveries_total",
            "marked-down peers recovered by a half-open health probe")
        self.stale_tag_hits = 0   # 200s discarded on tag mismatch (== 0
        #                           unless a server is misbehaving)
        self.preempt_markdowns = 0  # peers marked down on a single
        #                             `preempting` 503 (ISSUE 20)

    def _note_preempting(self, peer_id: str, exc) -> bool:
        """Immediate mark-down on an announced reclaim (ISSUE 20): a
        503 whose JSON body carries `"preempting": true` is not a
        flaky transport earning strikes — the replica has TOLD us it
        dies within its grace window, and it will never heal in place.
        Mark it down on the first refusal (bypassing the
        `fail_threshold` count-up) so zero further fetches route at
        it. Returns True when the mark-down happened."""
        if getattr(exc, "code", None) != 503:
            return False
        try:
            snap = json.loads(exc.read().decode("utf-8"))
        except Exception:
            return False
        if not isinstance(snap, dict) or not snap.get("preempting"):
            return False
        with self._lock:
            self._consecutive_failures.pop(peer_id, None)
            self._down[peer_id] = time.monotonic()
            self.preempt_markdowns += 1
        self.registry.mark(peer_id, up=False)
        return True

    def _note_transport_failure(self, peer_id: str):
        with self._lock:
            n = self._consecutive_failures.get(peer_id, 0) + 1
            if n >= self.fail_threshold:
                # reset on trip: when something marks the peer back up
                # it gets its full strike tolerance again, not a
                # hair-trigger leftover count
                self._consecutive_failures.pop(peer_id, None)
                self._down[peer_id] = time.monotonic()
            else:
                self._consecutive_failures[peer_id] = n
        if n >= self.fail_threshold:
            # stop routing at it until something marks it back up; the
            # registry bump makes every router rebuild its ring view
            self.registry.mark(peer_id, up=False)

    def _note_transport_ok(self, peer_id: str):
        with self._lock:
            self._consecutive_failures.pop(peer_id, None)

    def _maybe_probe_down_peers(self):
        """Half-open recovery: for each peer THIS client marked down
        whose cooldown elapsed, probe its /healthz once and mark it
        back up on a 200. Triggered by get() but probed on a short-
        lived daemon thread — a dead host answers a health probe with
        a full connect timeout, and that wait must tax the probe, not
        the live fold request that happened to trip it. The cooldown
        bookkeeping (one probe per peer per window, stamped before the
        thread starts) bounds the threads the same way it bounded the
        inline probes."""
        if not self._down:
            return
        now = time.monotonic()
        with self._lock:
            due = [pid for pid, t in self._down.items()
                   if now - t >= self.recovery_cooldown_s]
            for pid in due:
                self._down[pid] = now       # one probe per window
        for pid in due:
            threading.Thread(target=self._probe_peer, args=(pid,),
                             name=f"peer-probe-{pid}",
                             daemon=True).start()

    def _probe_peer(self, peer_id: str):
        info = self.registry.get(peer_id)
        if info is None or info.peer_addr is None \
                or self.registry.is_healthy(peer_id):
            # deregistered, unprobeable, or already recovered elsewhere:
            # stop tracking it either way
            with self._lock:
                self._down.pop(peer_id, None)
            return
        host, port = info.peer_addr
        try:
            if self.faults is not None:
                self.faults.on_peer_fetch(peer_id)
            with urlrequest.urlopen(f"http://{host}:{port}/healthz",
                                    timeout=self.timeout_s) as resp:
                ok = resp.status == 200
                if ok:
                    ok = self._probe_payload_healthy(resp.read())
        except Exception:
            ok = False                  # still down; cooldown restarts
        if ok:
            with self._lock:
                self._down.pop(peer_id, None)
                self.recoveries += 1
            self.registry.mark(peer_id, up=True)
            self._m_recoveries.inc()

    @staticmethod
    def _probe_payload_healthy(body: bytes) -> bool:
        """A 200 alone does not prove a replica serves: the unified
        health payload (Scheduler.health via the server's
        health_source) may say the breaker is OPEN — the process
        answers HTTP but fast-sheds every novel fold — or that it is
        draining/stopped. Both count as still-down; pre-unification
        payloads (no such fields) keep the old 200-is-up behavior."""
        try:
            snap = json.loads(body.decode("utf-8"))
        except Exception:
            return True           # not JSON: legacy probe, 200 wins
        if snap.get("breaker") == "open":
            return False
        if snap.get("draining") or snap.get("running") is False:
            return False
        if snap.get("preempting"):
            # announced reclaim (ISSUE 20): the process dies within
            # its grace window — never mark it back up
            return False
        return True

    def get(self, key: str, trace=NULL_TRACE) -> Optional[CachedFold]:
        self._maybe_probe_down_peers()
        owner = self.router.owner_for(key)
        if owner is None or owner == self.self_id:
            return None
        info = self.registry.get(owner)
        if info is None or info.peer_addr is None:
            return None
        tag = self.rollout.tag if self.rollout is not None else ""
        host, port = info.peer_addr
        url = (f"http://{host}:{port}/cache/"
               f"{urlparse.quote(key, safe='')}"
               f"?tag={urlparse.quote(tag, safe='')}")
        # cross-process stitching (ISSUE 15): the fetch carries the
        # request trace's context so the owner's PeerCacheServer can
        # emit a continued peer_serve record under this hop; the
        # span_id lands on the peer_fetch event below so the fleet
        # aggregator can match the two. Nothing on the wire when
        # tracing is off.
        ctx = trace.wire_context()
        t0 = time.monotonic()
        outcome, value = "error", None
        try:
            if self.faults is not None:
                # injected transport failure: caught by the generic
                # handler below, so chaos exercises the real
                # markdown/recovery machinery
                self.faults.on_peer_fetch(owner)
            req = urlrequest.Request(
                url, headers=ctx.to_headers() if ctx is not None else {})
            with urlrequest.urlopen(req, timeout=self.timeout_s) as resp:
                served_tag = resp.headers.get(_TAG_HEADER)
                body = resp.read()
            if served_tag is not None and served_tag != tag:
                with self._lock:
                    self.stale_tag_hits += 1
                outcome = "stale_tag"
            else:
                value = decode_fold(key, body)
                outcome = "hit"
            self._note_transport_ok(owner)
        except urlerror.HTTPError as exc:
            # 404 = clean miss, 409 = rollout tag mismatch; both prove
            # the transport is alive
            outcome = ("miss" if exc.code == 404
                       else "stale_tag" if exc.code == 409 else "error")
            self._note_transport_ok(owner)
            if outcome == "error":
                if self._note_preempting(owner, exc):
                    outcome = "preempting"
                else:
                    self._note_transport_failure(owner)
        except ValueError:
            outcome = "corrupt"       # decode_fold: bad bytes, live peer
            self._note_transport_ok(owner)
        except Exception:
            outcome = "error"         # refused/timeout/reset
            self._note_transport_failure(owner)
        self._m_latency.observe(time.monotonic() - t0)
        self._m_fetch.inc(peer=owner, outcome=outcome)
        if ctx is not None:
            trace.event("peer_fetch", peer=owner, outcome=outcome,
                        span_id=ctx.parent_span_id)
        else:
            trace.event("peer_fetch", peer=owner, outcome=outcome)
        return value

    # max healthy peers one checkpoint probe sweeps: the probe runs
    # once per orphaned fold (boot/admission, not per request), so a
    # small bound keeps failover cheap on wide fleets while still
    # covering every peer of the 2-4 replica deployments the smoke
    # harness runs
    CKPT_PROBE_LIMIT = 4

    def fetch_checkpoint(self, group: str,
                         model_tag: str = "") -> Optional[bytes]:
        """Checkpoint-tier fetch (ISSUE 18): ask live peers for the
        newest spilled carry under `group` (a checkpoint GROUP digest,
        cache.checkpoints.checkpoint_group). Unlike get(), there is no
        owner to route to — the replica that spilled the checkpoint is
        the one that just died, and the group digest has no ring
        position — so this probes up to CKPT_PROBE_LIMIT healthy peers
        (never itself) and returns the first hit's raw bytes for the
        caller (CheckpointStore._peer_fetch) to validate with
        decode_checkpoint. Every outcome lands in the same
        fleet_peer_fetch_total{peer,outcome} counter as fold fetches
        (ckpt_hit/ckpt_miss/ckpt_error) and transport failures feed
        the same markdown machinery; never raises."""
        tag = model_tag or (self.rollout.tag
                            if self.rollout is not None else "")
        probed = 0
        for pid in self.registry.member_ids():
            if probed >= self.CKPT_PROBE_LIMIT:
                break
            if pid == self.self_id or not self.registry.is_healthy(pid):
                continue
            info = self.registry.get(pid)
            if info is None or info.peer_addr is None:
                continue
            probed += 1
            host, port = info.peer_addr
            url = (f"http://{host}:{port}/cache/"
                   f"{urlparse.quote(group, safe='')}"
                   f"?kind=checkpoint&tag={urlparse.quote(tag, safe='')}")
            t0 = time.monotonic()
            outcome, body = "ckpt_error", None
            try:
                if self.faults is not None:
                    self.faults.on_peer_fetch(pid)
                with urlrequest.urlopen(url,
                                        timeout=self.timeout_s) as resp:
                    body = resp.read()
                outcome = "ckpt_hit"
                self._note_transport_ok(pid)
            except urlerror.HTTPError as exc:
                # 404 = this peer never saw the fold; 409 = it runs a
                # different tag — both are live-transport misses
                outcome = ("ckpt_miss" if exc.code in (404, 409)
                           else "ckpt_error")
                self._note_transport_ok(pid)
                if outcome == "ckpt_error":
                    if self._note_preempting(pid, exc):
                        outcome = "ckpt_preempting"
                    else:
                        self._note_transport_failure(pid)
            except Exception:
                self._note_transport_failure(pid)
            self._m_latency.observe(time.monotonic() - t0)
            self._m_fetch.inc(peer=pid, outcome=outcome)
            if body is not None:
                return body
        return None
