"""Forwarding transport: FoldTicket semantics over a process boundary.

Until now request forwarding rode an in-process callable
(`ReplicaInfo.submit` — the peer Scheduler's bound method), which means
a replica could never actually crash, hang, or partition away from its
peers. This module makes the transport an explicit seam:

- `LocalTransport` wraps a bound `Scheduler.submit` and IS the old
  behavior — same thread, same ticket object, zero copies. The
  in-process harness (`fleet.InProcessFleet`) and every existing test
  run through it unchanged.
- `HttpTransport` speaks the `fleet.frontdoor.FrontDoorServer` protocol
  (stdlib urllib, same trust model as the peer cache tier): submit is
  one POST carrying the request as npz bytes plus QoS headers
  (priority, deadline, forwarded, model tag); the result is long-polled
  on a daemon thread and resolves the LOCAL FoldTicket, so callers
  cannot tell a remote fold from a local one. Every transport-level
  failure after a successful submit resolves the ticket as
  `status="error"` with the `rpc_transport` marker — the scheduler's
  forwarding path recognizes that marker and FAILS OVER to folding
  locally (`fleet_failovers_total`) instead of surfacing a dead owner
  to the caller. A submit-time failure raises instead, which the
  scheduler already treats as "fold locally".

Cancellation: `FoldTicket.result(timeout=)` on a forwarded ticket arms
a timeout hook; on expiry the transport sends a best-effort
POST /v1/cancel to the owner (counted in `fleet_remote_cancels_total`)
so the remote side can drop the parked result instead of holding it
until TTL.

Wire format (the request/response analog of `cache.encode_fold`):
one npz payload per direction, self-identifying, validated on decode —
a corrupt or truncated body is a transport error, never a wrong fold.
"""

from __future__ import annotations

import io
import json
import threading
import time
from typing import Optional
from urllib import error as urlerror
from urllib import parse as urlparse
from urllib import request as urlrequest

import numpy as np

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE, TraceContext
from alphafold2_tpu.serve.request import (FoldRequest, FoldResponse,
                                          FoldTicket)

# error marker the scheduler's failover path keys off: any forwarded
# response whose error carries it means "the TRANSPORT died, not the
# fold" — retry locally, the work is still viable
RPC_TRANSPORT_MARKER = "rpc_transport"

_HDR_REQUEST_ID = "X-Request-Id"
_HDR_PRIORITY = "X-Priority"
_HDR_DEADLINE = "X-Deadline-S"
_HDR_FORWARDED = "X-Forwarded"
_HDR_TAG = "X-Model-Tag"
_HDR_STATUS = "X-Status"
_HDR_SOURCE = "X-Source"
_HDR_ATTEMPTS = "X-Attempts"
_HDR_BUCKET = "X-Bucket-Len"
_HDR_ERROR = "X-Error"
_HDR_RECYCLES = "X-Recycles"         # step-mode: iterations executed
_HDR_RECYCLE = "X-Recycle"           # progressive result: its iteration
_HDR_QOS = "X-Qos"                   # "bulk" / "express" mark the
#                                      non-default tiers (absent ==
#                                      "online", so the pre-ISSUE-18
#                                      wire is unchanged)
# cascade provenance (ISSUE 19) — all absent outside a cascade, so the
# pre-cascade response wire is byte-identical
_HDR_TIER = "X-Tier"
_HDR_ESCALATED = "X-Escalated"
_HDR_CONFIDENCE = "X-Confidence-Score"


# -- wire format ---------------------------------------------------------

def encode_request(request: FoldRequest) -> bytes:
    """One FoldRequest as npz bytes (seq + optional msa); QoS travels
    in headers, content in the body — the body alone is content-
    addressable the same way fold_key sees it."""
    buf = io.BytesIO()
    arrays = {"seq": np.asarray(request.seq, np.int32)}
    if request.msa is not None:
        arrays["msa"] = np.asarray(request.msa, np.int32)
    np.savez(buf, **arrays)
    return buf.getvalue()


def request_headers(request: FoldRequest, tag: str = "",
                    context: Optional[TraceContext] = None) -> dict:
    h = {_HDR_REQUEST_ID: request.request_id,
         _HDR_PRIORITY: str(int(request.priority)),
         _HDR_FORWARDED: "1" if request.forwarded else "0",
         "Content-Type": "application/octet-stream"}
    if request.deadline_s is not None:
        h[_HDR_DEADLINE] = repr(float(request.deadline_s))
    if getattr(request, "qos", "online") != "online":
        h[_HDR_QOS] = request.qos
    if tag:
        h[_HDR_TAG] = tag
    if context is not None:
        # cross-process trace propagation (ISSUE 15): the receiving
        # front door continues the SAME trace; absent when tracing is
        # off, so the off-switch leaves the wire byte-identical
        h.update(context.to_headers())
    return h


def decode_request(body: bytes, headers) -> FoldRequest:
    """Parse + validate a submit body/headers into a FoldRequest.
    Raises ValueError on anything wrong; the server turns that into a
    400, never a fold of garbage."""
    try:
        with np.load(io.BytesIO(body)) as z:
            seq = np.asarray(z["seq"], np.int32)
            msa = (np.asarray(z["msa"], np.int32)
                   if "msa" in z.files else None)
    except Exception as exc:
        raise ValueError(f"unreadable request body: {exc!r}")
    deadline = headers.get(_HDR_DEADLINE)
    kwargs = {}
    rid = headers.get(_HDR_REQUEST_ID)
    if rid:
        kwargs["request_id"] = rid
    # an unknown qos raises ValueError from FoldRequest itself -> 400
    return FoldRequest(
        seq=seq, msa=msa,
        priority=int(headers.get(_HDR_PRIORITY, "0") or 0),
        deadline_s=None if deadline is None else float(deadline),
        forwarded=headers.get(_HDR_FORWARDED, "0") == "1",
        qos=headers.get(_HDR_QOS) or "online",
        **kwargs)


def encode_raw_request(raw) -> tuple:
    """(body, headers) for one RAW submission (serve.features.
    RawFoldRequest): a JSON body — raw sequences are strings, which is
    exactly what JSON is for — with the same QoS headers as the token
    path plus Content-Type application/json, which is how the front
    door tells the two apart. Token-array inputs travel as int lists
    (the body stays self-contained; featurization happens replica-side
    either way)."""
    seq = raw.seq
    payload = {"seq": seq if isinstance(seq, str)
               else np.asarray(seq, np.int32).tolist()}
    if raw.msa is not None:
        msa = raw.msa
        if not isinstance(msa, np.ndarray) and len(msa) > 0 \
                and all(isinstance(r, str) for r in msa):
            payload["msa"] = list(msa)
        else:
            payload["msa"] = np.asarray(msa, np.int32).tolist()
    body = json.dumps(payload).encode("utf-8")
    headers = {_HDR_REQUEST_ID: raw.request_id,
               _HDR_PRIORITY: str(int(raw.priority)),
               _HDR_FORWARDED: "1" if raw.forwarded else "0",
               "Content-Type": "application/json"}
    if raw.deadline_s is not None:
        headers[_HDR_DEADLINE] = repr(float(raw.deadline_s))
    if getattr(raw, "qos", "online") != "online":
        headers[_HDR_QOS] = raw.qos
    return body, headers


def decode_raw_request(body: bytes, headers):
    """Parse + validate a raw (JSON) submit body into a
    serve.features.RawFoldRequest. Raises ValueError on anything wrong;
    the server turns that into a 400, never a featurize of garbage."""
    from alphafold2_tpu.serve.features import RawFoldRequest

    try:
        payload = json.loads(body.decode("utf-8"))
        seq = payload["seq"]
        # every malformed-content failure must surface as ValueError —
        # np.asarray raises TypeError on null/dict payloads, and a
        # TypeError escaping here turns a bad CLIENT payload into a
        # 500 that failover layers would retry across the whole fleet
        if not isinstance(seq, str):
            seq = np.asarray(seq, np.int32)
            if seq.ndim != 1 or seq.shape[0] == 0:
                raise ValueError(
                    f"raw seq must be a string or non-empty 1-D token "
                    f"list, got shape {seq.shape}")
        msa = payload.get("msa")
        if msa is not None and not (
                isinstance(msa, list) and msa
                and all(isinstance(r, str) for r in msa)):
            msa = np.asarray(msa, np.int32)
            if msa.ndim != 2:
                raise ValueError(
                    f"raw msa must be aligned strings or a 2-D token "
                    f"list, got shape {msa.shape}")
    except ValueError:
        raise
    except Exception as exc:
        raise ValueError(f"unreadable raw request body: {exc!r}")
    deadline = headers.get(_HDR_DEADLINE)
    kwargs = {}
    rid = headers.get(_HDR_REQUEST_ID)
    if rid:
        kwargs["request_id"] = rid
    # an unknown qos raises ValueError from RawFoldRequest itself -> 400
    return RawFoldRequest(
        seq=seq, msa=msa,
        priority=int(headers.get(_HDR_PRIORITY, "0") or 0),
        deadline_s=None if deadline is None else float(deadline),
        forwarded=headers.get(_HDR_FORWARDED, "0") == "1",
        qos=headers.get(_HDR_QOS) or "online",
        **kwargs)


def encode_arrays(coords=None, confidence=None) -> bytes:
    """The ONE coords/confidence npz framing every result body uses —
    terminal responses here and the front door's progressive 206
    (frontdoor._result) share it, so the two wire encodings cannot
    drift."""
    buf = io.BytesIO()
    arrays = {}
    if coords is not None:
        arrays["coords"] = np.asarray(coords, np.float32)
    if confidence is not None:
        arrays["confidence"] = np.asarray(confidence, np.float32)
    np.savez(buf, **arrays) if arrays else np.savez(
        buf, empty=np.zeros(0, np.float32))
    return buf.getvalue()


def encode_response(response: FoldResponse) -> tuple:
    """(body_bytes, headers) for one terminal FoldResponse. Arrays in
    the npz body, everything else in headers — a non-ok response is an
    empty npz plus headers."""
    body = encode_arrays(response.coords, response.confidence)
    headers = {_HDR_REQUEST_ID: response.request_id,
               _HDR_STATUS: response.status,
               _HDR_SOURCE: response.source,
               _HDR_ATTEMPTS: str(int(response.attempts)),
               "Content-Type": "application/octet-stream"}
    if response.bucket_len is not None:
        headers[_HDR_BUCKET] = str(int(response.bucket_len))
    # getattr: pre-ISSUE-9 peers' responses have no recycles field
    recycles = getattr(response, "recycles", None)
    if recycles is not None:
        headers[_HDR_RECYCLES] = str(int(recycles))
    # getattr: pre-ISSUE-19 peers' responses have no cascade fields
    tier = getattr(response, "tier", "")
    if tier:
        headers[_HDR_TIER] = tier
    if getattr(response, "escalated", False):
        headers[_HDR_ESCALATED] = "1"
    confidence_score = getattr(response, "confidence_score", None)
    if confidence_score is not None:
        headers[_HDR_CONFIDENCE] = repr(float(confidence_score))
    if response.error:
        # headers must be latin-1-safe single-line; errors are ours
        headers[_HDR_ERROR] = str(response.error)[:512].replace(
            "\n", " ").encode("ascii", "replace").decode("ascii")
    return body, headers


def decode_response(body: bytes, headers) -> FoldResponse:
    """Parse a result body/headers back into a FoldResponse. Raises
    ValueError on malformed payloads (a transport error, not a result)."""
    status = headers.get(_HDR_STATUS)
    if not status:
        raise ValueError("result missing X-Status header")
    coords = confidence = None
    try:
        with np.load(io.BytesIO(body)) as z:
            if "coords" in z.files:
                coords = np.asarray(z["coords"], np.float32)
            if "confidence" in z.files:
                confidence = np.asarray(z["confidence"], np.float32)
    except Exception as exc:
        raise ValueError(f"unreadable result body: {exc!r}")
    if status == "ok" and (coords is None or confidence is None
                           or coords.ndim != 2 or coords.shape[1] != 3
                           or confidence.shape != (coords.shape[0],)):
        raise ValueError("ok result fails shape validation")
    bucket = headers.get(_HDR_BUCKET)
    recycles = headers.get(_HDR_RECYCLES)
    confidence_score = headers.get(_HDR_CONFIDENCE)
    return FoldResponse(
        request_id=headers.get(_HDR_REQUEST_ID, "?"),
        status=status, coords=coords, confidence=confidence,
        bucket_len=None if bucket is None else int(bucket),
        error=headers.get(_HDR_ERROR) or None,
        source=headers.get(_HDR_SOURCE, "fold"),
        attempts=int(headers.get(_HDR_ATTEMPTS, "1") or 1),
        recycles=None if recycles is None else int(recycles),
        tier=headers.get(_HDR_TIER) or "",
        escalated=headers.get(_HDR_ESCALATED, "0") == "1",
        confidence_score=(None if confidence_score is None
                          else float(confidence_score)))


# -- transports ----------------------------------------------------------

class LocalTransport:
    """The in-process transport: today's behavior behind the new seam.

    Wraps a bound `Scheduler.submit` (or any callable with that
    signature); `submit()` returns the peer scheduler's OWN ticket, so
    coalescing, tracing, and settlement semantics are byte-for-byte
    what `ReplicaInfo.submit` gave the router before transports
    existed."""

    def __init__(self, submit, submit_raw=None):
        self._submit = submit
        # optional raw-path seam (the peer Scheduler.submit_raw bound
        # method): feature-key routing forwards RAW jobs through it so
        # the OWNER featurizes. Absent on legacy wirings — the router's
        # forward_raw then raises and the pool featurizes locally.
        self._submit_raw = submit_raw

    def submit(self, request: FoldRequest, trace=NULL_TRACE) -> FoldTicket:
        return self._submit(request)

    def submit_raw(self, raw, trace=NULL_TRACE) -> FoldTicket:
        if self._submit_raw is None:
            raise RuntimeError("transport has no raw submit path")
        return self._submit_raw(raw)

    def healthz(self) -> Optional[dict]:
        return None              # in-process: the registry IS the truth


class HttpTransport:
    """Forwarding client for one replica's `FrontDoorServer`.

    submit() POSTs the request and returns a LOCAL FoldTicket that a
    daemon poll thread resolves from the owner's long-poll result
    endpoint. Failure contract:

    - submit-time transport trouble RAISES (the scheduler's existing
      forward-error fallback folds locally — nothing was accepted);
    - post-submit transport trouble (owner died mid-fold, partition,
      poll exhausted) resolves the ticket `status="error"` with the
      `rpc_transport` marker — the scheduler's failover path re-folds
      locally and counts `fleet_failovers_total`;
    - a terminal result resolves the ticket verbatim (status, source,
      attempts, error all travel).

    poll_wait_s is the server-side long-poll window per request;
    poll_budget_s bounds the total wait before the transport gives up
    and error-resolves with the transport marker (a hung owner must
    not hold forwarded tickets forever — the owner's own watchdog and
    deadline machinery should terminate folds long before this fires).

    One daemon poll thread (and one connection per poll round — the
    server speaks HTTP/1.0) per forwarded request is deliberate, the
    same call the peer cache tier makes: folds are seconds-granular
    and in-flight forwards are bounded by the sender's queue_limit, so
    thread/connect cost is noise next to one fold — and a shared
    multiplexing poller would be wedged by exactly the hung-peer case
    this transport exists to survive. Revisit only if forwarding ever
    carries sub-100ms work.
    """

    def __init__(self, base_url: str, timeout_s: float = 5.0,
                 poll_wait_s: float = 10.0, poll_budget_s: float = 600.0,
                 rollout=None,
                 metrics: Optional[MetricsRegistry] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.poll_wait_s = float(poll_wait_s)
        self.poll_budget_s = float(poll_budget_s)
        self.rollout = rollout       # optional RolloutState: stamps tag
        reg = metrics or get_registry()
        self._m_rpc = reg.counter(
            "fleet_rpc_requests_total",
            "front-door RPCs by route and outcome (client side)",
            ("route", "outcome"))
        self._m_cancels = reg.counter(
            "fleet_remote_cancels_total",
            "best-effort cancels sent for timed-out forwarded tickets")
        self.cancels = 0

    # -- plumbing --------------------------------------------------------

    def _tag(self) -> str:
        return self.rollout.tag if self.rollout is not None else ""

    def _post(self, path: str, body: bytes, headers: dict,
              timeout: Optional[float] = None):
        req = urlrequest.Request(self.base_url + path, data=body,
                                 headers=headers, method="POST")
        return urlrequest.urlopen(req, timeout=timeout or self.timeout_s)

    # -- protocol --------------------------------------------------------

    def submit(self, request: FoldRequest, trace=NULL_TRACE) -> FoldTicket:
        """One forwarding hop. Raises on submit-time transport failure
        (caller folds locally); otherwise returns a ticket the poll
        thread resolves.

        The `rpc` span covers the WHOLE exchange — submit POST through
        terminal pickup — recorded as one completed interval (add_span)
        at whichever end the exchange reaches, with an `outcome` attr:
        "ok", "submit_error", "transport_death" (owner died/partitioned
        /restarted mid-fold — stamped BEFORE the ticket resolves, so a
        failover re-submission never inherits a dangling open span; the
        ISSUE-15 orphan fix), "poll_exhausted", or "cancelled". With
        tracing on, the request's TraceContext rides the submit headers
        and the span carries the matching `span_id`, so the receiving
        replica's continued trace stitches under exactly this span."""
        ctx = trace.wire_context()
        body = encode_request(request)
        headers = request_headers(request, tag=self._tag(), context=ctx)
        t0 = time.monotonic()
        try:
            with self._post("/v1/submit", body, headers) as resp:
                payload = json.loads(resp.read().decode("utf-8"))
            remote_ticket = payload["ticket"]
        except Exception:
            self._end_rpc(trace, t0, "submit", ctx, "submit_error")
            self._m_rpc.inc(route="submit", outcome="error")
            raise
        self._m_rpc.inc(route="submit", outcome="ok")
        return self._polled_ticket(remote_ticket, request, trace, t0,
                                   "submit", ctx)

    def submit_raw(self, raw, trace=NULL_TRACE) -> FoldTicket:
        """One RAW forwarding hop (feature-key routing, ISSUE 10): the
        owner featurizes replica-side and folds. Same failure contract
        (and rpc-span/trace-context lifecycle) as submit() —
        submit-time trouble raises (caller featurizes locally),
        post-submit trouble resolves with the transport marker (the
        feature pool then fails over to local featurization)."""
        ctx = trace.wire_context()
        body, headers = encode_raw_request(raw)
        tag = self._tag()
        if tag:
            headers[_HDR_TAG] = tag
        if ctx is not None:
            headers.update(ctx.to_headers())
        t0 = time.monotonic()
        try:
            with self._post("/v1/submit", body, headers) as resp:
                payload = json.loads(resp.read().decode("utf-8"))
            remote_ticket = payload["ticket"]
        except Exception:
            self._end_rpc(trace, t0, "submit_raw", ctx, "submit_error")
            self._m_rpc.inc(route="submit_raw", outcome="error")
            raise
        self._m_rpc.inc(route="submit_raw", outcome="ok")
        return self._polled_ticket(remote_ticket, raw, trace, t0,
                                   "submit_raw", ctx)

    def _end_rpc(self, trace, t0: float, route: str,
                 ctx: Optional[TraceContext], outcome: str):
        """Record the exchange's rpc span, exactly once per exchange,
        on every terminal path. add_span (a completed interval), never
        begin/end: the span can't be orphaned open by a dead owner, and
        a late poll-thread recording after the trace finished is
        silently dropped instead of colliding with a failover
        re-submission's fresh exchange."""
        attrs = {"peer": self.base_url, "route": route,
                 "outcome": outcome}
        if ctx is not None:
            attrs["span_id"] = ctx.parent_span_id
        trace.add_span("rpc", t0, time.monotonic(), **attrs)

    def _polled_ticket(self, remote_ticket: str, request, trace, t0,
                       route: str,
                       ctx: Optional[TraceContext]) -> FoldTicket:
        """Local ticket resolved by a daemon long-poll thread — the one
        pickup path both the token and raw submit hops share. `request`
        only needs a request_id (FoldRequest and RawFoldRequest both
        qualify)."""
        ticket = FoldTicket(request.request_id)
        # result(timeout=) expiry on the caller's side sends the owner a
        # best-effort cancel so the parked result is dropped, not leaked
        ticket._timeout_callback = lambda: self.cancel(remote_ticket)
        threading.Thread(
            target=self._poll,
            args=(remote_ticket, request, ticket, trace, t0, route, ctx),
            name=f"rpc-poll-{request.request_id}", daemon=True).start()
        return ticket

    def _transport_error(self, request: FoldRequest, detail: str
                         ) -> FoldResponse:
        return FoldResponse(
            request_id=request.request_id, status="error",
            error=f"{RPC_TRANSPORT_MARKER}: {detail}")

    def _poll(self, remote_ticket: str, request: FoldRequest,
              ticket: FoldTicket, trace, t0: float, route: str,
              ctx: Optional[TraceContext]):
        """Long-poll the owner until terminal; resolve the local ticket
        exactly once, with the transport marker on any failure. The
        exchange's rpc span is recorded (with its outcome) BEFORE the
        ticket resolves, so any failover path the resolution triggers
        re-submits against a trace whose dead-owner span is already
        closed — never auto-closed at finish, never spanning the
        retry."""
        deadline = time.monotonic() + self.poll_budget_s
        misses = 0
        while time.monotonic() < deadline:
            if ticket.done():
                # cancelled locally meanwhile (result-timeout path)
                self._end_rpc(trace, t0, route, ctx, "cancelled")
                return
            url = (f"{self.base_url}/v1/result/"
                   f"{urlparse.quote(remote_ticket, safe='')}"
                   f"?wait_s={self.poll_wait_s}")
            try:
                with urlrequest.urlopen(
                        url,
                        timeout=self.poll_wait_s + self.timeout_s) as resp:
                    if resp.status == 204:
                        misses += 1
                        continue     # still folding; poll again
                    body = resp.read()
                    response = decode_response(body, resp.headers)
            except urlerror.HTTPError as exc:
                outcome = ("unknown_ticket" if exc.code == 404
                           else "error")
                self._m_rpc.inc(route="result", outcome=outcome)
                # 404 = the owner restarted and forgot the ticket; both
                # cases mean the transport lost the fold, not the fold
                # failed — failover-eligible
                self._end_rpc(trace, t0, route, ctx, "transport_death")
                ticket._resolve(self._transport_error(
                    request, f"result fetch failed: HTTP {exc.code}"))
                return
            except Exception as exc:
                self._m_rpc.inc(route="result", outcome="error")
                self._end_rpc(trace, t0, route, ctx, "transport_death")
                ticket._resolve(self._transport_error(
                    request, f"result fetch failed: {exc!r}"))
                return
            self._m_rpc.inc(route="result", outcome="ok")
            self._end_rpc(trace, t0, route, ctx, "ok")
            ticket._resolve(response)
            return
        self._m_rpc.inc(route="result", outcome="poll_exhausted")
        self.cancel(remote_ticket)
        self._end_rpc(trace, t0, route, ctx, "poll_exhausted")
        ticket._resolve(self._transport_error(
            request, f"poll budget {self.poll_budget_s}s exhausted "
                     f"after {misses} empty polls"))

    def cancel(self, remote_ticket: str) -> bool:
        """Best-effort: tell the owner to drop the parked result."""
        try:
            path = ("/v1/cancel/"
                    + urlparse.quote(remote_ticket, safe=""))
            with self._post(path, b"", {}) as resp:
                ok = resp.status == 200
        except Exception:
            ok = False
        self.cancels += 1
        self._m_cancels.inc()
        self._m_rpc.inc(route="cancel", outcome="ok" if ok else "error")
        return ok

    def healthz(self) -> Optional[dict]:
        """The owner's /healthz payload, or None when unreachable."""
        try:
            with urlrequest.urlopen(self.base_url + "/healthz",
                                    timeout=self.timeout_s) as resp:
                if resp.status != 200:
                    return None
                return json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None


def transport_of(info) -> Optional[object]:
    """The forwarding transport for one `ReplicaInfo`: the explicit
    `transport` when set, else the legacy `submit` callable wrapped in
    a LocalTransport (so pre-transport callers and tests that assign
    `info.submit` keep exactly their old semantics), else None."""
    if info is None:
        return None
    tr = getattr(info, "transport", None)
    if tr is not None:
        return tr
    if info.submit is not None:
        return LocalTransport(info.submit)
    return None
