"""Consistent-hash request routing: fold_key -> owner replica.

ParaFold's observation is that AlphaFold serving is embarrassingly
parallel across sequences — the fleet-level win is routing: if every
replica behind a dumb load balancer sees a uniform slice of a Zipf-head
workload, each of them folds the head sequences independently. Mapping
each `fold_key` to ONE owner replica makes the whole fleet coalesce a
hot key on a single leader (the owner's InflightRegistry) and gives its
peer cache entry a well-known home.

The ring is classic consistent hashing: `vnodes` virtual points per
replica (blake2b of "replica#i"), keys located by bisect on the sorted
point list, ownership = first point at/after the key walking clockwise.
Adding/removing one replica moves ~1/N of the keyspace; the ring is
rebuilt lazily whenever the registry's membership epoch changes and is
otherwise one integer compare on the submit hot path.

Routing is advisory, never load-bearing for correctness:

- `route()` skips unhealthy owners (walks the ring to the next healthy
  point) and falls back to LOCAL when nobody else is routable — a
  partitioned replica degrades to exactly the pre-fleet single-host
  behavior, it never errors a request because of fleet state;
- forwarding is BOUNDED to one hop: a forwarded request carries
  `FoldRequest.forwarded=True` and the receiving scheduler serves it
  locally no matter what its own ring says, so two replicas with
  momentarily divergent membership views can bounce a request at most
  once, never loop it.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

from alphafold2_tpu.fleet.registry import ReplicaRegistry
from alphafold2_tpu.fleet.rpc import transport_of
from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry
from alphafold2_tpu.obs.trace import NULL_TRACE


def _point(s: str) -> int:
    """64-bit ring position. blake2b, not hash(): stable across
    processes so every replica computes the same ring."""
    return int.from_bytes(
        hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest(), "big")


def static_owner_for(key: str, replica_ids, vnodes: int = 64
                     ) -> Optional[str]:
    """Pure consistent-hash owner over a STATIC membership list — the
    identical blake2b/vnode scheme ConsistentHashRouter builds over a
    live registry, computable client-side with no registry at all.
    Campaign drivers (tools/bulk_submit.py --fleet, ISSUE 19) use it
    to shard a manifest exactly where the data plane's ring will look
    for each fold key, so coalescing leadership, peer-cache homes, and
    checkpoint locality all line up with the submit target. Returns
    None on an empty membership list."""
    ids = list(replica_ids)
    if not ids:
        return None
    pairs = sorted((_point(f"{rid}#{i}"), rid)
                   for rid in ids for i in range(int(vnodes)))
    points = [p for p, _ in pairs]
    start = bisect.bisect_left(points, _point(key)) % len(points)
    return pairs[start][1]


@dataclass
class RouteDecision:
    """Where one key should fold and why."""

    owner_id: Optional[str]   # ring owner after health walk; None = no ring
    is_local: bool            # serve on this replica
    reason: str               # "local_owner" | "forward" | "no_peers" |
    #                           "owner_down_local_fallback" | "not_forwardable"


class ConsistentHashRouter:
    """Hash-ring view of one ReplicaRegistry, bound to one replica.

    self_id: the replica this router routes FOR (its local-fallback
        target and its notion of "is_local").
    vnodes: virtual points per replica; 64 keeps the max/min keyspace
        share within ~30% for small fleets without making rebuilds
        noticeable.
    """

    def __init__(self, registry: ReplicaRegistry, self_id: str,
                 vnodes: int = 64,
                 metrics: Optional[MetricsRegistry] = None):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.registry = registry
        self.self_id = self_id
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._ring_epoch = -1
        self._points: List[int] = []
        self._owners: List[str] = []
        reg = metrics or get_registry()
        self._m_forwards = reg.counter(
            "fleet_forwards_total",
            "requests forwarded to their ring owner", ("peer",))
        self._m_fallbacks = reg.counter(
            "fleet_forward_fallback_total",
            "routed requests served locally despite a remote owner",
            ("reason",))
        self._m_routed = reg.counter(
            "fleet_routed_total", "routing decisions", ("decision",))

    # -- ring ------------------------------------------------------------

    def _ring(self) -> Tuple[List[int], List[str]]:
        """Current (points, owners), rebuilt iff the membership epoch
        moved. The rebuild is O(members * vnodes log ...), off the hot
        path for a stable fleet."""
        epoch = self.registry.epoch
        with self._lock:
            if epoch == self._ring_epoch:
                return self._points, self._owners
        pairs = sorted(
            (_point(f"{rid}#{i}"), rid)
            for rid in self.registry.member_ids()
            for i in range(self.vnodes))
        points = [p for p, _ in pairs]
        owners = [rid for _, rid in pairs]
        with self._lock:
            self._ring_epoch = epoch
            self._points, self._owners = points, owners
            return self._points, self._owners

    def owner_for(self, key: str) -> Optional[str]:
        """Healthy ring owner of `key` (clockwise walk skipping
        unhealthy replicas); None when the ring is empty or nobody is
        healthy."""
        points, owners = self._ring()
        if not points:
            return None
        start = bisect.bisect_left(points, _point(key)) % len(points)
        seen = set()
        for i in range(len(points)):
            rid = owners[(start + i) % len(points)]
            if rid in seen:
                continue
            seen.add(rid)
            if self.registry.is_healthy(rid):
                return rid
        return None

    # -- decisions -------------------------------------------------------

    def route(self, key: str) -> RouteDecision:
        """Decide where `key` folds, from this replica's seat."""
        owner = self.owner_for(key)
        if owner is None:
            decision = RouteDecision(None, True, "no_peers")
        elif owner == self.self_id:
            decision = RouteDecision(owner, True, "local_owner")
        else:
            info = self.registry.get(owner)
            if transport_of(info) is None:
                # owner routable for peer-cache purposes but exposes no
                # forwarding transport: fold locally, its cache tier is
                # still reachable through the peer client
                decision = RouteDecision(owner, True, "not_forwardable")
            else:
                decision = RouteDecision(owner, False, "forward")
        self._m_routed.inc(decision="local" if decision.is_local
                           else "forward")
        return decision

    def forward(self, owner_id: str, request, trace=NULL_TRACE):
        """Hand `request` to its owner through its transport
        (fleet.rpc: LocalTransport in-process, HttpTransport across
        machines); returns a FoldTicket resolving to the remote result.
        Raises when the owner vanished, has no transport, or the
        transport refuses at submit time — the caller (Scheduler) then
        falls back to folding locally."""
        transport = transport_of(self.registry.get(owner_id))
        if transport is None:
            raise RuntimeError(f"replica {owner_id!r} not forwardable")
        ticket = transport.submit(request, trace=trace)
        self._m_forwards.inc(peer=owner_id)
        return ticket

    def forward_raw(self, owner_id: str, raw, trace=NULL_TRACE):
        """Hand a RAW job (serve.features.RawFoldRequest) to its
        FEATURE-key owner, which featurizes replica-side and folds
        (ISSUE 10). Raises when the owner vanished, has no transport,
        the transport has no raw path (legacy wiring), or submit is
        refused — the caller (serve.features.FeaturePool) then
        featurizes locally. The ring is key-agnostic, so the same hash
        walk that places fold keys places feature keys."""
        transport = transport_of(self.registry.get(owner_id))
        if transport is None or not hasattr(transport, "submit_raw"):
            raise RuntimeError(f"replica {owner_id!r} not raw-forwardable")
        ticket = transport.submit_raw(raw, trace=trace)
        self._m_forwards.inc(peer=owner_id)
        return ticket

    def note_fallback(self, reason: str):
        """Record a routed-remote request that folded locally anyway
        (owner down mid-forward, transport error, remote backpressure)."""
        self._m_fallbacks.inc(reason=reason)

    def snapshot(self) -> dict:
        points, owners = self._ring()
        share = {}
        for rid in set(owners):
            share[rid] = owners.count(rid)
        return {"self_id": self.self_id,
                "ring_points": len(points),
                "ring_epoch": self._ring_epoch,
                "vnode_share": share}
