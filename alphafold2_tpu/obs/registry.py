"""Process-wide metrics registry: counters, gauges, histograms.

Before this module the repo had three uncoordinated telemetry surfaces
(`StepTimer`, `ServeMetrics`' private dicts, `MetricsLogger`), so the
same quantity — a latency, a cache hit — was counted three slightly
different ways and none of them were scrapeable. The registry is the
one sink they all report into:

- `Counter` / `Gauge` / `Histogram`, all thread-safe, all supporting
  Prometheus-style labels (`counter.inc(1, outcome="shed")`);
- histograms use fixed exponential latency buckets (1 ms .. ~17 min
  doublings) so two histograms are always mergeable, plus a bounded
  reservoir of raw observations so `Histogram.percentile` can answer
  with `utils.profiling.percentile` — the repo's single quantile
  implementation — instead of a second, subtly-different bucket
  interpolation;
- `get_registry()` returns the process-wide default; components take a
  `registry=` parameter for test isolation but default to it, so one
  Prometheus scrape (obs/export.py) sees serve, cache, and train
  together.

Metric creation is get-or-create by name: two `FoldCache` instances in
one process share `fold_cache_hits_total`, which is exactly the
process-level view an exporter wants. Per-instance views (e.g. one
scheduler's `serve_stats()`) keep their own unregistered metric
objects; both are the same classes, so there is one implementation of
bucketing and quantiles in the repo.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from alphafold2_tpu.utils.profiling import percentile

# Fixed exponential latency buckets (seconds): 1 ms doubling to ~1048 s.
# Fixed — not configurable per metric call — so histograms from any two
# processes/components can be merged bucket-for-bucket.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    0.001 * (2.0 ** i) for i in range(21))

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(label_names: Tuple[str, ...], labels: dict) -> _LabelKey:
    if set(labels) != set(label_names):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(label_names)}")
    return tuple((k, str(labels[k])) for k in label_names)


class Metric:
    """Shared shell: name, help text, declared label names."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()


class Counter(Metric):
    """Monotonic count. `inc(n, **labels)`."""

    kind = "counter"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, n: float = 1, **labels):
        if n < 0:
            raise ValueError("Counter.inc() must be >= 0")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._values.get(key, 0)

    def samples(self) -> List[dict]:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._values.items())]


class Gauge(Metric):
    """Last-write-wins instantaneous value. `set(v, **labels)`."""

    kind = "gauge"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[_LabelKey, float] = {}

    def set(self, value: float, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, n: float = 1, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._values.get(key, 0)

    def samples(self) -> List[dict]:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._values.items())]


class _HistChild:
    __slots__ = ("bucket_counts", "sum", "count", "reservoir")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * (n_buckets + 1)   # +1 = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.reservoir: List[float] = []


class Histogram(Metric):
    """Exponential-bucket histogram + bounded raw reservoir.

    The buckets are the mergeable/exportable form (Prometheus `le`
    semantics: cumulative at export time); the reservoir (a sliding
    window of the most recent `reservoir` observations) is what
    `percentile()` answers from, via `utils.profiling.percentile` — so
    in-process tail latencies are exact over the window rather than
    bucket-interpolated, and every p50/p90/p99 in the repo is computed
    by the same function.
    """

    kind = "histogram"

    def __init__(self, name, help="", label_names=(),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                 reservoir: int = 4096):
        super().__init__(name, help, label_names)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket edge")
        self.buckets = bs
        self.reservoir_size = max(0, int(reservoir))
        self._children: Dict[_LabelKey, _HistChild] = {}

    def _child(self, labels: dict) -> _HistChild:
        key = _label_key(self.label_names, labels)
        child = self._children.get(key)
        if child is None:
            child = self._children.setdefault(key,
                                              _HistChild(len(self.buckets)))
        return child

    def observe(self, value: float, **labels):
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            child = self._child(labels)
            child.bucket_counts[idx] += 1
            child.sum += value
            child.count += 1
            if self.reservoir_size:
                res = child.reservoir
                res.append(value)
                if len(res) > self.reservoir_size:
                    del res[: len(res) - self.reservoir_size]

    def percentile(self, q: float, **labels) -> float:
        """Quantile over the raw reservoir window, via the repo's one
        percentile implementation (utils.profiling.percentile)."""
        with self._lock:
            child = self._children.get(_label_key(self.label_names, labels))
            values = list(child.reservoir) if child is not None else []
        return percentile(values, q)

    def count(self, **labels) -> int:
        with self._lock:
            child = self._children.get(_label_key(self.label_names, labels))
            return child.count if child is not None else 0

    def sum(self, **labels) -> float:
        with self._lock:
            child = self._children.get(_label_key(self.label_names, labels))
            return child.sum if child is not None else 0.0

    def samples(self) -> List[dict]:
        with self._lock:
            out = []
            for key, child in sorted(self._children.items()):
                cum, buckets = 0, {}
                for edge, n in zip(self.buckets, child.bucket_counts):
                    cum += n
                    buckets[f"{edge:g}"] = cum
                buckets["+Inf"] = cum + child.bucket_counts[-1]
                out.append({"labels": dict(key), "sum": child.sum,
                            "count": child.count, "buckets": buckets})
            return out


class MetricsRegistry:
    """Named metric store; creation is get-or-create and type-checked."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, Metric]" = {}

    def _get_or_create(self, cls, name, help, label_names, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                if tuple(label_names) != existing.label_names:
                    raise ValueError(
                        f"metric {name!r} labels {existing.label_names} "
                        f"!= requested {tuple(label_names)}")
                # bucket edges are schema: two components disagreeing
                # would silently mis-bucket one of them (reservoir size
                # is only an in-process window bound; first-registration
                # wins there without complaint)
                want = kwargs.get("buckets")
                if want is not None and tuple(
                        sorted(float(b) for b in want)) \
                        != existing.buckets:
                    raise ValueError(
                        f"histogram {name!r} buckets {existing.buckets} "
                        f"!= requested {tuple(want)}")
                return existing
            metric = cls(name, help, label_names, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  reservoir: int = 4096) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets, reservoir=reservoir)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """JSON-ready view: {name: {kind, help, label_names, samples}}."""
        return {
            m.name: {"kind": m.kind, "help": m.help,
                     "label_names": list(m.label_names),
                     "samples": m.samples()}
            for m in self.metrics()
        }


_default_registry = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry serve/cache/train report into."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (tests / embedding apps). Returns the
    previous registry so callers can restore it."""
    global _default_registry
    with _default_lock:
        prev = _default_registry
        _default_registry = registry
        return prev
