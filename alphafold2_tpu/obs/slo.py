"""SLO engine: declarative per-QoS-class objectives over the registry.

The serving stack already emits everything an autoscaler needs —
per-bucket latency histograms, terminal-status counters, idle
fractions — but raw counters are not a control signal: "scale up" is a
decision about an OBJECTIVE (p99 under a target, availability above a
floor) and how fast its error budget is burning. This module turns the
`MetricsRegistry`'s own metrics into that signal surface (ISSUE 15):

- `SLOClass`: one QoS class's objective — a latency percentile target
  for a set of length buckets plus an availability floor over terminal
  statuses;
- `SLOPolicy`: the declarative set of classes + the error-budget
  window; `SLOPolicy.parse("32=400,all=2000")` is the shared CLI
  surface (`serve_loadtest --slo`, fleet configs);
- `SLOEngine`: computes windowed attainment, error-budget remaining,
  and burn rate per class from `serve_request_latency_seconds`
  (histogram, per bucket_len) and `serve_requests_total` (counter, per
  outcome) — the metrics `ServeMetrics` already mirrors into the
  registry, so the engine adds zero recording cost to the serving hot
  path. Results land in `serve_stats()["slo"]` (via `Scheduler(slo=)`)
  and in `slo_*` gauges every `/metrics` scrape carries.

Windowing: registry counters are cumulative, so the engine keeps a
small ring of timestamped snapshots and differences the newest against
the oldest inside the window — burn rate answers "how fast is the
budget going NOW", not "since boot". Latency targets are quantized to
the histogram's fixed exponential bucket edges (the report names the
quantized edge, so the approximation is visible, never silent).

Budget math (the standard SRE formulation): with an objective of
percentile p and window slow-fraction s, the allowed slow fraction is
a = 1 - p/100; burn_rate = s / a (1.0 = burning exactly at budget,
> 1 = the objective fails if sustained); error budget remaining =
1 - burn_rate (negative = overspent this window). Availability uses
the same shape over bad terminal statuses.

Off by default everywhere: constructing an engine mints the `slo_*`
gauges; a `Scheduler` without `slo=` leaves serve_stats() and the
registry metric-name set byte-identical.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry

# terminal statuses that spend availability budget unless a class
# overrides: an error or a poisoned quarantine is a failed promise to
# the caller; shed/rejected/degraded are explicit load-management
# refusals (count them by listing them in bad_statuses)
DEFAULT_BAD_STATUSES: Tuple[str, ...] = ("error", "poisoned")

_LATENCY_METRIC = "serve_request_latency_seconds"
_OUTCOME_METRIC = "serve_requests_total"
# the express lane's own series (ISSUE 19): the scheduler tallies
# qos="express" traffic under these IN ADDITION to the shared serve_*
# pair, so an express SLO class reads the express tail without the
# online majority diluting it
_EXPRESS_LATENCY_METRIC = "serve_express_latency_seconds"
_EXPRESS_OUTCOME_METRIC = "serve_express_requests_total"


def burn_rate(bad_frac: float, allowed_frac: float) -> float:
    """bad/allowed, the SRE burn rate: 1.0 = spending the error budget
    exactly as fast as the objective allows. A zero-allowance
    objective (percentile 100 / availability 1.0) burns infinitely on
    the first violation — surfaced as a large finite number so JSON
    and gauges stay well-formed."""
    if bad_frac <= 0.0:
        return 0.0
    if allowed_frac <= 0.0:
        return 1e9
    return bad_frac / allowed_frac


@dataclass(frozen=True)
class SLOClass:
    """One QoS class's objective.

    name: report/gauge label ("bucket32", "fleet", "interactive").
    target_s: latency target at `percentile` (quantized to the
        histogram's bucket edges at evaluation time). None = no
        latency objective (availability-only class).
    percentile: which tail the target governs (99 = p99).
    buckets: bucket_len edges this class covers; () = every bucket.
    availability: floor on the good-terminal fraction; None = no
        availability objective.
    bad_statuses: terminal outcomes that spend availability budget.
    latency_metric / outcome_metric: the registry series this class
        evaluates over. The defaults are the shared serve_* pair every
        request lands in; the express class (ISSUE 19) points at the
        serve_express_* pair so its burn rate answers for ONLY the
        express tail. Any override must keep ServeMetrics' label
        schema — histogram labeled by bucket_len, counter by outcome.
    """

    name: str
    target_s: Optional[float] = None
    percentile: float = 99.0
    buckets: Tuple[int, ...] = ()
    availability: Optional[float] = 0.99
    bad_statuses: Tuple[str, ...] = DEFAULT_BAD_STATUSES
    latency_metric: str = _LATENCY_METRIC
    outcome_metric: str = _OUTCOME_METRIC

    def __post_init__(self):
        if not self.name:
            raise ValueError("SLOClass needs a name")
        if not self.latency_metric or not self.outcome_metric:
            raise ValueError(
                "latency_metric/outcome_metric must be non-empty")
        if self.target_s is not None and self.target_s <= 0:
            raise ValueError(f"target_s must be > 0, got {self.target_s}")
        if not (0.0 < self.percentile <= 100.0):
            raise ValueError(
                f"percentile must be in (0, 100], got {self.percentile}")
        if self.availability is not None \
                and not (0.0 < self.availability <= 1.0):
            raise ValueError(
                f"availability must be in (0, 1], got "
                f"{self.availability}")

    def covers(self, bucket_len: int) -> bool:
        return not self.buckets or int(bucket_len) in self.buckets


@dataclass
class SLOPolicy:
    """The declarative objective set + the error-budget window."""

    classes: List[SLOClass] = field(default_factory=list)
    window_s: float = 300.0

    def __post_init__(self):
        if self.window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {self.window_s}")
        names = [c.name for c in self.classes]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate SLO class names: {names}")

    @classmethod
    def parse(cls, spec: str, window_s: float = 300.0,
              percentile: float = 99.0,
              availability: float = 0.99) -> "SLOPolicy":
        """The one CLI surface (`serve_loadtest --slo`, procfleet
        configs): comma-separated `CLASS=P99_MS` items where CLASS is
        a bucket edge (int — the class covers that bucket, named
        "bucket<edge>"), `all`/`fleet` (every bucket, named as
        given), or `express` (every bucket, evaluated over the
        serve_express_* series — the express lane's own SLO class,
        ISSUE 19). The value is the latency target in MILLISECONDS, or
        `auto` (target_s None — a driver-side calibration hook;
        SLOEngine evaluates such a class availability-only, as
        procfleet replicas fed the driver's auto spec rely on).
        Raises ValueError on anything malformed — a typo'd objective
        must fail loudly, not silently monitor nothing."""
        classes = []
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(
                    f"SLO item {item!r} is not CLASS=P99_MS")
            key, _, value = item.partition("=")
            key, value = key.strip(), value.strip()
            if value.lower() == "auto":
                target_s = None
            else:
                try:
                    target_s = float(value) / 1000.0
                except ValueError:
                    raise ValueError(
                        f"SLO target {value!r} is not milliseconds "
                        f"or 'auto'")
            if key.lower() in ("all", "fleet"):
                classes.append(SLOClass(
                    name=key.lower(), target_s=target_s,
                    percentile=percentile, buckets=(),
                    availability=availability))
            elif key.lower() == "express":
                classes.append(SLOClass(
                    name="express", target_s=target_s,
                    percentile=percentile, buckets=(),
                    availability=availability,
                    latency_metric=_EXPRESS_LATENCY_METRIC,
                    outcome_metric=_EXPRESS_OUTCOME_METRIC))
            else:
                try:
                    edge = int(key)
                except ValueError:
                    raise ValueError(
                        f"SLO class {key!r} is not a bucket edge or "
                        f"'all'")
                classes.append(SLOClass(
                    name=f"bucket{edge}", target_s=target_s,
                    percentile=percentile, buckets=(edge,),
                    availability=availability))
        if not classes:
            raise ValueError(f"empty SLO spec {spec!r}")
        return cls(classes=classes, window_s=window_s)


def quantize_target(target_s: float, edges) -> float:
    """The histogram edge a latency target evaluates at (nearest of
    the fixed exponential edges — visible in the report as
    `target_quantized_s`, so the approximation is never silent)."""
    return min(edges, key=lambda e: abs(e - float(target_s)))


def evaluate_class(cls_: SLOClass, good: int, total: int,
                   bad_terminal: int, total_terminal: int,
                   quantized_target_s: Optional[float] = None) -> dict:
    """The one budget-math implementation both the registry engine and
    the loadtest driver's offline window evaluation share: windowed
    counts in, attainment/burn/budget out."""
    out: dict = {"requests": int(total),
                 "terminal": int(total_terminal)}
    if cls_.target_s is not None:
        attainment = good / total if total else 1.0
        allowed = 1.0 - cls_.percentile / 100.0
        slow = 1.0 - attainment
        rate = burn_rate(slow, allowed)
        out["latency"] = {
            "percentile": cls_.percentile,
            "target_s": cls_.target_s,
            "target_quantized_s": (quantized_target_s
                                   if quantized_target_s is not None
                                   else cls_.target_s),
            "attainment": attainment,
            "allowed_slow_fraction": allowed,
            "burn_rate": rate,
            "budget_remaining": 1.0 - rate,
            "met": attainment >= cls_.percentile / 100.0,
        }
    if cls_.availability is not None:
        observed = (1.0 - bad_terminal / total_terminal
                    if total_terminal else 1.0)
        allowed = 1.0 - cls_.availability
        bad_frac = 1.0 - observed
        rate = burn_rate(bad_frac, allowed)
        out["availability"] = {
            "target": cls_.availability,
            "observed": observed,
            "bad": int(bad_terminal),
            "bad_statuses": list(cls_.bad_statuses),
            "burn_rate": rate,
            "budget_remaining": 1.0 - rate,
            "met": observed >= cls_.availability,
        }
    out["ok"] = all(section.get("met", True)
                    for section in (out.get("latency"),
                                    out.get("availability"))
                    if section is not None)
    return out


class SLOEngine:
    """Windowed SLO evaluation over a MetricsRegistry.

    policy: the SLOPolicy. A class parsed with target `auto`
        (target_s None) evaluates as availability-only here — auto
        latency targets are a driver-side calibration hook
        (serve_loadtest), not a registry feature.
    registry: the registry whose `serve_request_latency_seconds` /
        `serve_requests_total` this engine reads AND whose `slo_*`
        gauges it sets (None = the process default — the same registry
        a `/metrics` scrape renders, so the gauges land next to the
        metrics they summarize).
    clock: injectable monotonic clock (tests drive windows without
        sleeping).
    """

    def __init__(self, policy: SLOPolicy,
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.monotonic):
        for c in policy.classes:
            if c.target_s is None and c.availability is None:
                raise ValueError(
                    f"SLO class {c.name!r} has neither a latency nor "
                    f"an availability objective")
        self.policy = policy
        self._clock = clock
        reg = registry or get_registry()
        self._reg = reg
        # the read side: get-or-create with the exact label schema
        # ServeMetrics declares, so engine-before-scheduler and
        # scheduler-before-engine construction orders both work. One
        # handle pair per DISTINCT metric pair the policy references —
        # the shared serve_* pair for ordinary classes, the
        # serve_express_* pair for an express class (ISSUE 19)
        self._h_latency: Dict[str, object] = {}
        self._c_outcomes: Dict[str, object] = {}
        for c in policy.classes:
            if c.latency_metric not in self._h_latency:
                self._h_latency[c.latency_metric] = reg.histogram(
                    c.latency_metric,
                    "submit-to-resolve latency of served requests",
                    ("bucket_len",))
            if c.outcome_metric not in self._c_outcomes:
                self._c_outcomes[c.outcome_metric] = reg.counter(
                    c.outcome_metric,
                    "terminal request outcomes by state", ("outcome",))
        # the signal surface: one gauge family per quantity, labeled
        # by objective (class) name
        self._g_attain = reg.gauge(
            "slo_latency_attainment",
            "windowed fraction of served requests within the class's "
            "latency target", ("objective",))
        self._g_lat_burn = reg.gauge(
            "slo_latency_burn_rate",
            "windowed latency error-budget burn rate (1.0 = burning "
            "exactly at budget)", ("objective",))
        self._g_budget = reg.gauge(
            "slo_error_budget_remaining",
            "windowed error budget remaining (min of the class's "
            "latency and availability budgets; negative = overspent)",
            ("objective",))
        self._g_avail = reg.gauge(
            "slo_availability",
            "windowed good-terminal fraction", ("objective",))
        self._g_avail_burn = reg.gauge(
            "slo_availability_burn_rate",
            "windowed availability error-budget burn rate",
            ("objective",))
        self._lock = threading.Lock()
        # (t, {"lat": {metric: {bucket_len: {edge_str: cum,
        #                                    "__count": n}}},
        #      "out": {metric: {outcome: n}}}) — newest last, keyed by
        # metric name since classes may read different series. Seeded
        # with an EMPTY boot snapshot so the first report() covers
        # boot→now instead of differencing a snapshot against itself
        # (zero requests on a server that just folded a hundred)
        self._samples: deque = deque(
            [(self._clock(), {"lat": {}, "out": {}})])

    # -- snapshots ---------------------------------------------------------

    def _counts(self) -> dict:
        lat: Dict[str, dict] = {}
        for metric, hist in self._h_latency.items():
            per_bucket: Dict[int, dict] = {}
            for sample in hist.samples():
                try:
                    bucket_len = int(sample["labels"]["bucket_len"])
                except (KeyError, ValueError):
                    continue
                counts = dict(sample["buckets"])
                counts["__count"] = sample["count"]
                per_bucket[bucket_len] = counts
            lat[metric] = per_bucket
        out: Dict[str, dict] = {}
        for metric, ctr in self._c_outcomes.items():
            per_outcome = {}
            for sample in ctr.samples():
                per_outcome[sample["labels"].get("outcome", "?")] = \
                    sample["value"]
            out[metric] = per_outcome
        return {"lat": lat, "out": out}

    def _window_delta(self, now: float) -> Tuple[dict, dict, float]:
        """Append a fresh snapshot, prune the ring, and return
        (baseline, newest, span_s). The baseline is the NEWEST sample
        at least window_s old (so the delta covers one full window
        once the ring warms up); with no old-enough sample the oldest
        retained one serves (a short-lived engine reports over its
        whole lifetime — honest, just a smaller window)."""
        snap = self._counts()
        window = self.policy.window_s
        with self._lock:
            self._samples.append((now, snap))
            # retain everything inside the window plus ONE older
            # sample as the baseline
            while len(self._samples) >= 2 \
                    and now - self._samples[1][0] >= window:
                self._samples.popleft()
            base_t, base = self._samples[0]
        return base, snap, max(now - base_t, 0.0)

    @staticmethod
    def _lat_delta(base: dict, snap: dict, cls_: SLOClass,
                   edge_key: str) -> Tuple[int, int]:
        good = total = 0
        base_lat = base["lat"].get(cls_.latency_metric, {})
        for bucket_len, counts in \
                snap["lat"].get(cls_.latency_metric, {}).items():
            if not cls_.covers(bucket_len):
                continue
            b = base_lat.get(bucket_len, {})
            good += counts.get(edge_key, 0) - b.get(edge_key, 0)
            total += counts.get("__count", 0) - b.get("__count", 0)
        return max(int(good), 0), max(int(total), 0)

    @staticmethod
    def _out_delta(base: dict, snap: dict,
                   cls_: SLOClass) -> Tuple[int, int]:
        bad = total = 0
        base_out = base["out"].get(cls_.outcome_metric, {})
        for outcome, n in \
                snap["out"].get(cls_.outcome_metric, {}).items():
            d = n - base_out.get(outcome, 0)
            total += d
            if outcome in cls_.bad_statuses:
                bad += d
        return max(int(bad), 0), max(int(total), 0)

    # -- the report --------------------------------------------------------

    def report(self, now: Optional[float] = None) -> dict:
        """One windowed evaluation: refreshes the slo_* gauges and
        returns the serve_stats()["slo"] block."""
        now = self._clock() if now is None else now
        base, snap, span_s = self._window_delta(now)
        classes = {}
        for cls_ in self.policy.classes:
            q_target = q_key = None
            good = total = 0
            if cls_.target_s is not None:
                q_target = quantize_target(
                    cls_.target_s,
                    self._h_latency[cls_.latency_metric].buckets)
                q_key = f"{q_target:g}"
                good, total = self._lat_delta(base, snap, cls_, q_key)
            bad_term, total_term = self._out_delta(base, snap, cls_)
            result = evaluate_class(cls_, good, total, bad_term,
                                    total_term,
                                    quantized_target_s=q_target)
            classes[cls_.name] = result
            budgets = []
            lat = result.get("latency")
            if lat is not None:
                self._g_attain.set(lat["attainment"],
                                   objective=cls_.name)
                self._g_lat_burn.set(lat["burn_rate"],
                                     objective=cls_.name)
                budgets.append(lat["budget_remaining"])
            avail = result.get("availability")
            if avail is not None:
                self._g_avail.set(avail["observed"],
                                  objective=cls_.name)
                self._g_avail_burn.set(avail["burn_rate"],
                                       objective=cls_.name)
                budgets.append(avail["budget_remaining"])
            if budgets:
                self._g_budget.set(min(budgets), objective=cls_.name)
        return {"window_s": self.policy.window_s,
                "window_observed_s": round(span_s, 3),
                "classes": classes}
