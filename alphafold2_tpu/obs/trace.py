"""Request-scoped tracing: follow one fold from submit to terminal.

A single slow request in the serving stack crosses four components —
`Scheduler.submit` (cache lookup, coalescing, backpressure wait), the
pending queue, `FoldExecutor` (XLA compile vs device run), and
`FoldCache` writeback — each previously with its own uncoordinated
timing. A `Trace` is the per-request record that stitches them: named
spans (intervals), point events (cache hit/miss/quarantine,
coalescing), a link to a coalescing leader's trace, and exactly one
terminal `finish()`.

Design constraints, in priority order:

- zero cost when disabled: `NULL_TRACER.start_trace()` returns the
  `NULL_TRACE` singleton whose every method is a no-op and whose
  `span()` is one shared reusable context manager — no allocation, no
  string formatting, nothing on the hot path;
- spans cross threads (submit happens on the caller's thread, queue →
  fold → writeback on the scheduler worker), so in addition to the
  `span()` context manager there are explicit `begin(name)`/`end(name)`
  for stage handoffs and `add_span(name, t0, t1)` for batch-level spans
  recorded once and fanned out to every member trace (`MultiTrace`);
- `finish()` is idempotent and auto-closes any still-open span (marked
  `auto_closed`) so every terminal path — ok, cache hit, coalesced,
  shed, error, cancelled, worker crash — yields exactly one complete
  record, never an orphan;
- completed traces are emitted as one JSONL record each (`"schema": 1`,
  spans with offsets relative to trace start) and the K slowest are
  kept in a ring the scheduler exposes via `serve_stats()["traces"]`.

Cross-process propagation (ISSUE 15): a trace CROSSES the RPC seam.
`Trace.wire_context()` mints a `TraceContext` — trace id + a fresh
parent span id + this process's origin replica — that travels as HTTP
headers (`fleet.rpc.HttpTransport`, the peer cache client); the
receiving process continues the SAME trace via
`Tracer.start_trace(request_id, context=ctx)`, so a forwarded fold's
two halves share one trace id and the child record names the exact
sender span (`parent_span_id`) it hangs under. Child segments are
anchored to the parent's rpc span by the aggregator
(`tools/obs_fleet.py`) — NEVER by comparing wall clocks across hosts:
each record's offsets stay relative to its own monotonic start, and
monotonic clocks don't compare across processes. `Tracer(origin=...)`
makes trace ids globally unique (origin + a per-boot nonce ride the
id) so two replicas' local counters can never collide in a merged
file; origin-less tracers keep the compact single-process ids. No
context goes on the wire unless tracing is on (`NULL_TRACE.
wire_context()` is None).
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass
from typing import IO, List, Optional

# the one schema tag every observability record carries (obs/export.py)
from alphafold2_tpu.obs.export import SCHEMA_VERSION

_trace_counter = itertools.count()

# wire header names the trace context travels under (HttpTransport
# submit/submit_raw, PeerCacheClient fetches)
_HDR_TRACE_ID = "X-Trace-Id"
_HDR_PARENT_SPAN = "X-Parent-Span"
_HDR_ORIGIN = "X-Trace-Origin"


@dataclass(frozen=True)
class TraceContext:
    """The wire form of one cross-process trace hop: enough for the
    receiver to continue the SAME trace (trace_id), to name the exact
    sender span its segment hangs under (parent_span_id — the rpc or
    peer-fetch span the sender records with a matching `span_id`
    attr), and to attribute the hop (origin — the sender's replica
    id). Header-encoded; absent headers decode to None, so a
    pre-ISSUE-15 peer (or a tracing-off sender) costs nothing."""

    trace_id: str
    parent_span_id: str
    origin: str = ""

    def to_headers(self) -> dict:
        h = {_HDR_TRACE_ID: self.trace_id,
             _HDR_PARENT_SPAN: self.parent_span_id}
        if self.origin:
            h[_HDR_ORIGIN] = self.origin
        return h

    @classmethod
    def from_headers(cls, headers) -> Optional["TraceContext"]:
        trace_id = headers.get(_HDR_TRACE_ID)
        if not trace_id:
            return None
        return cls(trace_id=str(trace_id),
                   parent_span_id=str(
                       headers.get(_HDR_PARENT_SPAN) or ""),
                   origin=str(headers.get(_HDR_ORIGIN) or ""))


class _NullContext:
    """Reusable no-op context manager (one instance per process)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullContext()


class _NullTrace:
    """Do-nothing stand-in so instrumented code never branches."""

    __slots__ = ()
    enabled = False
    trace_id = ""

    def wire_context(self):
        return None         # tracing off: nothing goes on the wire

    def begin(self, name):
        pass

    def end(self, name, **attrs):
        pass

    def span(self, name, **attrs):
        return _NULL_CTX

    def add_span(self, name, t0, t1, **attrs):
        pass

    def event(self, name, **attrs):
        pass

    def link(self, leader_trace_id):
        pass

    def finish(self, status, source="fold", error=None):
        pass

    @property
    def finished(self):
        return False


NULL_TRACE = _NullTrace()


class _SpanContext:
    __slots__ = ("_trace", "_name", "_attrs", "_t0")

    def __init__(self, trace, name, attrs):
        self._trace = trace
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._trace.add_span(self._name, self._t0, time.monotonic(),
                             **self._attrs)
        return False


class Trace:
    """One request's span tree. Thread-safe; finish() is idempotent."""

    __slots__ = ("trace_id", "request_id", "leader_trace_id", "status",
                 "source", "error", "parent_span_id", "parent_origin",
                 "_span_seq", "_hop_nonce", "_tracer", "_lock", "_t0",
                 "_t0_unix", "_end", "_spans", "_events", "_open",
                 "_finished")

    enabled = True

    def __init__(self, tracer: "Tracer", request_id: str):
        # origin-tagged tracers (one per fleet replica) mint GLOBALLY
        # unique ids — origin + a per-boot nonce ride the id, so two
        # replicas' (or a restarted replica's) local counters can
        # never collide in a merged fleet trace file. Origin-less
        # tracers keep the compact pre-fleet single-process ids.
        n = next(_trace_counter)
        origin = getattr(tracer, "origin", "")
        self.trace_id = (f"t{n}" if not origin
                         else f"t{n}.{origin}.{tracer._nonce}")
        self.request_id = request_id
        # set when this trace CONTINUES a remote hop (started with a
        # TraceContext): the sender's span this record hangs under
        self.parent_span_id: Optional[str] = None
        self.parent_origin: str = ""
        self._span_seq = itertools.count()
        self._hop_nonce: Optional[str] = None   # minted on first hop
        self.leader_trace_id: Optional[str] = None
        self.status: Optional[str] = None
        self.source = "fold"
        self.error: Optional[str] = None
        self._tracer = tracer
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._t0_unix = time.time()
        self._end: Optional[float] = None
        self._spans: List[dict] = []
        self._events: List[dict] = []
        self._open: dict = {}          # name -> start (monotonic)
        self._finished = False

    # -- spans / events --------------------------------------------------

    def begin(self, name: str):
        """Open a span that a different thread may close (stage handoff)."""
        now = time.monotonic()
        with self._lock:
            if not self._finished:
                self._open[name] = now

    def end(self, name: str, **attrs):
        """Close a `begin()` span. Tolerant: unknown name is a no-op (the
        race where a worker resolves an entry while submit's bookkeeping
        is mid-flight must never raise into serving)."""
        now = time.monotonic()
        with self._lock:
            t0 = self._open.pop(name, None)
            if t0 is None or self._finished:
                return
            self._append_span(name, t0, now, attrs)

    def span(self, name: str, **attrs) -> _SpanContext:
        """Same-thread scope: `with trace.span("fold"): ...`."""
        return _SpanContext(self, name, attrs)

    def add_span(self, name: str, t0: float, t1: float, **attrs):
        """Record a finished interval (batch-level spans measured once
        and fanned out to every member trace)."""
        with self._lock:
            if not self._finished:
                self._append_span(name, t0, t1, attrs)

    def _append_span(self, name, t0, t1, attrs):
        """Caller holds self._lock."""
        dur = max(t1 - t0, 0.0)
        # a REAL (positive) interval must never round to zero: spans
        # are emitted at microsecond resolution, and a sub-microsecond
        # fold (a stub executor, a trivially small batch) rounding to
        # 0.0 trips obs_report --check's "accelerator-served request
        # with no non-zero fold span" rule — the pre-existing
        # zero-duration-span flake (ISSUE 10). Clamp to one emission
        # quantum; a genuinely empty interval (t1 == t0) stays 0.0.
        span = {"name": name,
                "start_s": round(t0 - self._t0, 6),
                "dur_s": round(dur, 6) if dur >= 5e-7
                else (1e-6 if dur > 0.0 else 0.0)}
        if attrs:
            span["attrs"] = attrs
        self._spans.append(span)

    def event(self, name: str, **attrs):
        now = time.monotonic()
        with self._lock:
            if self._finished:
                return
            ev = {"name": name, "at_s": round(now - self._t0, 6)}
            if attrs:
                ev["attrs"] = attrs
            self._events.append(ev)

    def link(self, leader_trace_id: str):
        """Follower -> leader edge (coalesced requests)."""
        with self._lock:
            self.leader_trace_id = leader_trace_id

    def wire_context(self) -> Optional[TraceContext]:
        """Mint the context for ONE outbound hop: this trace's id plus
        a fresh span id the sender tags its rpc/peer-fetch span with
        (`span_id` attr), so the receiver's continued record can name
        exactly which sender span it hangs under. One context per hop
        — two forwards from one trace get two parent span ids. The
        per-Trace-OBJECT nonce keeps ids unique when one replica
        continues the SAME trace twice (a failover retry looping back
        after a restart): each continuation is a fresh Trace whose
        counter restarts at 0, and two hops both named (origin, "s0")
        would stitch ambiguously in the fleet aggregator."""
        with self._lock:
            if self._finished:
                return None
            if self._hop_nonce is None:
                self._hop_nonce = uuid.uuid4().hex[:4]
            sid = f"s{next(self._span_seq)}.{self._hop_nonce}"
        return TraceContext(trace_id=self.trace_id, parent_span_id=sid,
                            origin=getattr(self._tracer, "origin", ""))

    # -- terminal --------------------------------------------------------

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._finished

    def finish(self, status: str, source: str = "fold",
               error: Optional[str] = None):
        """Terminal state; first call wins, later calls are no-ops.
        Auto-closes open spans so a trace can never leak an orphan."""
        now = time.monotonic()
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.status = status
            self.source = source
            self.error = error
            self._end = now
            for name, t0 in sorted(self._open.items(), key=lambda kv: kv[1]):
                self._append_span(name, t0, now, {"auto_closed": True})
            self._open.clear()
            record = self._record_locked()
        self._tracer._on_finish(record)

    def _record_locked(self) -> dict:
        record = {
            "schema": SCHEMA_VERSION,
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "status": self.status,
            "source": self.source,
            "start_unix_s": round(self._t0_unix, 6),
            "duration_s": round((self._end or self._t0) - self._t0, 6),
            "spans": list(self._spans),
            "events": list(self._events),
        }
        origin = getattr(self._tracer, "origin", "")
        if origin:
            record["origin"] = origin
        if self.parent_span_id:
            # the per-replica hop edge: which sender span (and whose)
            # this record's segments continue — the fleet aggregator
            # anchors child offsets at that span, never at wall clocks
            record["parent_span_id"] = self.parent_span_id
            if self.parent_origin:
                record["parent_origin"] = self.parent_origin
        if self.leader_trace_id is not None:
            record["leader_trace_id"] = self.leader_trace_id
        if self.error:
            record["error"] = str(self.error)
        return record

    def record(self) -> dict:
        """Snapshot of the (possibly unfinished) trace."""
        with self._lock:
            return self._record_locked()


class MultiTrace:
    """Fan one measurement out to many traces (a batch's members).

    The interval is measured ONCE (one clock read per edge) and appended
    to each member, so per-request cost stays O(1) appends."""

    __slots__ = ("_traces",)

    enabled = True

    def __init__(self, traces):
        self._traces = [t for t in traces if t.enabled]

    def span(self, name, **attrs):
        return _MultiSpanContext(self._traces, name, attrs)

    def add_span(self, name, t0, t1, **attrs):
        for t in self._traces:
            t.add_span(name, t0, t1, **attrs)

    def event(self, name, **attrs):
        for t in self._traces:
            t.event(name, **attrs)


class _MultiSpanContext:
    __slots__ = ("_traces", "_name", "_attrs", "_t0")

    def __init__(self, traces, name, attrs):
        self._traces = traces
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        for t in self._traces:
            t.add_span(self._name, self._t0, t1, **self._attrs)
        return False


class _NullTracer:
    __slots__ = ()
    enabled = False
    origin = ""

    def start_trace(self, request_id, context=None):
        return NULL_TRACE

    def slowest(self):
        return []

    def _on_finish(self, record):
        pass

    def close(self):
        pass


NULL_TRACER = _NullTracer()


class Tracer:
    """Trace factory + sink: JSONL emission and a slowest-K ring.

    jsonl_path: append one record per completed trace (schema above);
        None disables the file sink (the ring still works).
    slow_k: how many slowest completed traces to retain for
        `serve_stats()["traces"]` / `slowest()`.
    origin: this process's replica id for fleet-wide stitching
        (ISSUE 15). When set, trace ids become globally unique
        (origin + a per-boot nonce ride the id), every emitted record
        carries an `origin` field, and outbound wire contexts name
        this replica as the hop's sender. "" (the default) is the
        pre-fleet single-process behavior, byte-for-byte.
    """

    enabled = True

    def __init__(self, jsonl_path: Optional[str] = None, slow_k: int = 16,
                 origin: str = ""):
        self.origin = str(origin)
        # per-boot nonce: a RESTARTED replica reuses its origin id but
        # must never reuse the dead boot's trace ids (its counter
        # restarts at 0)
        self._nonce = uuid.uuid4().hex[:6]
        self._lock = threading.Lock()
        self._fh: Optional[IO] = None
        if jsonl_path:
            d = os.path.dirname(os.path.abspath(jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(jsonl_path, "a")
        self.slow_k = max(0, int(slow_k))
        self._seq = itertools.count()   # heap tie-break, never compares dicts
        self._slow: list = []           # min-heap of (duration, seq, record)
        self.completed = 0

    def start_trace(self, request_id: str,
                    context: Optional[TraceContext] = None) -> Trace:
        """Start a trace; with `context` (a remote hop's wire headers,
        decoded by the receiving server) the new trace CONTINUES the
        sender's — same trace id, and the emitted record names the
        sender span it hangs under (`parent_span_id`/`parent_origin`)
        so the fleet aggregator can stitch the two halves into one
        waterfall."""
        t = Trace(self, request_id)
        if context is not None:
            t.trace_id = context.trace_id
            t.parent_span_id = context.parent_span_id or None
            t.parent_origin = context.origin
        return t

    def _on_finish(self, record: dict):
        # serialize OUTSIDE the lock: finish() runs on the serving
        # resolve path, and every completing request contends on this
        # one lock with serve_stats()
        try:
            line = json.dumps(record) if self._fh is not None else None
        except Exception:
            line = None     # unserializable span attr: keep the ring
        try:
            with self._lock:
                self.completed += 1
                if self.slow_k:
                    item = (record["duration_s"], next(self._seq), record)
                    if len(self._slow) < self.slow_k:
                        heapq.heappush(self._slow, item)
                    elif item[0] > self._slow[0][0]:
                        heapq.heapreplace(self._slow, item)
                if line is not None and self._fh is not None:
                    self._fh.write(line + "\n")
                    self._fh.flush()
        except Exception:
            pass        # the trace sink is observability, not serving

    def slowest(self) -> List[dict]:
        """Completed traces, slowest first."""
        with self._lock:
            return [rec for _, _, rec in
                    sorted(self._slow, key=lambda it: -it[0])]

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
