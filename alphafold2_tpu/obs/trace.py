"""Request-scoped tracing: follow one fold from submit to terminal.

A single slow request in the serving stack crosses four components —
`Scheduler.submit` (cache lookup, coalescing, backpressure wait), the
pending queue, `FoldExecutor` (XLA compile vs device run), and
`FoldCache` writeback — each previously with its own uncoordinated
timing. A `Trace` is the per-request record that stitches them: named
spans (intervals), point events (cache hit/miss/quarantine,
coalescing), a link to a coalescing leader's trace, and exactly one
terminal `finish()`.

Design constraints, in priority order:

- zero cost when disabled: `NULL_TRACER.start_trace()` returns the
  `NULL_TRACE` singleton whose every method is a no-op and whose
  `span()` is one shared reusable context manager — no allocation, no
  string formatting, nothing on the hot path;
- spans cross threads (submit happens on the caller's thread, queue →
  fold → writeback on the scheduler worker), so in addition to the
  `span()` context manager there are explicit `begin(name)`/`end(name)`
  for stage handoffs and `add_span(name, t0, t1)` for batch-level spans
  recorded once and fanned out to every member trace (`MultiTrace`);
- `finish()` is idempotent and auto-closes any still-open span (marked
  `auto_closed`) so every terminal path — ok, cache hit, coalesced,
  shed, error, cancelled, worker crash — yields exactly one complete
  record, never an orphan;
- completed traces are emitted as one JSONL record each (`"schema": 1`,
  spans with offsets relative to trace start) and the K slowest are
  kept in a ring the scheduler exposes via `serve_stats()["traces"]`.
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import threading
import time
from typing import IO, List, Optional

# the one schema tag every observability record carries (obs/export.py)
from alphafold2_tpu.obs.export import SCHEMA_VERSION

_trace_counter = itertools.count()


class _NullContext:
    """Reusable no-op context manager (one instance per process)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullContext()


class _NullTrace:
    """Do-nothing stand-in so instrumented code never branches."""

    __slots__ = ()
    enabled = False
    trace_id = ""

    def begin(self, name):
        pass

    def end(self, name, **attrs):
        pass

    def span(self, name, **attrs):
        return _NULL_CTX

    def add_span(self, name, t0, t1, **attrs):
        pass

    def event(self, name, **attrs):
        pass

    def link(self, leader_trace_id):
        pass

    def finish(self, status, source="fold", error=None):
        pass

    @property
    def finished(self):
        return False


NULL_TRACE = _NullTrace()


class _SpanContext:
    __slots__ = ("_trace", "_name", "_attrs", "_t0")

    def __init__(self, trace, name, attrs):
        self._trace = trace
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._trace.add_span(self._name, self._t0, time.monotonic(),
                             **self._attrs)
        return False


class Trace:
    """One request's span tree. Thread-safe; finish() is idempotent."""

    __slots__ = ("trace_id", "request_id", "leader_trace_id", "status",
                 "source", "error", "_tracer", "_lock", "_t0", "_t0_unix",
                 "_end", "_spans", "_events", "_open", "_finished")

    enabled = True

    def __init__(self, tracer: "Tracer", request_id: str):
        self.trace_id = f"t{next(_trace_counter)}"
        self.request_id = request_id
        self.leader_trace_id: Optional[str] = None
        self.status: Optional[str] = None
        self.source = "fold"
        self.error: Optional[str] = None
        self._tracer = tracer
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._t0_unix = time.time()
        self._end: Optional[float] = None
        self._spans: List[dict] = []
        self._events: List[dict] = []
        self._open: dict = {}          # name -> start (monotonic)
        self._finished = False

    # -- spans / events --------------------------------------------------

    def begin(self, name: str):
        """Open a span that a different thread may close (stage handoff)."""
        now = time.monotonic()
        with self._lock:
            if not self._finished:
                self._open[name] = now

    def end(self, name: str, **attrs):
        """Close a `begin()` span. Tolerant: unknown name is a no-op (the
        race where a worker resolves an entry while submit's bookkeeping
        is mid-flight must never raise into serving)."""
        now = time.monotonic()
        with self._lock:
            t0 = self._open.pop(name, None)
            if t0 is None or self._finished:
                return
            self._append_span(name, t0, now, attrs)

    def span(self, name: str, **attrs) -> _SpanContext:
        """Same-thread scope: `with trace.span("fold"): ...`."""
        return _SpanContext(self, name, attrs)

    def add_span(self, name: str, t0: float, t1: float, **attrs):
        """Record a finished interval (batch-level spans measured once
        and fanned out to every member trace)."""
        with self._lock:
            if not self._finished:
                self._append_span(name, t0, t1, attrs)

    def _append_span(self, name, t0, t1, attrs):
        """Caller holds self._lock."""
        dur = max(t1 - t0, 0.0)
        # a REAL (positive) interval must never round to zero: spans
        # are emitted at microsecond resolution, and a sub-microsecond
        # fold (a stub executor, a trivially small batch) rounding to
        # 0.0 trips obs_report --check's "accelerator-served request
        # with no non-zero fold span" rule — the pre-existing
        # zero-duration-span flake (ISSUE 10). Clamp to one emission
        # quantum; a genuinely empty interval (t1 == t0) stays 0.0.
        span = {"name": name,
                "start_s": round(t0 - self._t0, 6),
                "dur_s": round(dur, 6) if dur >= 5e-7
                else (1e-6 if dur > 0.0 else 0.0)}
        if attrs:
            span["attrs"] = attrs
        self._spans.append(span)

    def event(self, name: str, **attrs):
        now = time.monotonic()
        with self._lock:
            if self._finished:
                return
            ev = {"name": name, "at_s": round(now - self._t0, 6)}
            if attrs:
                ev["attrs"] = attrs
            self._events.append(ev)

    def link(self, leader_trace_id: str):
        """Follower -> leader edge (coalesced requests)."""
        with self._lock:
            self.leader_trace_id = leader_trace_id

    # -- terminal --------------------------------------------------------

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._finished

    def finish(self, status: str, source: str = "fold",
               error: Optional[str] = None):
        """Terminal state; first call wins, later calls are no-ops.
        Auto-closes open spans so a trace can never leak an orphan."""
        now = time.monotonic()
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.status = status
            self.source = source
            self.error = error
            self._end = now
            for name, t0 in sorted(self._open.items(), key=lambda kv: kv[1]):
                self._append_span(name, t0, now, {"auto_closed": True})
            self._open.clear()
            record = self._record_locked()
        self._tracer._on_finish(record)

    def _record_locked(self) -> dict:
        record = {
            "schema": SCHEMA_VERSION,
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "status": self.status,
            "source": self.source,
            "start_unix_s": round(self._t0_unix, 6),
            "duration_s": round((self._end or self._t0) - self._t0, 6),
            "spans": list(self._spans),
            "events": list(self._events),
        }
        if self.leader_trace_id is not None:
            record["leader_trace_id"] = self.leader_trace_id
        if self.error:
            record["error"] = str(self.error)
        return record

    def record(self) -> dict:
        """Snapshot of the (possibly unfinished) trace."""
        with self._lock:
            return self._record_locked()


class MultiTrace:
    """Fan one measurement out to many traces (a batch's members).

    The interval is measured ONCE (one clock read per edge) and appended
    to each member, so per-request cost stays O(1) appends."""

    __slots__ = ("_traces",)

    enabled = True

    def __init__(self, traces):
        self._traces = [t for t in traces if t.enabled]

    def span(self, name, **attrs):
        return _MultiSpanContext(self._traces, name, attrs)

    def add_span(self, name, t0, t1, **attrs):
        for t in self._traces:
            t.add_span(name, t0, t1, **attrs)

    def event(self, name, **attrs):
        for t in self._traces:
            t.event(name, **attrs)


class _MultiSpanContext:
    __slots__ = ("_traces", "_name", "_attrs", "_t0")

    def __init__(self, traces, name, attrs):
        self._traces = traces
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        for t in self._traces:
            t.add_span(self._name, self._t0, t1, **self._attrs)
        return False


class _NullTracer:
    __slots__ = ()
    enabled = False

    def start_trace(self, request_id):
        return NULL_TRACE

    def slowest(self):
        return []

    def _on_finish(self, record):
        pass

    def close(self):
        pass


NULL_TRACER = _NullTracer()


class Tracer:
    """Trace factory + sink: JSONL emission and a slowest-K ring.

    jsonl_path: append one record per completed trace (schema above);
        None disables the file sink (the ring still works).
    slow_k: how many slowest completed traces to retain for
        `serve_stats()["traces"]` / `slowest()`.
    """

    enabled = True

    def __init__(self, jsonl_path: Optional[str] = None, slow_k: int = 16):
        self._lock = threading.Lock()
        self._fh: Optional[IO] = None
        if jsonl_path:
            d = os.path.dirname(os.path.abspath(jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(jsonl_path, "a")
        self.slow_k = max(0, int(slow_k))
        self._seq = itertools.count()   # heap tie-break, never compares dicts
        self._slow: list = []           # min-heap of (duration, seq, record)
        self.completed = 0

    def start_trace(self, request_id: str) -> Trace:
        return Trace(self, request_id)

    def _on_finish(self, record: dict):
        # serialize OUTSIDE the lock: finish() runs on the serving
        # resolve path, and every completing request contends on this
        # one lock with serve_stats()
        try:
            line = json.dumps(record) if self._fh is not None else None
        except Exception:
            line = None     # unserializable span attr: keep the ring
        try:
            with self._lock:
                self.completed += 1
                if self.slow_k:
                    item = (record["duration_s"], next(self._seq), record)
                    if len(self._slow) < self.slow_k:
                        heapq.heappush(self._slow, item)
                    elif item[0] > self._slow[0][0]:
                        heapq.heapreplace(self._slow, item)
                if line is not None and self._fh is not None:
                    self._fh.write(line + "\n")
                    self._fh.flush()
        except Exception:
            pass        # the trace sink is observability, not serving

    def slowest(self) -> List[dict]:
        """Completed traces, slowest first."""
        with self._lock:
            return [rec for _, _, rec in
                    sorted(self._slow, key=lambda it: -it[0])]

    def close(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
