"""alphafold2_tpu.obs — unified observability: tracing + metrics.

Three uncoordinated telemetry surfaces grew up with the serving stack
(`StepTimer`, `ServeMetrics`' per-batch JSONL, `MetricsLogger`); this
package replaces their private bookkeeping with one pair of primitives:

- trace:    request-scoped spans with stable trace IDs, created at
            `Scheduler.submit` and propagated through coalescing
            (followers link to the leader's trace), batching, the
            executor (compile vs run), and the result cache — emitted
            as JSONL, slowest-K exposed via `serve_stats()["traces"]`.
            `NULL_TRACER` makes instrumentation zero-cost when off.
- registry: process-wide `MetricsRegistry` (counter / gauge /
            histogram with fixed exponential latency buckets, labels,
            thread-safe) that serve, cache, and train report into.
- export:   Prometheus text exposition + JSONL sharing one versioned
            `"schema": 1` record convention; `flatten()` for
            arbitrary-depth dict keys.

`tools/obs_report.py` renders the per-stage latency waterfall and the
top-K slowest traces from a trace JSONL file (README "Observability").
"""

from alphafold2_tpu.obs.export import (JsonlExporter, SCHEMA_VERSION,  # noqa: F401
                                       flatten, prometheus_text,
                                       registry_json, write_prometheus)
from alphafold2_tpu.obs.registry import (DEFAULT_LATENCY_BUCKETS,  # noqa: F401
                                         Counter, Gauge, Histogram,
                                         MetricsRegistry, get_registry,
                                         set_registry)
from alphafold2_tpu.obs.slo import (SLOClass, SLOEngine,  # noqa: F401
                                    SLOPolicy)
from alphafold2_tpu.obs.trace import (NULL_TRACE, NULL_TRACER,  # noqa: F401
                                      MultiTrace, Trace, TraceContext,
                                      Tracer)
