"""Metric export: Prometheus text exposition + JSONL, one shared schema.

Every serialized observability record in the repo — trace records
(obs/trace.py), metric snapshots (here), and `MetricsLogger` training /
serving JSONL lines — carries the same versioned `"schema": 1` field so
downstream tooling can reject records it does not understand instead of
mis-parsing them (the MIGRATING note covers the `MetricsLogger`
change). This module also owns `flatten()`, the arbitrary-depth
dict-flattener `MetricsLogger` used to special-case at one level.

- `prometheus_text(registry)`: Prometheus text exposition format 0.0.4
  (`# HELP` / `# TYPE`, histogram `_bucket{le=...}` with cumulative
  counts plus `_sum`/`_count`) — serve it from any HTTP handler or dump
  it to a file for file-based scraping;
- `registry_json(registry)` / `JsonlExporter`: the same snapshot as one
  JSON object / appended JSONL line.
"""

from __future__ import annotations

import json
import os
import time
from typing import IO, Optional

from alphafold2_tpu.obs.registry import MetricsRegistry, get_registry

SCHEMA_VERSION = 1


def flatten(mapping: dict, sep: str = ".", prefix: str = "") -> dict:
    """Flatten arbitrarily nested dicts to `sep`-joined keys.

    {"cache": {"disk": {"hits": 3}}} -> {"cache.disk.hits": 3}. Non-dict
    values pass through unchanged; insertion order is preserved
    depth-first, matching the nesting's reading order."""
    out = {}
    for k, v in mapping.items():
        key = f"{prefix}{sep}{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten(v, sep=sep, prefix=key))
        else:
            out[key] = v
    return out


# -- Prometheus text exposition ------------------------------------------


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in merged.items())
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    # NaN/Inf must render as Prometheus tokens (a diverged train loss
    # setting a NaN gauge must not take down the whole exposition)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    lines = []
    for metric in registry.metrics():
        name = metric.name
        if metric.help:
            lines.append(f"# HELP {name} {_escape_help(metric.help)}")
        lines.append(f"# TYPE {name} {metric.kind}")
        if metric.kind == "histogram":
            for sample in metric.samples():
                labels = sample["labels"]
                for le, cum in sample["buckets"].items():
                    lines.append(
                        f"{name}_bucket{_fmt_labels(labels, {'le': le})} "
                        f"{_fmt_value(cum)}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(sample['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{_fmt_value(sample['count'])}")
        else:
            for sample in metric.samples():
                lines.append(f"{name}{_fmt_labels(sample['labels'])} "
                             f"{_fmt_value(sample['value'])}")
    return "\n".join(lines) + "\n" if lines else ""


def write_prometheus(path: str,
                     registry: Optional[MetricsRegistry] = None) -> str:
    """Dump the exposition to `path` (atomic enough for file scraping:
    tmp + rename). Returns the rendered text."""
    text = prometheus_text(registry)
    tmp = f"{path}.tmp.{os.getpid()}"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)
    return text


# -- JSON / JSONL --------------------------------------------------------


def registry_json(registry: Optional[MetricsRegistry] = None) -> dict:
    """One JSON object for the whole registry, schema-versioned."""
    registry = registry or get_registry()
    return {"schema": SCHEMA_VERSION,
            "unix_s": round(time.time(), 3),
            "metrics": registry.snapshot()}


class JsonlExporter:
    """Append registry snapshots (or arbitrary records) as JSONL lines,
    each carrying `"schema": 1`. The file sink MetricsLogger and the
    trace emitter share this record convention."""

    def __init__(self, path: str):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fh: Optional[IO] = open(path, "a")

    def write_registry(self, registry: Optional[MetricsRegistry] = None):
        self.write(registry_json(registry))

    def write(self, record: dict):
        if self._fh is None:
            raise ValueError("JsonlExporter already closed")
        record = dict(record)
        record.setdefault("schema", SCHEMA_VERSION)
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.flush()
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
