"""Multi-device sharding tests on the virtual 8-device CPU platform
(SURVEY.md §4: the reference has nothing like this — it's the main new risk
surface). Verifies mesh construction, sharded == unsharded numerics, the
full sharded training step, and the driver entry points."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.data.synthetic import synthetic_batch
from alphafold2_tpu.parallel import make_mesh, pair_spec, use_mesh
from alphafold2_tpu.train import TrainState, adam, make_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


@pytest.mark.quick
def test_make_mesh_shapes():
    mesh = make_mesh(2, 2, 2)
    assert mesh.shape == {"pipe": 1, "data": 2, "i": 2, "j": 2}
    with pytest.raises(ValueError):
        make_mesh(3, 3, 3)
    mesh = make_mesh(2, 1, 1, pipe=4)
    assert mesh.shape == {"pipe": 4, "data": 2, "i": 1, "j": 1}


@pytest.mark.quick
def test_pair_sharding_spec():
    assert pair_spec() == P("data", "i", "j", None)


def test_sharded_forward_matches_single_device():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=2, seq_len=16,
                            msa_depth=3, with_coords=False)
    args = (batch["seq"],)
    kwargs = dict(msa=batch["msa"], mask=batch["mask"],
                  msa_mask=batch["msa_mask"])
    params = model.init(jax.random.PRNGKey(1), *args, **kwargs)

    ret_single = jax.jit(
        lambda p: model.apply(p, *args, **kwargs))(params)

    mesh = make_mesh(2, 2, 2)
    with use_mesh(mesh):
        params_r = jax.device_put(params, NamedSharding(mesh, P()))
        ret_sharded = jax.jit(
            lambda p: model.apply(p, *args, **kwargs))(params_r)

    assert np.allclose(ret_single.distance, ret_sharded.distance, atol=2e-4)


def test_sharded_train_step_runs_and_matches():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=2, seq_len=16,
                            msa_depth=3, with_coords=True)

    def build_state():
        params = model.init(
            {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
            batch["seq"], msa=batch["msa"], mask=batch["mask"],
            msa_mask=batch["msa_mask"], train=True)
        return TrainState.create(apply_fn=model.apply, params=params,
                                 tx=adam(1e-3), rng=jax.random.PRNGKey(3))

    step = make_train_step(model)

    state = build_state()
    _, metrics_single = jax.jit(step)(state, batch)
    loss_single = float(metrics_single["loss"])

    mesh = make_mesh(2, 2, 2)
    with use_mesh(mesh):
        state_s = jax.device_put(build_state(), NamedSharding(mesh, P()))
        batch_s = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(*(["data"] + [None] * (x.ndim - 1))))
            ) if x.shape[0] == 2 else x,
            batch)
        new_state, metrics_sharded = jax.jit(step)(state_s, batch_s)
        jax.block_until_ready(metrics_sharded["loss"])

    # same math (MLM rng path identical: same fold_in of the same key)
    assert np.isclose(loss_single, float(metrics_sharded["loss"]), atol=5e-3)
    assert int(new_state.step) == 1


class TestZeroSharding:
    """ZeRO-style optimizer/param sharding (VERDICT round-1 item #4):
    actually materialize a sharded state, train on it, and prove the
    per-device optimizer bytes shrink ~n_data-fold — replacing the
    reference's empty deepspeed.py stub with evidence."""

    def _model_and_batch(self):
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16)
        batch = synthetic_batch(jax.random.PRNGKey(0), batch=4, seq_len=16,
                                msa_depth=3, with_coords=True)
        return model, batch

    def _state(self, model, batch):
        params = model.init(
            {"params": jax.random.PRNGKey(1), "mlm": jax.random.PRNGKey(2)},
            batch["seq"], msa=batch["msa"], mask=batch["mask"],
            msa_mask=batch["msa_mask"], train=True)
        return TrainState.create(apply_fn=model.apply, params=params,
                                 tx=adam(1e-3), rng=jax.random.PRNGKey(3))

    def test_sharded_opt_state_bytes_and_numerics(self):
        from alphafold2_tpu.parallel import (pytree_bytes_per_device,
                                             shard_pytree_zero)

        model, batch = self._model_and_batch()
        step = make_train_step(model)

        # replicated run for ground truth
        state = self._state(model, batch)
        ref_state, ref_metrics = jax.jit(step)(state, batch)
        ref_loss = float(ref_metrics["loss"])

        mesh = make_mesh(4, 2, 1)
        n_data = mesh.shape["data"]
        with use_mesh(mesh):
            state_z = shard_pytree_zero(self._state(model, batch), mesh)

            # the moments really are distributed: per-device bytes of the
            # adam state are ~1/n_data of the replicated footprint
            replicated_bytes = pytree_bytes_per_device(
                jax.device_put(jax.tree.map(np.asarray, state_z.opt_state),
                               NamedSharding(mesh, P())))
            sharded_bytes = pytree_bytes_per_device(state_z.opt_state)
            assert sharded_bytes < replicated_bytes / n_data * 1.5, \
                (sharded_bytes, replicated_bytes)
            # params too
            assert pytree_bytes_per_device(state_z.params) < \
                pytree_bytes_per_device(
                    jax.device_put(jax.tree.map(np.asarray, state_z.params),
                                   NamedSharding(mesh, P()))) / 2

            batch_s = jax.tree.map(
                lambda x: jax.device_put(
                    x, NamedSharding(
                        mesh, P(*(["data"] + [None] * (x.ndim - 1))))),
                batch)
            new_state, metrics = jax.jit(step, donate_argnums=(0,))(
                state_z, batch_s)
            jax.block_until_ready(metrics["loss"])

            # numerics match the replicated run
            assert np.isclose(float(metrics["loss"]), ref_loss, atol=5e-3)
            # updated params stay sharded (no silent re-replication), and
            # match the replicated step's result
            assert pytree_bytes_per_device(new_state.params) < \
                pytree_bytes_per_device(ref_state.params) / 2
            for a, b in zip(jax.tree.leaves(new_state.params),
                            jax.tree.leaves(ref_state.params)):
                assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4)

            # and a second step runs on the donated sharded state
            new_state2, metrics2 = jax.jit(step, donate_argnums=(0,))(
                new_state, batch_s)
            assert np.isfinite(float(metrics2["loss"]))

    def test_zero_specs_shape_rule(self):
        from alphafold2_tpu.parallel import zero_param_specs

        mesh = make_mesh(4, 2, 1)
        params = {"w": jnp.zeros((8, 12)), "b": jnp.zeros((3,)),
                  "s": jnp.zeros(())}
        specs = zero_param_specs(params, mesh)
        assert specs["w"] == P(None, "data")   # 12 % 4 == 0, largest dim
        assert specs["b"] == P()               # 3 % 4 != 0 -> replicated
        assert specs["s"] == P()


def test_graft_entry_contracts():
    import __graft_entry__ as graft

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 3

    graft.dryrun_multichip(8)


class TestTensorParallel:
    """Megatron-style TP via tp_param_specs (SURVEY §2.5): outputs match
    the replicated run and per-device param bytes actually shrink."""

    def test_tp_forward_matches_and_shards_bytes(self):
        from alphafold2_tpu.parallel.sharding import (
            pytree_bytes_per_device, shard_pytree_tp, tp_param_specs)

        model = Alphafold2(dim=32, depth=2, heads=4, dim_head=8)
        batch = synthetic_batch(jax.random.PRNGKey(3), batch=2, seq_len=16,
                                msa_depth=3, with_coords=False)
        args = (batch["seq"],)
        kwargs = dict(msa=batch["msa"], mask=batch["mask"],
                      msa_mask=batch["msa_mask"])
        params = model.init(jax.random.PRNGKey(4), *args, **kwargs)

        ret_single = jax.jit(lambda p: model.apply(p, *args, **kwargs))(
            params)

        mesh = make_mesh(1, 1, 8)  # all devices on the TP axis
        with use_mesh(mesh):
            params_tp = shard_pytree_tp(params, mesh, axis="j")
            ret_tp = jax.jit(lambda p: model.apply(p, *args, **kwargs))(
                params_tp)
        assert np.allclose(ret_single.distance, ret_tp.distance, atol=2e-4)

        replicated = jax.device_put(
            params, NamedSharding(mesh, P()))
        full = pytree_bytes_per_device(replicated)
        tp = pytree_bytes_per_device(params_tp)
        # the big projection kernels dominate; per-device bytes must drop
        # substantially (not 8x: embeddings/norms stay replicated)
        assert tp < 0.55 * full, (tp, full)

    @pytest.mark.quick
    def test_tp_specs_hit_attention_and_ff(self):
        from alphafold2_tpu.parallel.sharding import tp_param_specs

        model = Alphafold2(dim=32, depth=2, heads=4, dim_head=8)
        batch = synthetic_batch(jax.random.PRNGKey(5), batch=1, seq_len=8,
                                msa_depth=2, with_coords=False)
        params = model.init(jax.random.PRNGKey(6), batch["seq"],
                            msa=batch["msa"], mask=batch["mask"],
                            msa_mask=batch["msa_mask"])
        mesh = make_mesh(1, 1, 8)
        specs = tp_param_specs(params, mesh, axis="j")
        flat = jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
        named = {"/".join(str(getattr(k, "key", k)) for k in path): spec
                 for path, spec in flat}
        sharded = [n for n, s in named.items() if s != P()]
        assert any("to_q/kernel" in n for n in sharded)
        assert any("to_out/kernel" in n for n in sharded)
        assert any("Dense_0/kernel" in n for n in sharded)
        # norms and embeddings stay replicated
        assert all("norm" not in n.lower() for n in sharded)
        # Dense matches are anchored to the FeedForward module scope —
        # head MLPs / structure-module Dense layers stay replicated by
        # intent (round-2 ADVICE: bare Dense_0 suffixes also hit heads)
        assert all("/ff/" in n or "/msa_ff/" in n
                   for n in sharded if "Dense" in n), sharded
        # coverage snapshot: a silent fall-through to P() (renamed module,
        # new Dense) must fail loudly, not degrade TP to replication
        assert len(sharded) == 107, len(sharded)

    @pytest.mark.quick
    def test_tp_specs_warn_when_nothing_matches(self):
        import warnings

        from alphafold2_tpu.parallel.sharding import tp_param_specs

        mesh = make_mesh(1, 1, 8)
        params = {"params": {"encoder": {"kernel": jnp.ones((8, 8))}}}
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            specs = tp_param_specs(params, mesh, axis="j")
        assert any("matched no parameters" in str(x.message) for x in w)
        assert all(s == P() for s in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)))
