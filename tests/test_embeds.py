"""Embed-wrapper tests with stubbed pretrained backends.

The real LMs (ESM-1b, MSA-Transformer, ProtBert, ProtT5) cannot be
downloaded in this container, so these tests stub `_load()` with tiny
fakes that honor each hub's tokenization protocol, and verify the parts
that are OUR logic: special-token slicing, MSA flattening/reshaping, and
injection of `seq_embed`/`msa_embed` into Alphafold2 (reference
embeds.py:10-103, utils.py:295-390).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

from alphafold2_tpu import Alphafold2, constants
from alphafold2_tpu.embeds import (ESMEmbedWrapper, MSAEmbedWrapper,
                                   ProtT5EmbedWrapper, ProtTranEmbedWrapper)


class _FakeT5Tokenizer:
    """Space-separated residues in, ids + trailing </s> out (ProtT5 has
    no leading CLS — the asymmetry vs BERT that the slicing must honor)."""

    def batch_encode_plus(self, texts, add_special_tokens=True,
                          padding=True, return_tensors="pt"):
        n = max(len(t.split()) for t in texts)
        ids = torch.zeros((len(texts), n + 1), dtype=torch.long)
        mask = torch.zeros_like(ids)
        for i, t in enumerate(texts):
            L = len(t.split())
            ids[i, :L] = torch.arange(1, L + 1)
            ids[i, L] = 99  # </s>
            mask[i, :L + 1] = 1
        return {"input_ids": ids, "attention_mask": mask}


class _FakeT5Encoder:
    """last_hidden_state[b, i, :] encodes the token position i so the
    test can check which positions the wrapper keeps."""

    DIM = 8

    def __call__(self, input_ids=None, attention_mask=None):
        b, n = input_ids.shape
        h = torch.arange(n, dtype=torch.float32)[None, :, None]
        out = h.expand(b, n, self.DIM).clone()

        class R:
            last_hidden_state = out
        return R()


class TestProtT5Wrapper:
    def _wrapper(self):
        w = ProtT5EmbedWrapper(alphafold2=None)
        w._backend = (_FakeT5Encoder(), _FakeT5Tokenizer())
        return w

    def test_seq_slicing_drops_only_trailing_eos(self):
        w = self._wrapper()
        seq = np.zeros((2, 5), dtype=np.int32)  # 5 residues
        emb, msa_emb = w.embed_batch(seq)
        assert emb.shape == (2, 5, _FakeT5Encoder.DIM)
        assert msa_emb is None
        # positions 0..4 kept (no CLS shift), </s> at position 5 dropped
        np.testing.assert_allclose(emb[0, :, 0], np.arange(5.0))

    def test_msa_flatten_roundtrip(self):
        w = self._wrapper()
        seq = np.zeros((1, 4), dtype=np.int32)
        msa = np.zeros((1, 3, 4), dtype=np.int32)
        emb, msa_emb = w.embed_batch(seq, msa)
        assert emb.shape == (1, 4, _FakeT5Encoder.DIM)
        assert msa_emb.shape == (1, 3, 4, _FakeT5Encoder.DIM)

    def test_t5_dim_constant(self):
        assert constants.NUM_EMBEDDS_T5 == 1024


class TestInjection:
    def test_wrapper_call_injects_embeds(self):
        """__call__ feeds seq_embed/msa_embed into Alphafold2.apply; the
        wrapped model must accept the LM dims and produce a distogram."""
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=8,
                           dtype=jnp.float32)
        b, n, m, d = 1, 6, 2, 16
        seq = jnp.zeros((b, n), dtype=jnp.int32)
        msa = jnp.zeros((b, m, n), dtype=jnp.int32)
        seq_embed = jnp.ones((b, n, d), dtype=jnp.float32)
        msa_embed = jnp.ones((b, m, n, d), dtype=jnp.float32)
        params = model.init(jax.random.PRNGKey(0), seq, msa=msa,
                            seq_embed=seq_embed, msa_embed=msa_embed)

        class _Stub(ProtT5EmbedWrapper):
            def embed_batch(self, seq, msa=None):
                return np.asarray(seq_embed), np.asarray(msa_embed)

        w = _Stub(model, params=params)
        out = w(seq=seq, msa=msa)  # non-coords model -> ReturnValues
        assert out.distance.shape[:3] == (b, n, n)
        assert np.all(np.isfinite(np.asarray(out.distance)))


class TestProtTranWrapper:
    def test_bert_slicing_drops_leading_cls(self):
        """ProtBert-style: CLS at 0, so the wrapper keeps 1..L."""

        class _FakeBertTok:
            def __call__(self, texts, return_tensors="pt", padding=True):
                n = max(len(t.split()) for t in texts)

                class E(dict):
                    pass
                e = E()
                e["input_ids"] = torch.zeros((len(texts), n + 2),
                                             dtype=torch.long)
                e["attention_mask"] = torch.ones_like(e["input_ids"])
                return e

        class _FakeBert:
            def __call__(self, **enc):
                ids = enc["input_ids"]
                b, n = ids.shape
                h = torch.arange(n, dtype=torch.float32)[None, :, None]

                class R:
                    last_hidden_state = h.expand(b, n, 4).clone()
                return R()

        w = ProtTranEmbedWrapper(alphafold2=None)
        w._backend = (_FakeBert(), _FakeBertTok())
        seq = np.zeros((1, 5), dtype=np.int32)
        emb, _ = w.embed_batch(seq)
        assert emb.shape == (1, 5, 4)
        # CLS (position 0) dropped: first kept position is 1
        np.testing.assert_allclose(emb[0, :, 0], np.arange(1.0, 6.0))


# ---------------------------------------------------------------------------
# Recorded-convention goldens (VERDICT r4 #9)
# ---------------------------------------------------------------------------
#
# The classes above verify slicing against *hand-rolled* fakes; these pin
# it against *recorded* conventions: tests/goldens/embed_tokenizers.json
# transcribes the published vocabularies and special-token layouts of
# ESM-1b, the MSA Transformer, ProtBert and ProtT5 (BOS/EOS placement is
# exactly where the reference wrappers had subtle bugs). Each replay
# tokenizer below consults ONLY the golden data, asserts its encoding of
# the golden sequence reproduces the golden token ids verbatim, and the
# test then checks the wrapper keeps exactly `residue_positions`.

import json
import os

from alphafold2_tpu.data.featurize import tokenize
from alphafold2_tpu.embeds import ESMEmbedWrapper, MSAEmbedWrapper

_GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "goldens",
                            "embed_tokenizers.json")
with open(_GOLDEN_PATH) as f:
    GOLD = json.load(f)


def _esm_tokenize_one(text: str, vocab: dict, prepend_bos: bool,
                      append_eos: bool) -> list:
    """Replay of ESM Alphabet.tokenize: greedy match of <...> specials,
    otherwise per-character lookup."""
    ids = []
    i = 0
    while i < len(text):
        if text[i] == "<":
            j = text.index(">", i) + 1
            ids.append(vocab[text[i:j]])
            i = j
        else:
            ids.append(vocab[text[i]])
            i += 1
    if prepend_bos:
        ids = [vocab["<cls>"]] + ids
    if append_eos:
        ids = ids + [vocab["<eos>"]]
    return ids


def _position_token_reps(toks: "torch.Tensor", dim: int = 2):
    """Hidden state encoding (position, token id) so tests can see which
    encoder positions a wrapper keeps."""
    b, n = toks.shape[0], toks.shape[-1]
    flat = toks.reshape(-1, n)
    reps = torch.zeros((flat.shape[0], n, dim), dtype=torch.float32)
    reps[:, :, 0] = torch.arange(n, dtype=torch.float32)[None, :]
    reps[:, :, 1] = flat.float()
    return reps.reshape(*toks.shape, dim)


class TestTokenizerGoldens:
    SEQ = GOLD["sequence"]

    def test_internal_tokenize_roundtrip(self):
        """Wrapper text prep starts from detokenize(tokenize(seq))."""
        from alphafold2_tpu.data.featurize import detokenize
        assert detokenize(tokenize(self.SEQ)) == self.SEQ

    def _esm_backend(self, g, vocab, repr_layer):
        class _Converter:
            def __call__(self, data):
                rows = [_esm_tokenize_one(s, vocab, g["prepend_bos"],
                                          g["append_eos"]) for _, s in data]
                return None, None, torch.tensor(rows, dtype=torch.long)

        class _Model:
            def eval(self):
                return self

            def __call__(self, toks, repr_layers=None, return_contacts=False):
                return {"representations":
                        {repr_layer: _position_token_reps(toks)}}

        return _Model(), _Converter()

    def test_esm1b_keeps_residues_drops_bos_and_eos(self):
        g = GOLD["esm1b"]
        vocab = g["vocab"]
        # the replay reproduces the recorded encoding exactly
        got = _esm_tokenize_one(self.SEQ, vocab, g["prepend_bos"],
                                g["append_eos"])
        assert got == g["token_ids"]

        w = ESMEmbedWrapper(alphafold2=None)
        w._backend = self._esm_backend(g, vocab, ESMEmbedWrapper.REPR_LAYER)
        emb, _ = w.embed_batch(tokenize(self.SEQ)[None])
        np.testing.assert_allclose(
            emb[0, :, 0], np.asarray(g["residue_positions"], np.float32))
        # kept positions carry residue token ids only — BOS (<cls>) and
        # the trailing <eos> ESM-1b appends are both outside the slice
        np.testing.assert_allclose(
            emb[0, :, 1], np.asarray([vocab[c] for c in self.SEQ],
                                     np.float32))

    def test_esm_pad_token_survives_text_prep(self):
        """'_' padding must reach ESM as the '<pad>' special (id 1), not
        as an unknown character."""
        g = GOLD["esm1b"]
        text = self.SEQ + "<pad>"
        ids = _esm_tokenize_one(text, g["vocab"], g["prepend_bos"],
                                g["append_eos"])
        assert ids[len(self.SEQ) + 1] == g["vocab"]["<pad>"]

        w = ESMEmbedWrapper(alphafold2=None)
        w._backend = self._esm_backend(g, g["vocab"],
                                       ESMEmbedWrapper.REPR_LAYER)
        toks = tokenize(self.SEQ + "_")[None]
        emb, _ = w.embed_batch(toks)
        # padded slot still occupies one encoder position (id 1 = <pad>)
        assert emb.shape[1] == toks.shape[-1]
        assert emb[0, -1, 1] == g["vocab"]["<pad>"]

    def test_msa_transformer_no_eos_row_layout(self):
        g = GOLD["msa_transformer"]
        vocab = GOLD["esm1b"]["vocab"]
        got = _esm_tokenize_one(self.SEQ, vocab, g["prepend_bos"],
                                g["append_eos"])
        assert got == g["token_ids"]

        class _MsaConverter:
            def __call__(self, data):
                rows = [_esm_tokenize_one(s, vocab, g["prepend_bos"],
                                          g["append_eos"]) for _, s in data]
                # MSABatchConverter returns (1, R, L+1)
                return None, None, torch.tensor([rows], dtype=torch.long)

        class _MsaModel:
            def eval(self):
                return self

            def __call__(self, toks, repr_layers=None):
                return {"representations":
                        {MSAEmbedWrapper.REPR_LAYER:
                         _position_token_reps(toks)}}

        w = MSAEmbedWrapper(alphafold2=None)
        w._backend = (_MsaModel(), _MsaConverter())
        msa = np.stack([tokenize(self.SEQ), tokenize(self.SEQ)])[None]
        seq_emb, msa_emb = w.embed_batch(None, msa)
        assert msa_emb.shape[:3] == (1, 2, len(self.SEQ))
        for r in range(2):
            np.testing.assert_allclose(
                msa_emb[0, r, :, 0],
                np.asarray(g["residue_positions"], np.float32))
        # seq embedding is the query row (reference embeds.py:70-73)
        np.testing.assert_allclose(seq_emb[0], msa_emb[0, 0])

    def test_prot_bert_cls_sep_framing(self):
        g = GOLD["prot_bert"]
        vocab = g["vocab"]

        def encode(text):
            ids = [vocab["[CLS]"]] + [vocab[c] for c in text.split()] \
                + [vocab["[SEP]"]]
            return ids

        assert encode(" ".join(self.SEQ)) == g["token_ids"]

        class _Tok:
            def __call__(self, texts, return_tensors="pt", padding=True):
                return {"input_ids": torch.tensor(
                    [encode(t) for t in texts], dtype=torch.long)}

        class _Bert:
            def __call__(self, **enc):
                class R:
                    last_hidden_state = _position_token_reps(
                        enc["input_ids"])
                return R()

        w = ProtTranEmbedWrapper(alphafold2=None)
        w._backend = (_Bert(), _Tok())
        emb, _ = w.embed_batch(tokenize(self.SEQ)[None])
        np.testing.assert_allclose(
            emb[0, :, 0], np.asarray(g["residue_positions"], np.float32))
        np.testing.assert_allclose(
            emb[0, :, 1], np.asarray([vocab[c] for c in self.SEQ],
                                     np.float32))

    def test_prot_t5_no_bos_trailing_eos(self):
        g = GOLD["prot_t5"]
        vocab = g["vocab"]

        def encode(text):
            return [vocab[c] for c in text.split()] + [vocab["</s>"]]

        assert encode(" ".join(self.SEQ)) == g["token_ids"]

        class _Tok:
            def batch_encode_plus(self, texts, add_special_tokens=True,
                                  padding=True, return_tensors="pt"):
                ids = torch.tensor([encode(t) for t in texts],
                                   dtype=torch.long)
                return {"input_ids": ids,
                        "attention_mask": torch.ones_like(ids)}

        class _T5:
            def __call__(self, input_ids=None, attention_mask=None):
                class R:
                    last_hidden_state = _position_token_reps(input_ids)
                return R()

        w = ProtT5EmbedWrapper(alphafold2=None)
        w._backend = (_T5(), _Tok())
        emb, _ = w.embed_batch(tokenize(self.SEQ)[None])
        # T5 has no CLS: position 0 is residue 0; only </s> is dropped
        np.testing.assert_allclose(
            emb[0, :, 0], np.asarray(g["residue_positions"], np.float32))
        np.testing.assert_allclose(
            emb[0, :, 1], np.asarray([vocab[c] for c in self.SEQ],
                                     np.float32))
