"""Embed-wrapper tests with stubbed pretrained backends.

The real LMs (ESM-1b, MSA-Transformer, ProtBert, ProtT5) cannot be
downloaded in this container, so these tests stub `_load()` with tiny
fakes that honor each hub's tokenization protocol, and verify the parts
that are OUR logic: special-token slicing, MSA flattening/reshaping, and
injection of `seq_embed`/`msa_embed` into Alphafold2 (reference
embeds.py:10-103, utils.py:295-390).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

from alphafold2_tpu import Alphafold2, constants
from alphafold2_tpu.embeds import (ProtT5EmbedWrapper, ProtTranEmbedWrapper)


class _FakeT5Tokenizer:
    """Space-separated residues in, ids + trailing </s> out (ProtT5 has
    no leading CLS — the asymmetry vs BERT that the slicing must honor)."""

    def batch_encode_plus(self, texts, add_special_tokens=True,
                          padding=True, return_tensors="pt"):
        n = max(len(t.split()) for t in texts)
        ids = torch.zeros((len(texts), n + 1), dtype=torch.long)
        mask = torch.zeros_like(ids)
        for i, t in enumerate(texts):
            L = len(t.split())
            ids[i, :L] = torch.arange(1, L + 1)
            ids[i, L] = 99  # </s>
            mask[i, :L + 1] = 1
        return {"input_ids": ids, "attention_mask": mask}


class _FakeT5Encoder:
    """last_hidden_state[b, i, :] encodes the token position i so the
    test can check which positions the wrapper keeps."""

    DIM = 8

    def __call__(self, input_ids=None, attention_mask=None):
        b, n = input_ids.shape
        h = torch.arange(n, dtype=torch.float32)[None, :, None]
        out = h.expand(b, n, self.DIM).clone()

        class R:
            last_hidden_state = out
        return R()


class TestProtT5Wrapper:
    def _wrapper(self):
        w = ProtT5EmbedWrapper(alphafold2=None)
        w._backend = (_FakeT5Encoder(), _FakeT5Tokenizer())
        return w

    def test_seq_slicing_drops_only_trailing_eos(self):
        w = self._wrapper()
        seq = np.zeros((2, 5), dtype=np.int32)  # 5 residues
        emb, msa_emb = w.embed_batch(seq)
        assert emb.shape == (2, 5, _FakeT5Encoder.DIM)
        assert msa_emb is None
        # positions 0..4 kept (no CLS shift), </s> at position 5 dropped
        np.testing.assert_allclose(emb[0, :, 0], np.arange(5.0))

    def test_msa_flatten_roundtrip(self):
        w = self._wrapper()
        seq = np.zeros((1, 4), dtype=np.int32)
        msa = np.zeros((1, 3, 4), dtype=np.int32)
        emb, msa_emb = w.embed_batch(seq, msa)
        assert emb.shape == (1, 4, _FakeT5Encoder.DIM)
        assert msa_emb.shape == (1, 3, 4, _FakeT5Encoder.DIM)

    def test_t5_dim_constant(self):
        assert constants.NUM_EMBEDDS_T5 == 1024


class TestInjection:
    def test_wrapper_call_injects_embeds(self):
        """__call__ feeds seq_embed/msa_embed into Alphafold2.apply; the
        wrapped model must accept the LM dims and produce a distogram."""
        model = Alphafold2(dim=32, depth=1, heads=2, dim_head=8,
                           dtype=jnp.float32)
        b, n, m, d = 1, 6, 2, 16
        seq = jnp.zeros((b, n), dtype=jnp.int32)
        msa = jnp.zeros((b, m, n), dtype=jnp.int32)
        seq_embed = jnp.ones((b, n, d), dtype=jnp.float32)
        msa_embed = jnp.ones((b, m, n, d), dtype=jnp.float32)
        params = model.init(jax.random.PRNGKey(0), seq, msa=msa,
                            seq_embed=seq_embed, msa_embed=msa_embed)

        class _Stub(ProtT5EmbedWrapper):
            def embed_batch(self, seq, msa=None):
                return np.asarray(seq_embed), np.asarray(msa_embed)

        w = _Stub(model, params=params)
        out = w(seq=seq, msa=msa)  # non-coords model -> ReturnValues
        assert out.distance.shape[:3] == (b, n, n)
        assert np.all(np.isfinite(np.asarray(out.distance)))


class TestProtTranWrapper:
    def test_bert_slicing_drops_leading_cls(self):
        """ProtBert-style: CLS at 0, so the wrapper keeps 1..L."""

        class _FakeBertTok:
            def __call__(self, texts, return_tensors="pt", padding=True):
                n = max(len(t.split()) for t in texts)

                class E(dict):
                    pass
                e = E()
                e["input_ids"] = torch.zeros((len(texts), n + 2),
                                             dtype=torch.long)
                e["attention_mask"] = torch.ones_like(e["input_ids"])
                return e

        class _FakeBert:
            def __call__(self, **enc):
                ids = enc["input_ids"]
                b, n = ids.shape
                h = torch.arange(n, dtype=torch.float32)[None, :, None]

                class R:
                    last_hidden_state = h.expand(b, n, 4).clone()
                return R()

        w = ProtTranEmbedWrapper(alphafold2=None)
        w._backend = (_FakeBert(), _FakeBertTok())
        seq = np.zeros((1, 5), dtype=np.int32)
        emb, _ = w.embed_batch(seq)
        assert emb.shape == (1, 5, 4)
        # CLS (position 0) dropped: first kept position is 1
        np.testing.assert_allclose(emb[0, :, 0], np.arange(1.0, 6.0))
