"""Continuous batching tests (ISSUE 11): row-masked init numerics
(admitted row byte-equal to folding the same request alone), per-row
recycle accounting, admission ordering (deadline/priority) + HBM guard,
multi-chip in-place admission via the rows map, preemption composing
with freed-row claims, the continuous=False scrubbed-stats identity
pin, warmup of the init_rows variant, the admitted-duplicate
coalescing bugfix, and the loadtest --continuous flag surface."""

import json
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu import Alphafold2
from alphafold2_tpu.cache import FoldCache
from alphafold2_tpu.data.synthetic import synthetic_requests
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, FoldExecutor,
                                  FoldMemoryModel, FoldRequest,
                                  MeshPolicy, RecyclePolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)

MSA_DEPTH = 3


@pytest.fixture(scope="module")
def model_and_params():
    model = Alphafold2(dim=32, depth=1, heads=2, dim_head=16,
                       predict_coords=True, structure_module_depth=1)
    n = 16
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, n), jnp.int32),
        msa=jnp.zeros((1, MSA_DEPTH, n), jnp.int32),
        mask=jnp.ones((1, n), bool),
        msa_mask=jnp.ones((1, MSA_DEPTH, n), bool))
    return model, params


def requests_of(lengths, key=1):
    return synthetic_requests(jax.random.PRNGKey(key),
                              num=len(lengths), lengths=lengths,
                              msa_depth=MSA_DEPTH)


class GatedInitExecutor(FoldExecutor):
    """Real executor whose FIRST armed run_init blocks until released:
    the deterministic window for submitting work that must be admitted
    MID-LOOP rather than riding the founder batch."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.reached = threading.Event()
        self.release = threading.Event()
        self.armed = False

    def run_init(self, *a, **k):
        out = super().run_init(*a, **k)
        if self.armed:
            self.armed = False
            self.reached.set()
            assert self.release.wait(timeout=120)
        return out


def _scheduler(model_and_params, policy=None, num_recycles=2,
               buckets=(16,), max_batch=2, ex_cls=FoldExecutor, **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    ex = ex_cls(*model_and_params, max_entries=8)
    sched = Scheduler(
        ex, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=MSA_DEPTH),
        recycle_policy=policy, **kw)
    return ex, sched


class TestRowMaskedInit:
    def test_fold_init_rows_numerics(self, model_and_params):
        """The admission program's two contracts at the executor level:
        survivor rows pass through BYTE-identical, admitted rows equal
        a fresh init — and a step after admission equals folding the
        admitted request alone."""
        ex = FoldExecutor(*model_and_params, max_entries=8)
        pol = BucketPolicy((16,))
        a, b = requests_of((12, 10), key=5)
        batch, _ = pol.assemble([a, b], 16, 2)
        st1 = ex.run_step(batch, ex.run_init(batch), 1)
        new = requests_of((8,), key=6)[0]
        batch2, _ = pol.assemble([new, b], 16, 2)
        st2 = ex.run_init_rows(batch2, st1, np.array([True, False]))
        np.testing.assert_array_equal(np.asarray(st1.coords)[1],
                                      np.asarray(st2.coords)[1])
        np.testing.assert_array_equal(
            np.asarray(st1.recyclables.pairwise_repr)[1],
            np.asarray(st2.recyclables.pairwise_repr)[1])
        fresh = ex.run_init(batch2)
        np.testing.assert_array_equal(np.asarray(fresh.coords)[0],
                                      np.asarray(st2.coords)[0])
        st3 = ex.run_step(batch2, st2, 2)
        alone_batch, _ = pol.assemble([new], 16, 2)
        alone = ex.run_step(alone_batch, ex.run_init(alone_batch), 1)
        np.testing.assert_array_equal(np.asarray(st3.coords)[0],
                                      np.asarray(alone.coords)[0])

    def test_admitted_row_byte_equal_folded_alone(self,
                                                  model_and_params):
        """ISSUE 11 acceptance at tol 0, end to end through the
        scheduler: a request admitted into a freed row mid-loop serves
        final coords BYTE-equal to the same request folded alone, with
        its OWN full recycle count."""
        a, b = requests_of((12, 10), key=5)
        ex, sched = _scheduler(
            model_and_params,
            RecyclePolicy(converge_tol=0.0, continuous=True),
            ex_cls=GatedInitExecutor)
        sched.warmup()
        ex.armed = True
        sched.start()
        try:
            ta = sched.submit(FoldRequest(seq=a.seq, msa=a.msa))
            assert ex.reached.wait(timeout=120)
            tb = sched.submit(FoldRequest(seq=b.seq, msa=b.msa))
            time.sleep(0.1)       # let B reach the pending queue
            ex.release.set()
            ra = ta.result(timeout=300)
            rb = tb.result(timeout=300)
        finally:
            sched.stop()
        assert ra.ok and rb.ok, (ra.error, rb.error)
        assert ra.recycles == 2 and rb.recycles == 2
        rec = sched.serve_stats()["recycle"]
        assert rec["row_admissions"] == 1
        assert 0 < rec["rows_occupied_fraction"] < 1
        _, alone = _scheduler(model_and_params,
                              RecyclePolicy(converge_tol=0.0))
        with alone:
            rb2 = alone.submit(
                FoldRequest(seq=b.seq, msa=b.msa)).result(timeout=300)
        np.testing.assert_array_equal(rb.coords, rb2.coords)
        np.testing.assert_array_equal(rb.confidence, rb2.confidence)

    def test_warmup_compiles_row_init_variant(self, model_and_params):
        ex = FoldExecutor(*model_and_params, max_entries=8)
        fresh = ex.warmup([(16, 2, MSA_DEPTH, 3)], step_mode=True,
                          continuous=True)
        assert fresh == 3                    # init + init_rows + step
        variants = {k[6] for k in ex.stats()["keys"]}
        assert variants == {"init", "init_rows", "step"}
        # the scheduler's warmup warms what continuous serving runs:
        # a mid-loop admission afterwards never compiles
        ex2, sched = _scheduler(
            model_and_params,
            RecyclePolicy(converge_tol=0.0, continuous=True))
        assert sched.warmup() == 3
        assert "init_rows" in {k[6] for k in ex2.stats()["keys"]}


class _ContStub:
    """Step/admission-capable executor stub with deterministic per-row
    convergence: a row's coords climb 1.0 per step until the plan's
    converge count for its request (keyed by the seq's first token),
    then freeze — its inter-recycle delta drops to 0 exactly at age
    plan+1. An optional gate blocks inside the armed run_step so the
    test can inject pending work at a chosen recycle gap."""

    def __init__(self, plan):
        self.plan = plan              # first token -> freeze count
        self.calls = []
        self.reached = threading.Event()
        self.release = threading.Event()
        self.gate_at = None           # recycle index to block at
        self._lock = threading.Lock()

    def _mk_state(self, ids, counts, b, n):
        coords = np.zeros((b, n, 3), np.float32)
        for i, c in enumerate(counts):
            coords[i] = float(c)
        return SimpleNamespace(coords=coords,
                               confidence=np.zeros((b, n), np.float32),
                               recyclables=None,
                               ids=np.array(ids), counts=np.array(counts))

    def run_init(self, batch, trace=None, devices=None,
                 mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        ids = seq[:, 0]
        with self._lock:
            self.calls.append(("init", [int(i) for i in ids]))
        return self._mk_state(ids, [0] * b, b, n)

    def run_init_rows(self, batch, state, row_mask, trace=None,
                      devices=None, mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        mask = np.asarray(row_mask)
        ids = state.ids.copy()
        counts = state.counts.copy()
        ids[mask] = seq[:, 0][mask]
        counts[mask] = 0
        with self._lock:
            self.calls.append(
                ("init_rows", [int(i) for i in seq[:, 0][mask]]))
        return self._mk_state(ids, counts, b, n)

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None, span_attrs=None):
        b, n = np.asarray(batch["seq"]).shape
        with self._lock:
            self.calls.append(("step", int(recycle_index)))
            gated = self.gate_at is not None \
                and recycle_index == self.gate_at
            if gated:
                self.gate_at = None
        if gated:
            self.reached.set()
            assert self.release.wait(timeout=60)
        counts = [min(int(c) + 1,
                      self.plan.get(int(t), 10 ** 9))
                  for t, c in zip(state.ids, state.counts)]
        time.sleep(0.01)
        return self._mk_state(state.ids, counts, b, n)

    def run(self, batch, num_recycles, **kw):       # opaque fallback
        st = self.run_init(batch)
        return SimpleNamespace(coords=st.coords,
                               confidence=st.confidence)

    def stats(self):
        return {"calls": len(self.calls)}


def _stub_sched(stub, num_recycles, policy, max_batch=2,
                buckets=(32,), **kw):
    kw.setdefault("metrics", ServeMetrics(registry=MetricsRegistry()))
    kw.setdefault("registry", MetricsRegistry())
    return Scheduler(
        stub, BucketPolicy(buckets),
        SchedulerConfig(max_batch_size=max_batch, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=0),
        recycle_policy=policy, **kw)


def _req(token, length=12, **kw):
    return FoldRequest(seq=np.full(length, token, np.int32), **kw)


class TestPerRowAccounting:
    def test_recycles_reported_per_row_age(self):
        """Founders and admitted rows each report recycles against
        their OWN age: a founder that converges at 2 says 2, a row
        admitted mid-loop that runs its full depth says num_recycles —
        even though the loop stepped far past that for the founders."""
        stub = _ContStub({1: 1, 2: 10 ** 9, 3: 10 ** 9})
        stub.gate_at = 2
        sched = _stub_sched(
            stub, 4, RecyclePolicy(converge_tol=0.5, continuous=True,
                                   preempt=False))
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            t2 = sched.submit(_req(2))
            assert stub.reached.wait(timeout=60)
            t3 = sched.submit(_req(3))       # pending mid-loop
            time.sleep(0.05)
            stub.release.set()
            r1 = t1.result(timeout=60)
            r2 = t2.result(timeout=60)
            r3 = t3.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r2.ok and r3.ok
        # token 1 freezes at count 1 -> delta 0 at age 2 -> early exit
        assert r1.recycles == 2
        # token 2 never converges -> full depth
        assert r2.recycles == 4
        # token 3 admitted into token 1's freed row, runs ITS full
        # depth from age 0 (never measured against the pre-admission
        # occupant's state)
        assert r3.recycles == 4
        rec = sched.serve_stats()["recycle"]
        assert rec["row_admissions"] == 1
        assert rec["retired_early"] == 1
        assert ("init_rows", [3]) in stub.calls

    def test_admission_deadline_order(self):
        """Freed rows fill tightest-deadline-first, then FIFO: an
        urgent fold submitted AFTER a bulk one still claims the first
        freed row — composing with preemption without needing a batch
        gap (preemptions stays 0)."""
        stub = _ContStub({1: 1, 2: 10 ** 9, 3: 1, 4: 1})
        stub.gate_at = 2
        sched = _stub_sched(
            stub, 6, RecyclePolicy(converge_tol=0.5, continuous=True,
                                   preempt=True))
        order = []
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            t2 = sched.submit(_req(2))
            assert stub.reached.wait(timeout=60)
            t4 = sched.submit(_req(4))                 # bulk, FIFO-first
            t3 = sched.submit(_req(3, deadline_s=30.0))  # urgent, later
            for tok, t in ((4, t4), (3, t3)):
                t.add_done_callback(
                    lambda r, tok=tok: order.append(tok))
            time.sleep(0.05)
            stub.release.set()
            rs = [t.result(timeout=60) for t in (t1, t2, t3, t4)]
        finally:
            sched.stop()
        assert all(r.ok for r in rs)
        admitted = [c[1] for c in stub.calls if c[0] == "init_rows"]
        # the urgent fold claimed the FIRST freed row despite arriving
        # after the bulk one; the bulk fold took the next freed row
        assert admitted[0] == [3]
        assert [3] in admitted and [4] in admitted
        assert order.index(3) < order.index(4)
        rec = sched.serve_stats()["recycle"]
        assert rec["preemptions"] == 0
        assert rec["row_admissions"] == 2

    def test_admission_honors_hbm_guard(self):
        """A candidate the (tightened) HBM guard refuses is NOT
        admitted mid-loop — it returns to the pending queue and folds
        through normal batch formation once the loop ends."""
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        mem.hbm_bytes_per_device = 1 << 60       # admits everything
        pol = MeshPolicy({32: 1}, devices=jax.devices()[:1], memory=mem)
        stub = _ContStub({1: 10 ** 9})
        stub.gate_at = 1
        sched = _stub_sched(
            stub, 3, RecyclePolicy(converge_tol=0.5, continuous=True,
                                   preempt=False),
            mesh_policy=pol)
        sched.start()
        try:
            t1 = sched.submit(_req(1))      # founder, under-filled batch
            assert stub.reached.wait(timeout=60)
            t2 = sched.submit(_req(2))      # candidate for the free row
            time.sleep(0.05)
            # the guard tightens mid-flight: admission must refuse
            mem.hbm_bytes_per_device = 1
            stub.release.set()
            r1 = t1.result(timeout=60)
            r2 = t2.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r2.ok
        rec = sched.serve_stats()["recycle"]
        assert rec["row_admissions"] == 0
        # token 2 folded in its own batch afterwards, full depth
        assert r2.recycles == 3
        assert ("init", [2, 2]) in stub.calls or \
            ("init", [2]) in [(c[0], c[1][:1]) for c in stub.calls
                              if c[0] == "init"]

    def test_continuous_false_scrubbed_stats_identity(
            self, model_and_params):
        """The off switch: RecyclePolicy(continuous=False) leaves
        scrubbed serve_stats() byte-identical to a policy that never
        mentioned the field (same scrub discipline as the
        recycle_policy=None pin in test_recycle.py)."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(policy):
            _, sched = _scheduler(model_and_params, policy,
                                  num_recycles=1)
            reqs = requests_of((12, 8), key=9)
            with sched:
                for r in reqs:
                    assert sched.submit(
                        FoldRequest(seq=r.seq, msa=r.msa)).result(
                            timeout=300).ok
            return scrub(sched.serve_stats())

        explicit_off = run_one(RecyclePolicy(converge_tol=0.0,
                                             continuous=False))
        never_heard = run_one(RecyclePolicy(converge_tol=0.0))
        assert json.dumps(explicit_off, sort_keys=True, default=str) \
            == json.dumps(never_heard, sort_keys=True, default=str)
        assert explicit_off["recycle"]["row_admissions"] == 0
        assert explicit_off["recycle"]["continuous"] is False


class TestInlineWorkerLiveness:
    def test_other_bucket_past_max_wait_stops_admission(self):
        """The inline (no-mesh) continuous loop runs ON the worker
        thread: with a same-bucket backlog feeding admissions it could
        hold the worker forever while other buckets starve. The
        admission gate yields as soon as another bucket is past its
        max_wait window: the loop stops refilling (admissions stay
        well below the backlog) and the other bucket's request still
        resolves."""
        plan = {t: 1 for t in range(1, 12)}   # everyone converges fast
        plan[99] = 1
        stub = _ContStub(plan)
        stub.gate_at = 1
        sched = _stub_sched(
            stub, 4, RecyclePolicy(converge_tol=0.5, continuous=True,
                                   preempt=False),
            max_batch=2, buckets=(32, 64))
        backlog = 8
        sched.start()
        try:
            t0 = sched.submit(_req(1))
            assert stub.reached.wait(timeout=60)
            tickets = [sched.submit(_req(2 + i)) for i in range(backlog)]
            t_other = sched.submit(_req(99, length=40))  # bucket 64
            time.sleep(0.05)
            stub.release.set()
            r_other = t_other.result(timeout=60)
            rs = [t.result(timeout=60) for t in [t0] + tickets]
        finally:
            sched.stop()
        assert r_other.ok
        assert all(r.ok for r in rs)
        # the gate halted refills once bucket 64 went past max_wait:
        # nowhere near the whole backlog rode the first loop
        rec = sched.serve_stats()["recycle"]
        assert rec["row_admissions"] < backlog

    def test_expired_pending_sheds_during_inline_loop(self):
        """The worker's expired-deadline sweep runs from the inline
        loop's admission gaps: a pending request whose deadline dies
        mid-loop resolves "shed" promptly instead of hanging until the
        loop ends (admission itself skips expired entries by design,
        so without the in-loop sweep they would wait out the whole
        batch)."""
        stub = _ContStub({1: 10 ** 9})        # founder never converges
        stub.gate_at = 1
        sched = _stub_sched(
            stub, 40, RecyclePolicy(converge_tol=0.5, continuous=True,
                                    preempt=False),
            max_batch=1)                      # no free rows: only the
        #                                       sweep can serve C
        done = {}
        sched.start()
        try:
            t1 = sched.submit(_req(1))
            assert stub.reached.wait(timeout=60)
            tc = sched.submit(_req(3, deadline_s=0.05))
            tc.add_done_callback(
                lambda r: done.setdefault("at", time.monotonic()))
            t_rel = time.monotonic()
            stub.release.set()
            rc = tc.result(timeout=60)
            assert rc.status == "shed"
            shed_after = done["at"] - t_rel
            r1 = t1.result(timeout=60)
        finally:
            sched.stop()
        assert r1.ok and r1.recycles == 40
        # 40 recycles at >= 10ms each: the loop ran ~0.4s+; the shed
        # landed from an early gap, not after the loop
        assert shed_after < 0.3, shed_after


class TestMultiChipAdmission:
    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="needs >= 2 devices")
    def test_inplace_admission_on_mesh_lease(self, model_and_params):
        """Admission on a multi-chip lease writes into freed rows via
        the position->row map (no physical repack of the mesh-sharded
        carry) from the dispatch-pool thread — and the admitted row's
        result is byte-equal to folding it alone on the same mesh."""
        a, b = requests_of((12, 10), key=5)

        def mk(gated):
            # pool of exactly ONE 2-chip slice: pending work cannot
            # dodge admission by grabbing a free slice of its own
            ex, sched = _scheduler(
                model_and_params,
                RecyclePolicy(converge_tol=0.0, continuous=True),
                ex_cls=GatedInitExecutor if gated else FoldExecutor,
                mesh_policy=MeshPolicy({16: 2},
                                       devices=jax.devices()[:2]))
            return ex, sched

        ex, sched = mk(True)
        sched.warmup()
        ex.armed = True
        sched.start()
        try:
            ta = sched.submit(FoldRequest(seq=a.seq, msa=a.msa))
            assert ex.reached.wait(timeout=300)
            tb = sched.submit(FoldRequest(seq=b.seq, msa=b.msa))
            time.sleep(0.1)
            ex.release.set()
            ra = ta.result(timeout=300)
            rb = tb.result(timeout=300)
        finally:
            sched.stop()
        assert ra.ok and rb.ok, (ra.error, rb.error)
        stats = sched.serve_stats()
        assert stats["recycle"]["row_admissions"] == 1
        assert "1x2" in stats["mesh"]["folds"]      # ran sharded
        _, alone = mk(False)
        alone.warmup()
        with alone:
            rb2 = alone.submit(
                FoldRequest(seq=b.seq, msa=b.msa)).result(timeout=300)
        np.testing.assert_array_equal(rb.coords, rb2.coords)


class TestAdmittedDuplicateCoalesces:
    def test_inflight_duplicate_parks_never_double_folds(
            self, model_and_params):
        """Bugfix satellite: an admission candidate that is an
        in-flight duplicate (the saturated block-mode fall-through:
        store_key set, not a leader) attaches as a coalescing follower
        instead of burning a row on a double fold — and the leader's
        fold populates the store under the policy's own key_extras
        keying, settling it."""
        cache = FoldCache(registry=MetricsRegistry())
        policy = RecyclePolicy(converge_tol=1e9, min_recycles=3,
                               continuous=True, preempt=False)
        a, b = requests_of((12, 10), key=5)
        ex, sched = _scheduler(
            model_and_params, policy, num_recycles=3,
            ex_cls=GatedInitExecutor, cache=cache, model_tag="v1")
        # saturate the queue so the duplicate takes the block-mode
        # fall-through (store_key, no leader attach)
        sched.config.queue_limit = 1
        sched.config.full_policy = "block"
        sched.warmup()
        ex.armed = True
        sched.start()
        dup_box = {}

        def submit_dup():
            t = sched.submit(FoldRequest(seq=b.seq.copy(),
                                         msa=b.msa.copy()))
            dup_box["ticket"] = t

        try:
            ta = sched.submit(FoldRequest(seq=a.seq, msa=a.msa))
            assert ex.reached.wait(timeout=120)
            tb = sched.submit(FoldRequest(seq=b.seq, msa=b.msa))
            # a duplicate of B while the queue is full: blocks until
            # B's admission frees capacity, then enqueues with
            # store_key only (the fall-through under test)
            th = threading.Thread(target=submit_dup, daemon=True)
            th.start()
            time.sleep(0.1)
            ex.release.set()
            ra = ta.result(timeout=300)
            rb = tb.result(timeout=300)
            th.join(timeout=120)
            rdup = dup_box["ticket"].result(timeout=300)
        finally:
            sched.stop()
        assert ra.ok and rb.ok and rdup.ok
        assert rdup.source == "coalesced"
        # exactly one admission (B); the duplicate never burned a row
        rec = sched.serve_stats()["recycle"]
        assert rec["row_admissions"] == 1
        # the result landed in the store under the SAME key the
        # queue path uses (RecyclePolicy.key_extras included)
        key = sched._cache_key_for(FoldRequest(seq=b.seq, msa=b.msa))
        assert cache.get(key) is not None
        np.testing.assert_array_equal(rb.coords, rdup.coords)


class TestMemoryPricing:
    def test_continuous_admission_seam_priced(self):
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        plain = mem.fold_bytes(256, 2, 3)
        carry = mem.fold_bytes(256, 2, 3, carry_recyclables=True)
        cont = mem.fold_bytes(256, 2, 3, carry_recyclables=True,
                              continuous=True)
        assert plain < carry < cont

    def test_admits_flips_under_continuous(self):
        mem = FoldMemoryModel(param_bytes=0, dim=64, heads=4)
        L, B, M = 256, 2, 3
        carry = mem.fold_bytes(L, B, M, carry_recyclables=True)
        cont = mem.fold_bytes(L, B, M, carry_recyclables=True,
                              continuous=True)
        mem.hbm_bytes_per_device = (carry + cont) // 2
        pol = MeshPolicy({L: 1}, devices=[0], memory=mem)
        assert pol.admits(L, B, M, carry_recyclables=True)
        assert not pol.admits(L, B, M, carry_recyclables=True,
                              continuous=True)


class TestLoadtestFlags:
    def test_continuous_flags_fast(self, tmp_path, capsys):
        """Tier-1 flag-rot tripwire: the --continuous/--converge-
        percentile surface drives a real (tiny) run and reports the
        occupancy fields."""
        import sys
        sys.path.insert(0, "tools")
        try:
            import serve_loadtest
        finally:
            sys.path.pop(0)
        rc = serve_loadtest.main([
            "--requests", "8", "--concurrency", "4",
            "--lengths", "12", "--buckets", "16",
            "--msa-depth", str(MSA_DEPTH), "--max-batch", "2",
            "--max-wait-ms", "5", "--num-recycles", "2",
            "--continuous", "--converge-percentile", "50",
            "--dim", "32", "--depth", "1",
            "--metrics-path", str(tmp_path / "m.jsonl")])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert report["continuous"] is True
        assert report["served"] == 8
        assert "rows_occupied_fraction" in report
        assert "row_admissions" in report
        assert report["converge_tol_calibrated"] > 0
        assert report["recycle"]["continuous"] is True
