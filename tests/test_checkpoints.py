"""Durable mid-loop checkpoint tests (ISSUE 18): the codec (roundtrip
byte-equality, self-identification, sharding specs on a single chip
AND an 8-fake-device mesh), the ByteStore enumeration satellites
(keys()/scan(), TTL sweep during scan, the disk-TTL bugfix), the
CheckpointStore tiers (spill/prune/latest/discard/survivors, stale-tag
discard + counter, backend mirror, the peer duck-type), and the
scheduler integration: spill-at-cadence, restart -> resume-at-age
byte-equality with bounded recycles_lost, terminal discard, and the
knob-off scrubbed-stats + metric-name identity pin.

Scheduler tests run a pytree-carry scripted stub (numpy-only stubs
snapshot as opaque reference leaves and are correctly refused by
row_checkpoint) — coords accumulate multiplicatively so a refold from
zero with fewer steps CANNOT byte-match a resumed loop.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alphafold2_tpu.cache.bytestore import ByteStore
from alphafold2_tpu.cache.checkpoints import (CheckpointStore,
                                              RowCheckpoint,
                                              checkpoint_group,
                                              checkpoint_key,
                                              decode_checkpoint,
                                              encode_checkpoint, key_age,
                                              row_checkpoint,
                                              sharding_from_spec,
                                              sharding_spec)
from alphafold2_tpu.obs.registry import MetricsRegistry
from alphafold2_tpu.serve import (BucketPolicy, FoldRequest,
                                  RecyclePolicy, RetryPolicy, Scheduler,
                                  SchedulerConfig, ServeMetrics)


# -- pytree-carry step stub -------------------------------------------


class _PtState:
    def __init__(self, coords, confidence, ids, counts):
        self.coords = coords
        self.confidence = confidence
        self.ids = ids
        self.counts = counts


jax.tree_util.register_pytree_node(
    _PtState,
    lambda s: ((s.coords, s.confidence, s.ids, s.counts), None),
    lambda aux, ch: _PtState(*ch))


class _PtStub:
    def __init__(self):
        self.calls = []

    def run_init(self, batch, trace=None, devices=None, mesh_shape=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        self.calls.append(("init", [int(i) for i in seq[:, 0]]))
        return _PtState(jnp.zeros((b, n, 3), jnp.float32),
                        jnp.zeros((b, n), jnp.float32),
                        jnp.asarray(seq[:, 0], jnp.int32),
                        jnp.zeros((b,), jnp.int32))

    def run_init_rows(self, batch, state, row_mask, trace=None,
                      devices=None, mesh_shape=None, span_attrs=None):
        seq = np.asarray(batch["seq"])
        b, n = seq.shape
        mask = jnp.asarray(np.asarray(row_mask))
        self.calls.append(("init_rows", int(np.asarray(row_mask).sum())))
        return _PtState(
            jnp.where(mask[:, None, None],
                      jnp.zeros((b, n, 3), jnp.float32), state.coords),
            jnp.where(mask[:, None],
                      jnp.zeros((b, n), jnp.float32), state.confidence),
            jnp.where(mask, jnp.asarray(seq[:, 0], jnp.int32), state.ids),
            jnp.where(mask, 0, state.counts))

    def run_step(self, batch, state, recycle_index, trace=None,
                 devices=None, mesh_shape=None, span_attrs=None):
        self.calls.append(("step", int(recycle_index)))
        return _PtState(
            state.coords * jnp.float32(1.01) + jnp.float32(1.0)
            + state.ids[:, None, None].astype(jnp.float32) * 0.001,
            state.confidence, state.ids, state.counts + 1)

    def stats(self):
        return {"calls": len(self.calls)}

    def steps(self):
        return sum(1 for c in self.calls if c[0] == "step")


def _sched(stub, spill_dir, num_recycles=6, registry=None,
           checkpoint_every=1, **kw):
    registry = registry or MetricsRegistry()
    return Scheduler(
        stub, BucketPolicy((32,)),
        SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                        num_recycles=num_recycles, msa_depth=0,
                        poll_ms=2.0),
        recycle_policy=RecyclePolicy(converge_tol=0.0),
        retry=RetryPolicy(checkpoint_every=checkpoint_every,
                          checkpoint_spill=spill_dir or "",
                          backoff_base_s=0.0, jitter=0.0),
        metrics=ServeMetrics(registry=registry), registry=registry,
        **kw)


def _req(token=7, length=12):
    return FoldRequest(seq=np.full(length, token, np.int32))


def _mk_ckpt(fold_key="fk", tag="t@1", age=3, n=8, with_msa=False,
             leaves=None):
    return RowCheckpoint(
        fold_key=fold_key, model_tag=tag, age=age,
        seq=np.arange(n, dtype=np.int32),
        msa=(np.ones((2, n), np.int32) if with_msa else None),
        leaves=(leaves if leaves is not None else
                [("dev", np.arange(n * 3, dtype=np.float32)
                  .reshape(1, n, 3), None),
                 ("ref", 5, None)]),
        created_s=123.0)


# -- keys -------------------------------------------------------------


class TestKeys:
    def test_group_prefix_and_age_order(self):
        g = checkpoint_group("fk", "t@1")
        keys = [checkpoint_key("fk", "t@1", a) for a in (0, 2, 10)]
        assert all(k.startswith(g + "-a") for k in keys)
        assert sorted(keys) == keys            # zero-pad == age order
        assert [key_age(k) for k in keys] == [0, 2, 10]

    def test_tag_namespaces_group(self):
        assert checkpoint_group("fk", "t@1") != checkpoint_group(
            "fk", "t@2")
        assert checkpoint_group("fk", "t@1") != checkpoint_group(
            "other", "t@1")


# -- codec ------------------------------------------------------------


class TestCodec:
    def test_roundtrip_byte_equality(self):
        ck = _mk_ckpt(with_msa=True)
        key = checkpoint_key(ck.fold_key, ck.model_tag, ck.age)
        out = decode_checkpoint(key, encode_checkpoint(key, ck))
        assert out.fold_key == ck.fold_key
        assert out.model_tag == ck.model_tag
        assert out.age == ck.age and out.created_s == ck.created_s
        assert np.array_equal(out.seq, ck.seq)
        assert np.array_equal(out.msa, ck.msa)
        assert [k for k, _v, _s in out.leaves] == ["dev", "ref"]
        assert out.leaves[0][1].tobytes() == ck.leaves[0][1].tobytes()
        assert out.leaves[0][1].dtype == np.float32
        assert out.leaves[1][1] == 5

    def test_bfloat16_leaf_roundtrips(self):
        import ml_dtypes
        arr = np.arange(6, dtype=np.float32).reshape(1, 6).astype(
            ml_dtypes.bfloat16)
        ck = _mk_ckpt(leaves=[("dev", arr, None)])
        key = checkpoint_key(ck.fold_key, ck.model_tag, ck.age)
        out = decode_checkpoint(key, encode_checkpoint(key, ck))
        assert out.leaves[0][1].dtype == arr.dtype
        assert out.leaves[0][1].tobytes() == arr.tobytes()

    def test_embedded_key_mismatch_raises(self):
        ck = _mk_ckpt()
        key = checkpoint_key(ck.fold_key, ck.model_tag, ck.age)
        data = encode_checkpoint(key, ck)
        with pytest.raises(ValueError):
            decode_checkpoint(
                checkpoint_key("other", ck.model_tag, ck.age), data)

    def test_corrupt_bytes_raise(self):
        ck = _mk_ckpt()
        key = checkpoint_key(ck.fold_key, ck.model_tag, ck.age)
        data = encode_checkpoint(key, ck)
        with pytest.raises(Exception):
            decode_checkpoint(key, data[: len(data) // 2])

    def test_multi_row_leaf_refused(self):
        ck = _mk_ckpt(leaves=[("dev", np.zeros((2, 4), np.float32),
                               None)])
        key = checkpoint_key(ck.fold_key, ck.model_tag, ck.age)
        with pytest.raises(ValueError):
            decode_checkpoint(key, encode_checkpoint(key, ck))


# -- row slicing ------------------------------------------------------


class TestRowCheckpoint:
    def _snapshot(self, b=3, n=4):
        from alphafold2_tpu.predict import snapshot_step_state
        state = _PtState(
            jnp.arange(b * n * 3, dtype=jnp.float32).reshape(b, n, 3),
            jnp.ones((b, n), jnp.float32),
            jnp.arange(b, dtype=jnp.int32),
            jnp.full((b,), 5, jnp.int32))
        return snapshot_step_state(state)

    def test_slices_one_row(self):
        snap = self._snapshot()
        ck = row_checkpoint(snap, 1, fold_key="fk", model_tag="t",
                            age=2, seq=np.arange(4, dtype=np.int32))
        coords = ck.leaves[0][1]
        assert coords.shape == (1, 4, 3)
        assert np.array_equal(
            coords[0],
            np.arange(12, dtype=np.float32).reshape(4, 3) + 12)
        assert ck.leaves[3][1][0] == 5     # counts row

    def test_opaque_reference_leaf_refused(self):
        from alphafold2_tpu.predict import snapshot_step_state
        snap = snapshot_step_state({"arr": jnp.zeros((2, 3)),
                                    "opaque": object()})
        with pytest.raises(ValueError):
            row_checkpoint(snap, 0, fold_key="fk", model_tag="t",
                           age=1, seq=np.arange(3, dtype=np.int32))

    def test_restore_leaves_byte_equal(self):
        snap = self._snapshot()
        ck = row_checkpoint(snap, 2, fold_key="fk", model_tag="t",
                            age=2, seq=np.arange(4, dtype=np.int32))
        key = checkpoint_key("fk", "t", 2)
        out = decode_checkpoint(key, encode_checkpoint(key, ck))
        restored = out.restore_leaves()
        assert len(restored) == 4
        assert np.asarray(restored[0]).tobytes() == \
            np.asarray(snap[1][0][1][2:3]).tobytes()


# -- sharding specs ---------------------------------------------------


class TestShardingSpecs:
    def test_single_device_spec_is_none(self):
        arr = jnp.zeros((2, 3))
        assert sharding_spec(arr.sharding) is None or \
            sharding_from_spec(sharding_spec(arr.sharding)) is not None

    def test_mesh_spec_roundtrip_8_devices(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        devs = jax.devices()
        assert len(devs) >= 8, "conftest forces 8 fake devices"
        mesh = Mesh(np.asarray(devs[:8]).reshape(2, 4), ("dp", "mp"))
        sh = NamedSharding(mesh, PartitionSpec(None, "mp"))
        spec = sharding_spec(sh)
        assert spec == {"kind": "named", "axes": ["dp", "mp"],
                        "sizes": [2, 4], "spec": [None, "mp"]}
        back = sharding_from_spec(spec)
        assert back is not None
        arr = jax.device_put(
            np.arange(32, dtype=np.float32).reshape(4, 8), back)
        assert np.array_equal(np.asarray(arr),
                              np.arange(32, dtype=np.float32)
                              .reshape(4, 8))

    def test_mesh_sharded_checkpoint_roundtrips(self):
        """The resume contract on a mesh: a leaf snapshotted from a
        NamedSharding re-uploads byte-equal through its wire spec."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from alphafold2_tpu.predict import snapshot_step_state
        devs = jax.devices()
        mesh = Mesh(np.asarray(devs[:8]).reshape(8), ("mp",))
        sh = NamedSharding(mesh, PartitionSpec(None, "mp"))
        coords = jax.device_put(
            np.arange(2 * 8 * 3, dtype=np.float32).reshape(2, 8, 3),
            NamedSharding(mesh, PartitionSpec(None, "mp", None)))
        del sh
        snap = snapshot_step_state({"coords": coords})
        ck = row_checkpoint(snap, 1, fold_key="fk", model_tag="t",
                            age=3, seq=np.arange(8, dtype=np.int32))
        key = checkpoint_key("fk", "t", 3)
        out = decode_checkpoint(key, encode_checkpoint(key, ck))
        assert out.leaves[0][2] is not None        # spec traveled
        restored = out.restore_leaves()[0]
        assert np.asarray(restored).tobytes() == \
            np.asarray(coords[1:2]).tobytes()


# -- ByteStore enumeration satellites ---------------------------------


def _bytestore(tmp_path, ttl_s=None, clock=time.time):
    return ByteStore(
        encode=lambda k, v: v, decode=lambda k, b: b,
        max_bytes=1 << 20, max_entries=64, ttl_s=ttl_s,
        disk_dir=str(tmp_path / "bs"), clock=clock)


class TestByteStoreEnumeration:
    def test_keys_sorted_and_prefix_filtered(self, tmp_path):
        bs = _bytestore(tmp_path)
        for k in ("aa1", "aa2", "bb1"):
            bs.disk_put(k, b"v-" + k.encode())
        assert bs.keys() == ["aa1", "aa2", "bb1"]
        assert bs.keys("aa") == ["aa1", "aa2"]
        assert bs.keys("zz") == []

    def test_scan_yields_values(self, tmp_path):
        bs = _bytestore(tmp_path)
        bs.disk_put("aa1", b"one")
        bs.disk_put("ab2", b"two")
        assert dict(bs.scan("a")) == {"aa1": b"one", "ab2": b"two"}

    def test_keys_sweeps_expired_from_disk(self, tmp_path):
        """ISSUE-18 bugfix: disk TTL is enforced during enumeration,
        not just on point get — the expired file is REMOVED, so a
        restart-survivor sweep leaves no unreachable garbage. The
        disk clock is file mtime, so expiry is simulated by
        backdating the file."""
        bs = _bytestore(tmp_path, ttl_s=10.0)
        bs.disk_put("aa1", b"old")
        bs.disk_put("aa2", b"new")
        old = time.time() - 60
        os.utime(bs.path("aa1"), (old, old))
        assert bs.keys() == ["aa2"]
        assert not os.path.exists(bs.path("aa1"))
        assert bs.disk_get("aa2") is not None

    def test_scan_quarantines_corrupt(self, tmp_path):
        bs = ByteStore(
            encode=lambda k, v: v,
            decode=lambda k, b: (_ for _ in ()).throw(
                ValueError("corrupt")) if b == b"bad" else b,
            max_bytes=1 << 20, max_entries=64,
            disk_dir=str(tmp_path / "bs"))
        bs.disk_put("aa1", b"ok")
        bs.disk_put("aa2", b"bad")
        assert dict(bs.scan()) == {"aa1": b"ok"}


# -- CheckpointStore --------------------------------------------------


class TestCheckpointStore:
    def test_put_prunes_older_ages_and_latest_wins(self, tmp_path):
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             registry=MetricsRegistry())
        assert st.put_row(_mk_ckpt(age=1, tag="t@1")) is not None
        assert st.put_row(_mk_ckpt(age=4, tag="t@1")) is not None
        got = st.latest("fk")
        assert got is not None and got.age == 4
        # older age pruned from disk
        assert st.store.keys(st.group("fk")) == [
            checkpoint_key("fk", "t@1", 4)]

    def test_discard_and_miss(self, tmp_path):
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             registry=MetricsRegistry())
        st.put_row(_mk_ckpt(age=2))
        st.discard("fk")
        assert st.latest("fk") is None
        assert st.stats.snapshot()["discards"] >= 1

    def test_survivors_newest_per_group(self, tmp_path):
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             registry=MetricsRegistry())
        st.put_row(_mk_ckpt(fold_key="f1", age=2))
        st.put_row(_mk_ckpt(fold_key="f2", age=5))
        got = {ck.fold_key: ck.age for _k, ck in st.survivors()}
        assert got == {"f1": 2, "f2": 5}

    def test_stale_tag_survivors_swept_with_counter(self, tmp_path):
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             registry=MetricsRegistry())
        st.put_row(_mk_ckpt(age=2, tag="t@1"))
        st.model_tag = "t@2"        # rollout re-tag
        assert list(st.survivors()) == []
        assert st.stats.snapshot()["stale_tag_discards"] >= 1

    def test_latest_ignores_other_tag(self, tmp_path):
        a = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                            registry=MetricsRegistry())
        a.put_row(_mk_ckpt(age=2, tag="t@1"))
        a.model_tag = "t@2"
        assert a.latest("fk") is None

    def test_ttl_expires_checkpoints(self, tmp_path):
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             ttl_s=10.0, registry=MetricsRegistry())
        key = st.put_row(_mk_ckpt(age=2))
        old = time.time() - 60
        os.utime(st.store.path(key), (old, old))
        assert st.latest("fk") is None
        assert list(st.survivors()) == []

    def test_backend_mirror_and_fetch(self, tmp_path):
        backend = {}
        bk = type("Bk", (), {
            "put": lambda self, k, v: backend.__setitem__(k, v),
            "get": lambda self, k: backend.get(k)})()
        a = CheckpointStore(str(tmp_path / "a"), model_tag="t@1",
                            backend=bk, registry=MetricsRegistry())
        a.put_row(_mk_ckpt(age=3))
        assert len(backend) == 1       # mirrored under the GROUP key
        assert set(backend) == {a.group("fk")}
        # a different replica, same backend, empty local disk
        b = CheckpointStore(str(tmp_path / "b"), model_tag="t@1",
                            backend=bk, registry=MetricsRegistry())
        got = b.latest("fk")
        assert got is not None and got.age == 3
        assert b.stats.snapshot()["backend_hits"] == 1
        # promoted: next lookup is local
        assert b.store.keys(b.group("fk"))

    def test_peer_duck_type_fetch(self, tmp_path):
        ck = _mk_ckpt(age=4)
        key = checkpoint_key("fk", "t@1", 4)
        raw = encode_checkpoint(key, ck)

        class _Peer:
            def fetch_checkpoint(self, group, model_tag=""):
                return raw if group == checkpoint_group(
                    "fk", "t@1") else None

        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             peer=_Peer(), registry=MetricsRegistry())
        got = st.latest("fk")
        assert got is not None and got.age == 4
        assert st.stats.snapshot()["peer_hits"] == 1
        assert st.latest("other") is None

    def test_latest_raw_serves_wire_bytes(self, tmp_path):
        st = CheckpointStore(str(tmp_path / "ck"), model_tag="t@1",
                             registry=MetricsRegistry())
        st.put_row(_mk_ckpt(age=2))
        raw = st.latest_raw(st.group("fk"))
        assert raw is not None
        out = decode_checkpoint(checkpoint_key("fk", "t@1", 2), raw)
        assert out.age == 2
        assert st.latest_raw("nope") is None


# -- scheduler integration --------------------------------------------


class TestSchedulerSpillResume:
    def test_kill_restart_resume_byte_equal(self, tmp_path):
        """The acceptance choreography: spill on, loop interrupted
        (simulated by keeping the terminal checkpoint), restarted
        scheduler resumes at the checkpointed age — coords byte-equal
        to the uninterrupted run, recycles_lost <= checkpoint_every,
        and the survivor shows up in the boot count."""
        spill = str(tmp_path / "spill")
        stub_a = _PtStub()
        sa = _sched(stub_a, spill)
        # simulate dying before retirement: keep the last spill
        sa._ckpt_store.discard = lambda key: None
        with sa:
            ra = sa.submit(_req()).result(timeout=60)
        assert ra.ok and stub_a.steps() == 6

        stub_b = _PtStub()
        sb = _sched(stub_b, spill)
        assert sb._boot_survivors == 1
        with sb:
            rb = sb.submit(_req()).result(timeout=60)
        assert rb.ok
        st = sb.serve_stats()["resilience"]["checkpoint_spill"]
        assert st["spill_resumes"] == 1
        assert st["survivors_at_boot"] == 1
        # checkpoint_every=1 -> at most 1 recycle refolds
        assert stub_b.steps() <= 1
        assert np.array_equal(ra.coords, rb.coords)
        assert np.array_equal(ra.confidence, rb.confidence)

    def test_terminal_resolution_discards_checkpoint(self, tmp_path):
        spill = str(tmp_path / "spill")
        stub = _PtStub()
        s = _sched(stub, spill)
        with s:
            assert s.submit(_req()).result(timeout=60).ok
        st = s.serve_stats()["resilience"]["checkpoint_spill"]
        assert st["stats"]["spills"] >= 1
        assert st["stats"]["discards"] >= 1
        # nothing survives a clean completion
        assert sum(1 for _ in s._ckpt_store.survivors()) == 0

    def test_different_sequence_never_resumes(self, tmp_path):
        """A colliding store key cannot inject another fold's carry:
        the resume path validates the stored sequence against the
        request's before touching the state."""
        spill = str(tmp_path / "spill")
        stub_a = _PtStub()
        sa = _sched(stub_a, spill)
        sa._ckpt_store.discard = lambda key: None
        with sa:
            assert sa.submit(_req(token=3)).result(timeout=60).ok

        stub_b = _PtStub()
        sb = _sched(stub_b, spill)
        with sb:
            rb = sb.submit(_req(token=9)).result(timeout=60)
        assert rb.ok
        st = sb.serve_stats()["resilience"]["checkpoint_spill"]
        assert st["spill_resumes"] == 0
        assert stub_b.steps() == 6      # refolded from zero

    def test_rollout_retag_invalidates_survivors(self, tmp_path):
        spill = str(tmp_path / "spill")
        stub_a = _PtStub()
        sa = _sched(stub_a, spill, model_tag="m@1")
        sa._ckpt_store.discard = lambda key: None
        with sa:
            assert sa.submit(_req()).result(timeout=60).ok

        stub_b = _PtStub()
        sb = _sched(stub_b, spill, model_tag="m@1")
        sb.model_tag = "m@2"           # rollout before the fold
        with sb:
            rb = sb.submit(_req()).result(timeout=60)
        assert rb.ok
        assert sb.serve_stats()["resilience"]["checkpoint_spill"][
            "spill_resumes"] == 0
        assert stub_b.steps() == 6


class TestOffIdentity:
    def test_spill_off_stats_and_metric_names_identical(self):
        """checkpoint_spill off is byte-for-byte the PR 16 surface:
        scrubbed serve_stats() identical to a policy that never heard
        of the field, and none of the new metric names are minted."""
        def scrub(obj):
            if isinstance(obj, dict):
                return {k: scrub(v) for k, v in sorted(obj.items())
                        if k != "traces" and not k.endswith("_s")}
            if isinstance(obj, list):
                return [scrub(v) for v in obj]
            return obj

        def run_one(retry):
            reg = MetricsRegistry()
            sched = Scheduler(
                _PtStub(), BucketPolicy((32,)),
                SchedulerConfig(max_batch_size=2, max_wait_ms=5.0,
                                num_recycles=2, msa_depth=0,
                                poll_ms=2.0),
                recycle_policy=RecyclePolicy(converge_tol=0.0),
                retry=retry, metrics=ServeMetrics(registry=reg),
                registry=reg)
            with sched:
                assert sched.submit(_req()).result(timeout=60).ok
            return scrub(sched.serve_stats()), set(reg.snapshot())

        off, names_off = run_one(
            RetryPolicy(max_attempts=3, jitter=0.0,
                        checkpoint_every=1, checkpoint_spill=""))
        base, names_base = run_one(
            RetryPolicy(max_attempts=3, jitter=0.0,
                        checkpoint_every=1))
        assert json.dumps(off, sort_keys=True, default=str) == \
            json.dumps(base, sort_keys=True, default=str)
        assert names_off == names_base
        new = {"serve_spill_resumes_total",
               "fold_checkpoint_events_total"}
        assert not (new & names_base)

    def test_spill_on_mints_new_names(self, tmp_path):
        reg = MetricsRegistry()
        _sched(_PtStub(), str(tmp_path / "s"), registry=reg)
        names = set(reg.snapshot())
        assert {"serve_spill_resumes_total",
                "fold_checkpoint_events_total"} <= names

    def test_spill_requires_checkpoint_cadence(self):
        with pytest.raises(ValueError):
            RetryPolicy(checkpoint_spill="/tmp/x", checkpoint_every=0)
