"""AMX host-GEMM path (ops/cpu_gemm.py + native/amx_gemm.cc).

The kernel computes in bf16 on the AMX tiles with f32 accumulation, so
comparisons against the XLA f32 dot use bf16-level tolerances. Every test
skips cleanly on hosts without AMX (the library probe returns False).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from alphafold2_tpu.ops import cpu_gemm


def _amx_or_skip():
    cpu_gemm.use_amx_dense(True)
    if not cpu_gemm.amx_dense_enabled():
        pytest.skip("host CPU has no AMX tiles / library unavailable")


@pytest.fixture(autouse=True)
def _reset_flag():
    # restore the prior tri-state (None = consult the AF2_CPU_AMX env), not
    # False — pinning False would kill the env opt-in for the whole
    # pytest process
    prior = cpu_gemm._enabled
    yield
    cpu_gemm._enabled = prior


def _rel_err(got, want):
    got, want = np.asarray(got), np.asarray(want)
    return np.abs(got - want).max() / max(1e-6, np.abs(want).max())


@pytest.mark.quick
def test_forward_matches_xla_dot():
    _amx_or_skip()
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    a = jax.random.normal(k1, (1000, 256), jnp.float32)  # M not 32-aligned
    b = jax.random.normal(k2, (256, 528), jnp.float32)   # odd 16-col tail
    got = cpu_gemm.amx_matmul(a, b)
    assert _rel_err(got, a @ b) < 2e-2  # bf16 operand rounding


def test_batched_forward():
    _amx_or_skip()
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    a = jax.random.normal(k1, (3, 65, 64), jnp.float32)
    b = jax.random.normal(k2, (3, 64, 96), jnp.float32)
    got = cpu_gemm.amx_matmul(a, b)
    assert _rel_err(got, jnp.einsum("gmk,gkn->gmn", a, b)) < 2e-2


def test_gradients_match_xla():
    _amx_or_skip()
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    a = jax.random.normal(k1, (128, 64), jnp.float32)
    b = jax.random.normal(k2, (64, 32), jnp.float32)
    da, db = jax.grad(lambda a, b: (cpu_gemm.amx_matmul(a, b) ** 2).sum(),
                      (0, 1))(a, b)
    ra, rb = jax.grad(lambda a, b: ((a @ b) ** 2).sum(), (0, 1))(a, b)
    assert _rel_err(da, ra) < 5e-2
    assert _rel_err(db, rb) < 5e-2


def test_dense_dot_general_routes_and_matches():
    """Through flax Dense(dot_general=…): same params, same output (to
    bf16 tolerance), and under jit."""
    _amx_or_skip()
    from flax import linen as nn

    from alphafold2_tpu.model.primitives import Dense

    x = jax.random.normal(jax.random.PRNGKey(3), (200, 128), jnp.float32)
    amx_layer = Dense(96)
    ref_layer = nn.Dense(96)
    params = amx_layer.init(jax.random.PRNGKey(4), x)
    apply = jax.jit(amx_layer.apply)
    # not vacuous: the custom call must actually be in the compiled HLO
    # (a silent fall-through to lax.dot_general would match bit-for-bit)
    hlo = apply.lower(params, x).compile().as_text()
    assert "af2_amx_gemm" in hlo
    out_amx = apply(params, x)
    out_ref = ref_layer.apply(params, x)  # identical params tree
    assert _rel_err(out_amx, out_ref) < 2e-2
    assert float(jnp.abs(out_amx - out_ref).max()) > 0.0  # really routed


def test_ineligible_shapes_fall_back():
    """K or N misaligned, tiny M, non-f32 — all must fall through to
    lax.dot_general bit-for-bit."""
    _amx_or_skip()
    dn = (((1,), (0,)), ((), ()))
    for a, b in [
        (jnp.ones((64, 48)), jnp.ones((48, 64))),          # K % 32 != 0
        (jnp.ones((64, 64)), jnp.ones((64, 37))),          # N % 16 != 0
        (jnp.ones((8, 64)), jnp.ones((64, 64))),           # M < 32
        (jnp.ones((64, 64), jnp.bfloat16),
         jnp.ones((64, 64), jnp.bfloat16)),                # non-f32
    ]:
        got = cpu_gemm.amx_dense_dot_general(a, b, dn)
        want = jax.lax.dot_general(a, b, dn)
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_flag_off_is_pure_xla():
    cpu_gemm.use_amx_dense(False)
    dn = (((1,), (0,)), ((), ()))
    a = jnp.ones((64, 64)) * 0.5
    b = jnp.ones((64, 64)) * 0.25
    got = cpu_gemm.amx_dense_dot_general(a, b, dn)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(jax.lax.dot_general(a, b, dn)))


def test_train_step_under_amx_matches_xla():
    """A small Alphafold2 train step with the flag on vs off: losses agree
    to mixed-precision tolerance (the AMX path is engaged via env at trace
    time, so jit caches must not be shared across the flip)."""
    _amx_or_skip()
    from alphafold2_tpu import Alphafold2
    from alphafold2_tpu.data.synthetic import synthetic_batch
    from alphafold2_tpu.train import TrainState, adam, make_train_step

    model = Alphafold2(dim=64, depth=1, heads=4, dim_head=16)
    batch = synthetic_batch(jax.random.PRNGKey(0), batch=1, seq_len=32,
                            msa_depth=3, with_coords=True)
    params = model.init(jax.random.PRNGKey(1), batch["seq"],
                        msa=batch["msa"], mask=batch["mask"],
                        msa_mask=batch["msa_mask"])

    def loss_of(flag):
        cpu_gemm.use_amx_dense(flag)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=adam(1e-3), rng=jax.random.PRNGKey(2))
        step = jax.jit(make_train_step(model))
        _, metrics = step(state, batch)
        return float(metrics["loss"])

    try:
        l_amx, l_xla = loss_of(True), loss_of(False)
    finally:
        cpu_gemm.use_amx_dense(False)
    assert np.isfinite(l_amx) and np.isfinite(l_xla)
    assert abs(l_amx - l_xla) / max(1.0, abs(l_xla)) < 5e-2


class TestBatchedAndAttention:
    """Batched AMX matmuls + the attention einsum routing."""

    def test_bmm_and_tb_match_einsum(self):
        _amx_or_skip()
        k1, k2 = jax.random.split(jax.random.PRNGKey(5))
        a = jax.random.normal(k1, (3, 50, 64), jnp.float32)
        b = jax.random.normal(k2, (3, 64, 48), jnp.float32)
        bt = jax.random.normal(k2, (3, 48, 64), jnp.float32)
        assert _rel_err(cpu_gemm.amx_bmm(a, b),
                        jnp.einsum("gmk,gkn->gmn", a, b)) < 2e-2
        assert _rel_err(cpu_gemm.amx_bmm_tb(a, bt),
                        jnp.einsum("gmk,gnk->gmn", a, bt)) < 2e-2

    def test_bmm_gradients(self):
        _amx_or_skip()
        k1, k2 = jax.random.split(jax.random.PRNGKey(6))
        a = jax.random.normal(k1, (2, 40, 32), jnp.float32)
        bt = jax.random.normal(k2, (2, 48, 32), jnp.float32)
        da1, db1 = jax.grad(
            lambda a, b: (cpu_gemm.amx_bmm_tb(a, b) ** 2).sum(),
            (0, 1))(a, bt)
        da2, db2 = jax.grad(
            lambda a, b: (jnp.einsum("gmk,gnk->gmn", a, b) ** 2).sum(),
            (0, 1))(a, bt)
        assert _rel_err(da1, da2) < 5e-2
        assert _rel_err(db1, db2) < 5e-2

    def test_attention_helpers_route_and_fall_back(self):
        _amx_or_skip()
        k1, k2 = jax.random.split(jax.random.PRNGKey(7))
        # eligible: d=64 (%32), j=64 (%16 for dots, %32 for out)
        q = jax.random.normal(k1, (2, 4, 30, 64), jnp.float32)
        k = jax.random.normal(k2, (2, 4, 64, 64), jnp.float32)
        v = jax.random.normal(k1, (2, 4, 64, 64), jnp.float32)
        dots = cpu_gemm.amx_attention_dots(q, k)
        want = jnp.einsum("bhid,bhjd->bhij", q, k)
        assert 0.0 < _rel_err(dots, want) < 2e-2   # routed (bf16 rounding)
        attn = jax.nn.softmax(want, -1)
        out = cpu_gemm.amx_attention_out(attn, v)
        wout = jnp.einsum("bhij,bhjd->bhid", attn, v)
        assert 0.0 < _rel_err(out, wout) < 2e-2
        # ineligible (msa column attention shape: j=5) -> exact einsum
        q5 = jax.random.normal(k1, (2, 4, 5, 64), jnp.float32)
        k5 = jax.random.normal(k2, (2, 4, 5, 64), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(cpu_gemm.amx_attention_dots(q5, k5)),
            np.asarray(jnp.einsum("bhid,bhjd->bhij", q5, k5)))

    def test_attention_module_under_amx_matches_xla(self):
        """primitives.Attention end to end, flag on vs off."""
        _amx_or_skip()
        from alphafold2_tpu.model.primitives import Attention

        x = jax.random.normal(jax.random.PRNGKey(8), (2, 64, 32),
                              jnp.float32)
        attn = Attention(dim=32, heads=2, dim_head=32)
        from conftest import perturb_params
        params = perturb_params(attn.init(jax.random.PRNGKey(9), x),
                                jax.random.PRNGKey(10))
        out_amx = attn.apply(params, x)
        cpu_gemm.use_amx_dense(False)
        out_xla = attn.apply(params, x)
        assert 0.0 < _rel_err(out_amx, out_xla) < 3e-2

    def test_natural_layout_attention_ops(self):
        """amx_attn_qk/amx_attn_av consume token-major [B,N,H,D] operands
        (no transposes around the FFI boundary) and are each other's
        backward duals."""
        _amx_or_skip()
        k1, k2 = jax.random.split(jax.random.PRNGKey(11))
        q = jax.random.normal(k1, (2, 64, 4, 32), jnp.float32)
        k = jax.random.normal(k2, (2, 96, 4, 32), jnp.float32)
        v = jax.random.normal(k1, (2, 96, 4, 32), jnp.float32)
        dots = cpu_gemm.amx_attn_qk(q, k)
        want = jnp.einsum("bnhd,bmhd->bhnm", q, k)
        assert 0.0 < _rel_err(dots, want) < 2e-2
        p = jax.nn.softmax(want, -1)
        out = cpu_gemm.amx_attn_av(p, v)
        wout = jnp.einsum("bhnm,bmhd->bnhd", p, v)
        assert 0.0 < _rel_err(out, wout) < 2e-2
        # gradients (dual-kernel backward)
        dq1, dk1 = jax.grad(
            lambda q, k: (cpu_gemm.amx_attn_qk(q, k) ** 2).sum(),
            (0, 1))(q, k)
        dq2, dk2 = jax.grad(
            lambda q, k: (jnp.einsum("bnhd,bmhd->bhnm", q, k) ** 2).sum(),
            (0, 1))(q, k)
        assert _rel_err(dq1, dq2) < 5e-2 and _rel_err(dk1, dk2) < 5e-2
        dp1, dv1 = jax.grad(
            lambda p, v: (cpu_gemm.amx_attn_av(p, v) ** 2).sum(),
            (0, 1))(p, v)
        dp2, dv2 = jax.grad(
            lambda p, v: (jnp.einsum("bhnm,bmhd->bnhd", p, v) ** 2).sum(),
            (0, 1))(p, v)
        assert _rel_err(dp1, dp2) < 5e-2 and _rel_err(dv1, dv2) < 5e-2

    def test_natural_eligibility_gate(self):
        _amx_or_skip()
        ok = jnp.zeros((1, 64, 2, 32), jnp.float32)
        assert cpu_gemm.amx_attention_natural_ok(ok, ok)
        # misaligned token count -> whole natural path declines
        bad_n = jnp.zeros((1, 48, 2, 32), jnp.float32)
        assert not cpu_gemm.amx_attention_natural_ok(bad_n, ok)
        # misaligned head dim
        bad_d = jnp.zeros((1, 64, 2, 48), jnp.float32)
        assert not cpu_gemm.amx_attention_natural_ok(bad_d, bad_d)
